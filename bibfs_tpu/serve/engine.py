"""Adaptive micro-batching query engine — the serving-shaped hot path.

The measured shape of the problem (PERF_NOTES.md §2-3): a single device
solve pays a ~67 ms dispatch round trip and ~2 ms per level through the
tunneled backend, while the batched solvers amortize the same fixed
costs across every queued query — 26.8 ms/query at batch 256 vs 31.1 ms
at batch 32, flat by ~256. The reference's serving story is one
PROCESS per query (benchmark_test.sh:44-59); nothing in this repo until
now turned the measured batch asymptote into an end-to-end serving
path. :class:`QueryEngine` is that path:

- **micro-batcher** — ``submit()`` accumulates ``(src, dst)`` queries;
  ``flush()`` routes the queue through ONE batched device program
  (``dense._batch_dispatch``, mode resolved by the measured preference
  order) once it crosses the calibrated batch-vs-latency crossover
  (``batch_minor.small_batch_threshold``, the round-5 measurement
  banked in ``calibration.json``), and falls back to per-query
  native/serial host dispatch below it — small queues are a
  host-latency problem, not a device problem (PERF_NOTES §3).
  The routing has a platform dimension, also by measurement: batching
  exists to amortize the per-dispatch tax, which calibration puts at
  ~67 ms through the tunneled TPU but ~9 us on the CPU backend — so
  when the jax substrate IS the host CPU there is nothing to amortize,
  the device program can never beat the native runtime it shares cores
  with, and above-crossover flushes route through the scratch-reusing
  host loop instead (override with ``device_batches=True``; tests do,
  to exercise the device path on the CPU backend).
- **shape buckets + executable accounting** — the graph is padded up to
  the geometric buckets of :mod:`bibfs_tpu.serve.buckets` and every
  flush is padded to a batch rung, so arbitrary graph sizes and queue
  depths reuse a handful of compiled programs; hit/miss counters are
  exposed via :meth:`QueryEngine.stats`.
- **distance/result cache** — solved parent forests land in the
  :class:`bibfs_tpu.serve.cache.DistanceCache`; repeated sources (and
  their undirected reverse twins) answer follow-up queries on the host
  with ZERO device dispatches.

Every result is a plain :class:`~bibfs_tpu.solvers.api.BFSResult`;
batch-solved results carry the whole-batch wall clock in ``time_s``
(the ``solve_batch_graph`` convention), cache hits carry ~0.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from contextlib import contextmanager

import numpy as np

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.dtrace import stage_histogram
from bibfs_tpu.obs.metrics import REGISTRY, MetricBank, next_instance_label
from bibfs_tpu.obs.trace import span
from bibfs_tpu.serve.buckets import (
    DEFAULT_EXEC_CACHE,
    ExecutableCache,
    bucket_batch,
    ell_bucket_key,
)
from bibfs_tpu.serve.cache import DistanceCache
from bibfs_tpu.serve.faults import FaultPlan
from bibfs_tpu.serve.resilience import (
    BREAKER_STATE_CODES,
    ERROR_KINDS,
    CircuitBreaker,
    HealthMonitor,
    QueryError,
    RetryPolicy,
    to_query_error,
)
from bibfs_tpu.query.types import PointToPoint, Query, coerce_query
from bibfs_tpu.solvers.api import BFSResult
from bibfs_tpu.store.snapshot import GraphSnapshot


def _engine_counter_bank(label: str) -> MetricBank:
    """The engine's query-accounting cells, all in the process registry
    under the stable documented names (README "Observability"). One
    bank per engine instance (label = ``engine="sync-3"`` etc.), so
    per-engine ``stats()`` stays exact while ``/metrics`` sees every
    engine in one scrape."""
    queries = REGISTRY.counter(
        "bibfs_queries_total", "Queries submitted to a serving engine",
        ("engine",),
    )
    routed = REGISTRY.counter(
        "bibfs_queries_routed_total",
        "Queries by resolution route "
        "(trivial/oracle/cache/mesh/blocked/device/host/overlay)",
        ("engine", "route"),
    )
    batches = REGISTRY.counter(
        "bibfs_device_batches_total", "Batched device flush dispatches",
        ("engine",),
    )
    skipped = REGISTRY.counter(
        "bibfs_cache_inserts_skipped_total",
        "Forest-bank inserts skipped by flush-time hygiene",
        ("engine",),
    )
    return MetricBank({
        "queries": queries.labels(engine=label),
        "trivial": routed.labels(engine=label, route="trivial"),
        "oracle_served": routed.labels(engine=label, route="oracle"),
        "cache_served": routed.labels(engine=label, route="cache"),
        "device_batches": batches.labels(engine=label),
        "device_queries": routed.labels(engine=label, route="device"),
        "host_queries": routed.labels(engine=label, route="host"),
        "overlay_queries": routed.labels(engine=label, route="overlay"),
        "mesh_queries": routed.labels(engine=label, route="mesh"),
        "blocked_queries": routed.labels(engine=label, route="blocked"),
        "inserts_skipped": skipped.labels(engine=label),
    })


class _ResilienceCells:
    """The per-engine resilience registry cells (stable names in README
    "Robustness"): every cell minted at engine construction so a
    /metrics scrape shows the families at zero from the first breath —
    the chaos CI gate asserts they render even before anything fails."""

    def __init__(self, label: str, *, mesh: bool = False,
                 blocked: bool = False):
        errors = REGISTRY.counter(
            "bibfs_errors_total",
            "Per-ticket query failures by taxonomy kind",
            ("engine", "kind"),
        )
        fallbacks = REGISTRY.counter(
            "bibfs_route_fallbacks_total",
            "Batches re-routed down the fallback ladder",
            ("engine", "from", "to"),
        )
        retries = REGISTRY.counter(
            "bibfs_retries_total", "Route retries before fallback",
            ("engine", "route"),
        )
        bisections = REGISTRY.counter(
            "bibfs_batch_bisections_total",
            "Poison-batch bisection splits during failure isolation",
            ("engine",),
        )
        self.breaker_gauge = REGISTRY.gauge(
            "bibfs_breaker_state",
            "Device-route circuit breaker (0=closed 1=half_open 2=open)",
            ("engine",),
        ).labels(engine=label)
        self._breaker_trans = REGISTRY.counter(
            "bibfs_breaker_transitions_total",
            "Circuit breaker state transitions",
            ("engine", "to"),
        )
        self.health_gauge = REGISTRY.gauge(
            "bibfs_health_state",
            "Serving health (0=live 1=ready 2=degraded 3=draining)",
            ("engine",),
        ).labels(engine=label)
        self.errors = {
            k: errors.labels(engine=label, kind=k) for k in ERROR_KINDS
        }
        # every ladder transition minted eagerly (the chaos gate asserts
        # the families render at zero); a mesh-configured engine adds
        # its rung's two exits (next-eligible device, or straight to
        # host on a CPU substrate / finish-worker recovery)
        self._fallback_family = fallbacks
        pairs = [("device", "host"), ("host", "serial")]
        if blocked:
            # the blocked rung's two exits: the next dispatch rung, or
            # straight to host when device is ineligible
            pairs = [("blocked", "device"), ("blocked", "host")] + pairs
        if mesh:
            pairs = [("mesh", "device"), ("mesh", "host")] + pairs
            if blocked:
                pairs = [("mesh", "blocked")] + pairs
        self.fallbacks = {
            (a, b): fallbacks.labels(**{"engine": label, "from": a, "to": b})
            for a, b in pairs
        }
        self._retry_family = retries
        self._retry_cells = {
            "device": retries.labels(engine=label, route="device"),
        }
        if mesh:
            self._retry_cells["mesh"] = retries.labels(
                engine=label, route="mesh"
            )
        if blocked:
            self._retry_cells["blocked"] = retries.labels(
                engine=label, route="blocked"
            )
        self.bisections = bisections.labels(engine=label)
        self._label = label

    def retry_cell(self, route: str):
        """The ``bibfs_retries_total{route=...}`` cell for one route
        (labelled on demand for routes outside the eager set)."""
        cell = self._retry_cells.get(route)
        if cell is None:
            cell = self._retry_family.labels(
                engine=self._label, route=route
            )
            self._retry_cells[route] = cell
        return cell

    def fallback_cell(self, frm: str, to: str):
        cell = self.fallbacks.get((frm, to))
        if cell is None:
            cell = self._fallback_family.labels(
                **{"engine": self._label, "from": frm, "to": to}
            )
            self.fallbacks[(frm, to)] = cell
        return cell

    def on_breaker_transition(self, state: str) -> None:
        self.breaker_gauge.set(BREAKER_STATE_CODES[state])
        self._breaker_trans.labels(to=state, engine=self._label).inc()

    def snapshot(self) -> dict:
        return {
            "errors": {k: c.value for k, c in self.errors.items()},
            "fallbacks": {
                f"{a}->{b}": c.value
                for (a, b), c in self.fallbacks.items()
            },
            "retries": sum(c.value for c in self._retry_cells.values()),
            "bisections": self.bisections.value,
        }


def _solve_serial_cutoff_checked(n, row_ptr, col_ind, s, d, cutoff):
    """Cutoff-armed serial solve with the false-unreachable guard.

    An oracle cutoff is armed at SUBMIT time, against the live graph of
    that instant; by the time the flush solves, a delete + hot-swap may
    have raced in and the flush's bound graph can hold a larger true
    distance than the stale UB — a seeded search would then stop early
    and report a connected pair unreachable. The asymmetry that saves
    us: a too-small cutoff can ONLY manifest as found=False, never as a
    wrong distance (a found result's hops is a real path length <=
    cutoff, and any real path is >= the true distance — so found
    answers are exact whatever the cutoff was). So: trust found
    results, and retry a not-found WITHOUT the seed. The retry fires
    only when the pair is truly disconnected (one full component sweep,
    the price of exactness) or the cutoff was stale (rare: a racing
    delete between submit and flush) — no generation bookkeeping, no
    race windows."""
    from bibfs_tpu.solvers.serial import solve_serial_csr

    res = solve_serial_csr(n, row_ptr, col_ind, s, d, cutoff=cutoff)
    if cutoff is not None and not res.found:
        res = solve_serial_csr(n, row_ptr, col_ind, s, d)
    return res


class _Pending:
    """A submitted query's handle; ``result`` lands at flush time.
    Exactly one of ``result`` / ``error`` lands: failure isolation
    gives a poisoned query a structured
    :class:`~bibfs_tpu.serve.resilience.QueryError` instead of sinking
    its whole batch. ``graph`` is the store graph name the query is
    against (None on a store-less engine's single graph); ``cutoff`` is
    the distance oracle's proven upper bound when it had one — the
    serial host rung seeds its meet bound with it (exact pruning).
    ``query`` carries the typed taxonomy query on non-point-to-point
    tickets (None = the classic ``(src, dst)`` shape; ``src``/``dst``
    then hold a representative pair for error reporting). ``ctx`` is
    the distributed-trace context (:mod:`bibfs_tpu.obs.dtrace`) the
    ingress hop sampled — None on the overwhelmingly common unsampled
    query, where it costs one slot and nothing else."""

    __slots__ = ("src", "dst", "graph", "result", "error", "cutoff",
                 "query", "ctx")

    def __init__(self, src: int, dst: int, graph: str | None = None,
                 ctx=None):
        self.src = src
        self.dst = dst
        self.graph = graph
        self.result: BFSResult | None = None
        self.error: BaseException | None = None
        self.cutoff: int | None = None
        self.query: Query | None = None
        self.ctx = ctx


@guarded_by("_lock", "_graph", "bucket_key", "_host_solver",
            "host_native_graph", "_serial_solver", "host_backend_resolved",
            "_mesh_graph", "mesh_bucket_key", "_dp_graph", "dp_bucket_key",
            "_blocked_graph", "blocked_bucket_key", "_blocked_meta",
            "_weights", "_wtables")
class _GraphRuntime:
    """Everything an engine knows about solving ONE immutable graph
    snapshot: the lazily built+uploaded device graph and its compiled-
    program bucket key, the host solvers (native / serial), and the
    distance-cache namespace. Engines keep one runtime per served graph
    name and build a fresh one when the store hot-swaps the snapshot; a
    flush BINDS a runtime for its whole lifetime (``engine._bound``), so
    in-flight batches finish on the snapshot they started on while new
    submissions already resolve the new version — the swap barrier.

    ``graph_id`` defaults to the snapshot's content digest: the old
    ``id(self)`` default was reused by CPython after GC, so two engines
    sharing a :class:`DistanceCache` could silently alias namespaces.
    Digests cannot alias (and snapshots built without hashable content
    fall back to a process-wide monotonic ``anon-N`` — still never
    reused)."""

    def __init__(self, snapshot: GraphSnapshot, *, layout: str = "ell",
                 device=None, host_backend: str | None = None,
                 graph_id=None):
        self.snapshot = snapshot
        self.n = snapshot.n
        self.layout = layout
        self.graph_id = snapshot.digest if graph_id is None else graph_id
        self._device = device
        self._host_backend = host_backend
        self._lock = threading.Lock()  # lazy builders: the pipelined
        # engine resolves host solvers from the flusher AND (on the
        # device->host recovery path) the finish worker
        self._graph = None
        self.bucket_key = None
        self._mesh_graph = None
        self.mesh_bucket_key = None
        self._dp_graph = None
        self.dp_bucket_key = None
        self._blocked_graph = None
        self.blocked_bucket_key = None
        self._blocked_meta = None
        self._host_solver = None
        self.host_native_graph = None
        self._serial_solver = None
        self.host_backend_resolved: str | None = None
        # per-seed derived edge weights for the weighted query kind
        # (seed -> float64 aligned with the snapshot CSR), built on
        # first weighted-routed flush like the other lazy tables
        self._weights: dict = {}
        # the weighted DEVICE rung's uploaded (targets, weights) ELL
        # tables, seed-keyed like _weights (bounded the same way)
        self._wtables: dict = {}
        # the blocked SSSP rung's uploaded float32 weight TILE tables
        # (graph/blocked.build_blocked_weights), seed-keyed like
        # _weights (bounded the same way — the seed is client input)
        self._awtabs: dict = {}

    @property
    def graph(self):
        """The bucketed device-resident graph (built and uploaded on
        first use: a host-routed runtime — the default on the CPU
        substrate — never pays the padded table build)."""
        if self._graph is None:
            from bibfs_tpu.solvers.dense import DeviceGraph

            with self._lock:
                if self._graph is None:
                    if self.layout == "ell":
                        ell = self.snapshot.ell()
                        self.bucket_key = ell_bucket_key(ell)
                        self._graph = DeviceGraph.from_ell(
                            ell, device=self._device
                        )
                    else:
                        g = DeviceGraph.from_tiered(
                            self.snapshot.tiered(), device=self._device
                        )
                        self.bucket_key = (
                            "tiered", g.n_pad, g.width, g.tier_meta,
                        )
                        self._graph = g
        return self._graph

    def mesh_graph(self, route):
        """The vertex-sharded device graph for the mesh route (built
        and uploaded on first mesh-routed flush — a runtime that never
        routes mesh never pays the shard build). Rows are re-padded to
        the mesh size when the bucket rung does not divide, and the
        compiled-program identity lands in ``mesh_bucket_key`` WITH the
        shard geometry (:func:`placement_bucket_key`) so it can never
        collide with the single-device key of the same padded shape.
        Rebuilt per runtime, so a store hot-swap re-shards the new
        snapshot the same way it re-uploads the dense table."""
        g = self._mesh_graph
        if g is None:
            from bibfs_tpu.serve.buckets import repad_rows
            from bibfs_tpu.solvers.sharded import ShardedGraph

            with self._lock:
                g = self._mesh_graph
                if g is None:
                    ell = repad_rows(self.snapshot.ell(), route.ndev)
                    g = ShardedGraph(ell, route.mesh)
                    self.mesh_bucket_key = ell_bucket_key(ell)
                    self._mesh_graph = g
        return g

    def dp_graph(self):
        """The dp-batch replicated table for the mesh route's
        query-sharded sub-path, on the FINE row ladder
        (:func:`bibfs_tpu.serve.buckets.dp_aligned_ell` — the measured
        dp win over the device route is shard-plane cache residency,
        which the geometric row buckets would spill). Built lazily on
        the first dp-routed flush; rebuilt per runtime across
        hot-swaps like every other device table."""
        g = self._dp_graph
        if g is None:
            from bibfs_tpu.serve.buckets import dp_aligned_ell
            from bibfs_tpu.solvers.dense import DeviceGraph

            with self._lock:
                g = self._dp_graph
                if g is None:
                    ell = dp_aligned_ell(
                        self.snapshot.n, pairs=self.snapshot.pairs
                    )
                    g = DeviceGraph.from_ell(ell, device=self._device)
                    self.dp_bucket_key = ell_bucket_key(ell)
                    self._dp_graph = g
        return g

    def blocked_meta(self) -> tuple:
        """``(nblocks, bwidth, nnz_blocks)`` of the snapshot's blocked
        layout WITHOUT materializing the table
        (:func:`bibfs_tpu.graph.blocked.blocked_meta` — shares the
        build's grid math), so the blocked route's ``eligible()`` can
        gate on tile compactness before anything is built. Cached per
        runtime like the other lazy builders."""
        m = self._blocked_meta
        if m is None:
            from bibfs_tpu.graph.blocked import blocked_meta

            with self._lock:
                m = self._blocked_meta
                if m is None:
                    m = blocked_meta(self.n, self.snapshot.pairs)
                    self._blocked_meta = m
        return m

    def blocked_graph(self):
        """The MXU-tile blocked device table for ``route="blocked"``
        (built from the snapshot's memoized
        :meth:`~bibfs_tpu.store.snapshot.GraphSnapshot.blocked` layout
        and uploaded on the first blocked-routed flush — a runtime that
        never routes blocked never pays the tile build). Rebuilt per
        runtime, so a store hot-swap re-tiles the new snapshot through
        the same machinery as every other device table."""
        g = self._blocked_graph
        if g is None:
            from bibfs_tpu.graph.blocked import blocked_bucket_key
            from bibfs_tpu.solvers.dense import BlockedDeviceGraph

            with self._lock:
                g = self._blocked_graph
                if g is None:
                    bg = self.snapshot.blocked()
                    g = BlockedDeviceGraph.from_host(
                        bg, device=self._device
                    )
                    self.blocked_bucket_key = blocked_bucket_key(bg)
                    self._blocked_graph = g
        return g

    def get_host_solver(self):
        """The sub-crossover per-query path: the native C++ runtime when
        it loads (the measured latency winner, PERF_NOTES §3), else the
        NumPy serial oracle over the snapshot's memoized CSR. Every
        solver takes an optional ``cutoff`` (the distance oracle's
        proven upper bound); the serial rung seeds its meet bound with
        it, the native runtime ignores it (the C search loop has no
        seed seam and is fast enough not to need one)."""
        if self._host_solver is not None:
            return self._host_solver
        with self._lock:
            if self._host_solver is not None:
                return self._host_solver
            backend = self._host_backend
            if backend in (None, "native"):
                try:
                    from bibfs_tpu.solvers.native import (
                        NativeGraph,
                        solve_native_graph,
                    )

                    mapped = self.snapshot.native_csr()
                    if mapped is not None:
                        # zero-copy: the sidecar's csr32 table is exactly
                        # the (int64 row_ptr, int32 col_ind) layout the C
                        # runtime consumes, so M replicas mapping one
                        # store dir share a single page-cache copy of
                        # the adjacency instead of M private builds
                        ng = NativeGraph(
                            n=self.n,
                            row_ptr=np.ascontiguousarray(mapped[0]),
                            col_ind=mapped[1],
                        )
                    else:
                        ng = NativeGraph.build(
                            self.n, self.snapshot.undirected_edges()
                        )
                    # kept for the threaded C batch route (_solve_host):
                    # bibfs_solve_batch shares only the read-only CSR and
                    # creates per-C-thread scratches, so the handle is
                    # safe to use from any thread
                    self.host_native_graph = ng
                    self.host_backend_resolved = "native"
                    self._host_solver = (
                        lambda s, d, cutoff=None: solve_native_graph(
                            ng, s, d
                        )
                    )
                    return self._host_solver
                except (ImportError, OSError):
                    if backend == "native":
                        raise
            from bibfs_tpu.solvers.serial import solve_serial_csr

            row_ptr, col_ind = self.snapshot.csr()
            self._host_solver = (
                lambda s, d, cutoff=None: _solve_serial_cutoff_checked(
                    self.n, row_ptr, col_ind, s, d, cutoff
                )
            )
            self.host_backend_resolved = "serial"
            return self._host_solver

    #: memoized weight derivations kept per runtime — each costs one
    #: float64 per CSR entry and the seed is CLIENT input, so the memo
    #: must be bounded (FIFO eviction) or a seed-scanning client pins
    #: O(seeds * E) memory for the snapshot's lifetime
    WEIGHT_SEEDS_MAX = 8

    def weights_for(self, seed: int, row_ptr, col_ind) -> "np.ndarray":
        """The snapshot's derived edge weights for one ``weight_seed``
        (:func:`bibfs_tpu.query.weighted.synthetic_weights`), memoized
        per runtime — every weighted query of one seed against one
        snapshot shares one derivation (bounded: ``WEIGHT_SEEDS_MAX``
        seeds, FIFO). Only valid for the snapshot's own CSR (the
        weighted route derives fresh over an overlay-merged CSR)."""
        w = self._weights.get(int(seed))
        if w is None:
            from bibfs_tpu.query.weighted import synthetic_weights

            with self._lock:
                w = self._weights.get(int(seed))
                if w is None:
                    w = synthetic_weights(row_ptr, col_ind, int(seed))
                    while len(self._weights) >= self.WEIGHT_SEEDS_MAX:
                        # dicts iterate in insert order: FIFO eviction
                        self._weights.pop(next(iter(self._weights)))
                    self._weights[int(seed)] = w
        return w

    def weighted_device_tables(self, seed: int):
        """The weighted device rung's uploaded relaxation tables for
        one ``weight_seed`` (:func:`bibfs_tpu.solvers.query_device.
        delta_tables` over the snapshot's serving ELL), memoized per
        runtime like :meth:`weights_for` — one upload per (snapshot,
        seed), freed with the runtime on hot-swap. Bounded by the same
        ``WEIGHT_SEEDS_MAX`` argument: the seed is client input."""
        t = self._wtables.get(int(seed))
        if t is None:
            from bibfs_tpu.solvers.query_device import delta_tables

            with self._lock:
                t = self._wtables.get(int(seed))
                if t is None:
                    t = delta_tables(self.snapshot.ell(), int(seed))
                    while len(self._wtables) >= self.WEIGHT_SEEDS_MAX:
                        # dicts iterate in insert order: FIFO eviction
                        self._wtables.pop(next(iter(self._wtables)))
                    self._wtables[int(seed)] = t
        return t

    def analytics_weight_table(self, seed: int):
        """The blocked SSSP rung's uploaded float32 weight tile table
        for one ``weight_seed`` (:func:`bibfs_tpu.graph.blocked.
        build_blocked_weights` over the snapshot's memoized blocked
        layout), memoized per runtime like :meth:`weights_for` — one
        build+upload per (snapshot, seed), freed with the runtime on
        hot-swap, bounded at ``WEIGHT_SEEDS_MAX`` seeds FIFO."""
        t = self._awtabs.get(int(seed))
        if t is None:
            import jax

            from bibfs_tpu.graph.blocked import build_blocked_weights

            self.blocked_graph()  # ensure the tiling is materialized
            with self._lock:
                t = self._awtabs.get(int(seed))
                if t is None:
                    wtab = build_blocked_weights(
                        self.snapshot.blocked(), self.snapshot.pairs,
                        seed=int(seed),
                    )
                    t = (
                        jax.device_put(wtab, device=self._device)
                        if self._device else jax.device_put(wtab)
                    )
                    while len(self._awtabs) >= self.WEIGHT_SEEDS_MAX:
                        # dicts iterate in insert order: FIFO eviction
                        self._awtabs.pop(next(iter(self._awtabs)))
                    self._awtabs[int(seed)] = t
        return t

    def solve_serial_one(self, src: int, dst: int,
                         cutoff: int | None = None) -> BFSResult:
        """The bottom of the fallback ladder: the pure-NumPy serial
        oracle over the snapshot's CSR — no native runtime, no device
        stack, nothing left to be broken but the graph itself."""
        if self._serial_solver is None:
            with self._lock:
                if self._serial_solver is None:
                    if (self.host_backend_resolved == "serial"
                            and self._host_solver is not None):
                        # the host route already IS the serial oracle
                        self._serial_solver = self._host_solver
                    else:
                        from bibfs_tpu.solvers.serial import solve_serial_csr

                        row_ptr, col_ind = self.snapshot.csr()
                        self._serial_solver = (
                            lambda s, d, cutoff=None:
                            _solve_serial_cutoff_checked(
                                self.n, row_ptr, col_ind, s, d, cutoff
                            )
                        )
        return self._serial_solver(int(src), int(dst), cutoff=cutoff)


@guarded_by("_rt_lock", "_runtimes", "_rts_released")
class QueryEngine:
    """Serve ``(src, dst)`` shortest-path queries over one graph.

    Parameters
    ----------
    n, edges : the graph (same contract as ``api.solve``); ``pairs``
        optionally passes a precomputed ``canonical_pairs`` result.
        Internally the graph becomes an immutable
        :class:`~bibfs_tpu.store.snapshot.GraphSnapshot`.
    store, graph : serve a :class:`~bibfs_tpu.store.GraphStore` instead
        of one inline graph: ``store=`` attaches the store (mutually
        exclusive with ``n``/``edges``/``pairs``), ``graph=`` names the
        default graph (default: the store's). Queries then take a
        per-query graph name (``submit(s, d, graph="social")``), live
        edge updates answer exactly through the store's delta overlay,
        and a hot-swapped snapshot is picked up at the next flush while
        in-flight flushes finish on the version they started on.
    mode : batch mode for device flushes (default ``"auto"``: the
        measured preference order minor8 > minor > vmapped sync).
    layout : ``"ell"`` (shape-bucketed; the serving default) or
        ``"tiered"`` (power-law graphs; exact shapes, no bucketing —
        tier geometry is per-graph by construction).
    flush_threshold : queue depth at which a flush goes to the device;
        below it queries dispatch per-query through the host runtime.
        Default: the calibrated crossover
        (``batch_minor.small_batch_threshold``).
    max_batch : largest single device flush (rounded up to a batch
        rung); longer queues solve in chunks.
    cache_entries : distance-cache forest capacity (2 forests bank per
        solved query; each costs one int32[n] row).
    host_backend : ``"native"``, ``"serial"`` or None (auto: native
        when its runtime loads, else serial).
    device_batches : route above-crossover flushes through the batched
        device program. None (default) = auto: only when the jax
        backend is a real accelerator (module docstring — on the CPU
        substrate there is no dispatch tax to amortize and the host
        runtime wins every regime).
    exec_cache : an :class:`ExecutableCache` to share compiled-program
        accounting across engines (default: the process-wide one).
    dist_cache : a :class:`DistanceCache` to SHARE across engines
        (default: a private one). Safe to share because entries are
        namespaced by snapshot content digest (see ``graph_id``).
    oracle_k : landmark count for an engine-local distance-oracle tier
        over the inline graph (``bibfs_tpu/oracle``): K landmark BFS
        trees are built once at construction and consulted BEFORE the
        distance cache on every submit — exact answers (endpoint is a
        landmark, tight bounds, provably-disconnected pair) resolve
        with no queueing and no solver (``route="oracle"``), and a
        non-exact consult attaches its upper bound as a search cutoff
        for the serial host rung. Store-backed engines get their
        oracles FROM the store (``GraphStore(oracle_k=...)`` — the
        store owns the index lifecycle across updates and hot-swaps),
        so combining ``oracle_k`` with ``store=`` is an error.
    graph_id : distance-cache namespace override for the default graph.
        Default: the snapshot's content digest — two engines over the
        same graph share entries, engines over different graphs cannot
        alias (the old ``id(self)`` default could, after GC reuse). On a
        store-backed engine the override applies only until the first
        hot-swap of that graph: the replacement runtime reverts to
        digest namespacing (and the override namespace is invalidated),
        because pinning a caller-chosen namespace across versions would
        let stale version-k entries answer version-k+1 queries.
    obs_label : the ``engine=`` label value this engine's counters carry
        in the process metrics registry (default: a process-unique
        ``sync-N`` / ``pipe-N``). ``counters`` (and the pipelined
        subclass's ``pipe_counters``) are dict-style views over those
        registry cells, so ``stats()`` and a ``/metrics`` scrape always
        agree.
    faults : a :class:`bibfs_tpu.serve.faults.FaultPlan` injecting
        failures at the engine seams (chaos testing against the real
        engine). Default: parsed from ``BIBFS_FAULTS`` when set, else
        None — and a None plan costs one attribute check per seam.
    retry : :class:`~bibfs_tpu.serve.resilience.RetryPolicy` for the
        device route (default: 2 attempts, exp backoff + jitter).
    breaker : :class:`~bibfs_tpu.serve.resilience.CircuitBreaker`
        gating the device route (default: opens after 3 consecutive
        failures, half-open probe after 5 s). While open, above-
        crossover flushes fall back to the host ladder instead of
        failing — a dead accelerator degrades throughput, not
        availability.
    mesh : enable ``route="mesh"`` — serve batches from the device
        mesh (``serve/routes/mesh.py``): an int (mesh device count),
        ``"auto"`` (every visible device), or a
        :class:`~bibfs_tpu.serve.routes.MeshConfig`. The mesh rung
        leads the fallback ladder (mesh -> device -> host) with its own
        circuit breaker and retry policy; below-crossover traffic is
        rerouted to the single-device rungs (calibrated constants, the
        platform's ``mesh`` block in ``calibration.json``) and counted
        in ``bibfs_mesh_crossover_reroutes_total``. Default None: no
        mesh rung, the pre-mesh ladder exactly.
    blocked : enable ``route="blocked"`` — MXU-native blocked-adjacency
        expansion (``serve/routes/blocked.py``): ``True`` or a
        :class:`~bibfs_tpu.serve.routes.BlockedConfig`. The blocked
        rung sits ahead of device in the fallback ladder
        (``blocked -> device -> host``) with its own circuit breaker,
        retry policy and chaos sites; eligibility is the calibrated
        batch crossover plus the tile-compactness gate (the platform's
        ``blocked`` block in ``calibration.json``). Default None: no
        blocked rung, the pre-blocked ladder exactly.
    adaptive : telemetry-driven adaptive routing
        (:class:`~bibfs_tpu.serve.policy.AdaptiveRouter`): ``True``
        learns a per-graph-digest ladder ordering from measured
        per-route latencies and sampled level telemetry, counted in
        ``bibfs_routes_adaptive_total{route,reason}`` and persisted as
        a ``policy.json`` sidecar next to a durable store's
        checkpoints — a respawned replica serves its first flush on
        the learned route. Pass a ready ``AdaptiveRouter`` to share one
        across engines. Default None: the static ladder, exactly.
    health_window_s : sliding window for the health monitor's recent-
        error degradation input (default 5.0; the chaos harness
        shrinks it to measure recovery time).
    """

    _OBS_PREFIX = "sync"

    def __init__(
        self,
        n: int | None = None,
        edges: np.ndarray | None = None,
        *,
        store=None,
        graph: str | None = None,
        pairs: np.ndarray | None = None,
        mode: str = "auto",
        layout: str = "ell",
        flush_threshold: int | None = None,
        max_batch: int = 1024,
        cache_entries: int = 64,
        host_backend: str | None = None,
        device_batches: bool | None = None,
        exec_cache: ExecutableCache | None = None,
        dist_cache: DistanceCache | None = None,
        oracle_k: int | None = None,
        graph_id=None,
        device=None,
        obs_label: str | None = None,
        faults: FaultPlan | None = None,
        retry: RetryPolicy | None = None,
        breaker: CircuitBreaker | None = None,
        health_window_s: float = 5.0,
        mesh=None,
        blocked=None,
        adaptive=None,
    ):
        from bibfs_tpu.serve.routes import (
            BlockedConfig,
            MeshConfig,
            mesh_prebuild,
        )
        from bibfs_tpu.solvers.batch_minor import small_batch_threshold

        # cheap argument validation FIRST: below here a store-backed
        # ctor acquires a snapshot pin, which a later raise would leak
        # (the swapped-out snapshot would never retire)
        if layout not in ("ell", "tiered"):
            raise ValueError(
                f"unknown layout {layout!r} (expected 'ell' or 'tiered')"
            )
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        # mesh validation (config coercion AND mesh construction) also
        # runs pre-pin: make_1d_mesh raises on an over-sized device
        # count, and raising after the pin would leak it
        self._mesh_cfg = None
        mesh_pre = None
        if mesh is not None:
            self._mesh_cfg = MeshConfig.coerce(mesh)
            mesh_pre = mesh_prebuild(self._mesh_cfg)
        # blocked/adaptive validation is pre-pin for the same reason
        self._blocked_cfg = (
            None if not blocked else BlockedConfig.coerce(blocked)
        )
        if adaptive is not None and not isinstance(adaptive, bool):
            from bibfs_tpu.serve.policy import AdaptiveRouter

            if not isinstance(adaptive, AdaptiveRouter):
                raise ValueError(
                    "adaptive= takes True/None or an AdaptiveRouter; "
                    f"got {adaptive!r}"
                )
        if oracle_k is not None:
            if store is not None:
                raise ValueError(
                    "oracle_k configures an engine-local oracle over an "
                    "inline graph; a store-backed engine's oracles come "
                    "from the store (GraphStore(oracle_k=...))"
                )
            if int(oracle_k) < 1:
                raise ValueError(f"oracle_k must be >= 1, got {oracle_k}")
        self._store = store
        if store is not None:
            if n is not None or edges is not None or pairs is not None:
                raise ValueError(
                    "pass the graph inline (n, edges/pairs) OR store=, "
                    "not both"
                )
            self._default_name = (
                store.default_graph() if graph is None else str(graph)
            )
            try:
                snap = store.acquire(self._default_name)  # the engine's pin
            except KeyError as e:
                # ctor misuse is a ValueError like every other bad
                # argument here (query-time _resolve_graph does the same)
                raise ValueError(str(e)) from e
        else:
            if graph is not None:
                raise ValueError("graph= names a store graph; pass store=")
            if n is None:
                raise ValueError("n (and edges/pairs) required without store=")
            snap = GraphSnapshot.build(n, edges, pairs=pairs)
            self._default_name = None
        self._device = device
        self.mode = mode
        self.layout = layout
        self.flush_threshold = (
            small_batch_threshold() if flush_threshold is None
            else int(flush_threshold)
        )
        self.max_batch = bucket_batch(max_batch)
        self._host_backend = host_backend
        # per-graph solving state lives in _GraphRuntime objects (device
        # table + bucket key built lazily on the first device-routed
        # flush; host solvers on the first host-routed one). One runtime
        # per served graph name; a store hot-swap replaces the runtime at
        # the next resolution while bound flushes finish on the old one.
        self._rt_lock = threading.RLock()
        self._flush_tls = threading.local()
        self._rts_released = False
        self._runtimes: dict = {
            self._default_name: _GraphRuntime(
                snap, layout=layout, device=device,
                host_backend=host_backend, graph_id=graph_id,
            )
        }
        self.obs_label = (
            next_instance_label(self._OBS_PREFIX) if obs_label is None
            else obs_label
        )
        # engine-local distance oracle over the inline graph (the
        # store-backed variant reads per-graph oracles off the store
        # at submit time instead — _oracle_for)
        self._oracle = None
        if oracle_k is not None:
            from bibfs_tpu.oracle import DistanceOracle, build_index

            row_ptr, col_ind = snap.csr()
            self._oracle = DistanceOracle(
                build_index(
                    snap.n, row_ptr, col_ind, int(oracle_k),
                    digest=snap.digest, version=snap.version,
                ),
                metrics_label=self.obs_label,
            )
        self.dist_cache = (
            DistanceCache(entries=cache_entries,
                          metrics_label=self.obs_label)
            if dist_cache is None else dist_cache
        )
        self.exec_cache = (
            DEFAULT_EXEC_CACHE if exec_cache is None else exec_cache
        )
        self._device_batches = device_batches
        # resilience: fault plan (None = zero-cost), device retry policy,
        # device-route circuit breaker, health state machine. The breaker
        # transition hook keeps the bibfs_breaker_state gauge exact.
        self._faults = FaultPlan.from_env() if faults is None else faults
        self._retry = RetryPolicy() if retry is None else retry
        self._res_cells = _ResilienceCells(
            self.obs_label, mesh=self._mesh_cfg is not None,
            blocked=self._blocked_cfg is not None,
        )
        self._breaker = CircuitBreaker() if breaker is None else breaker
        # listener, not ownership: a breaker SHARED across engines (one
        # accelerator, several engines) keeps every engine's gauge exact.
        # WEAKLY bound like the registry health collector below: a
        # shared breaker outlives engines that churn per solve_many
        # call, and a strong subscription would pin every dead engine's
        # cells and fire its gauge forever under the breaker lock
        # (returning False unsubscribes)
        cells_ref = weakref.ref(self._res_cells)

        def _on_breaker_transition(state):
            cells = cells_ref()
            if cells is None:
                return False
            cells.on_breaker_transition(state)
            return True

        self._breaker.add_listener(_on_breaker_transition)
        self._res_cells.breaker_gauge.set(
            BREAKER_STATE_CODES[self._breaker.state]
        )
        # the pipelined subclass rebuilds this with its queue-depth
        # input once max_queue exists (it sets up after super().__init__)
        self.health = HealthMonitor(
            breaker=self._breaker,
            window_s=health_window_s,
            gauge=self._res_cells.health_gauge,
        )
        self._health_window_s = health_window_s
        # drain gate (begin_drain/end_drain): while set, NEW submits are
        # refused with a kind='capacity' QueryError but everything
        # already queued still resolves — the replica-at-a-time seam a
        # fleet rolling swap drains through (bibfs_tpu/fleet)
        self._draining = False
        self.health.set_ready()
        # render-time health refresh: breaker windows elapse and error
        # windows age out with no event, so a /metrics-only scraper
        # needs the gauges recomputed at scrape time (state() sets the
        # health gauge; the breaker gauge needs the same refresh — an
        # open breaker's window elapsing to half_open fires no
        # transition listener, it is a read-time reinterpretation).
        # Late-bound through self.health — the pipelined subclass
        # swaps the monitor in after this ctor returns. WEAKLY bound:
        # the registry hook must not pin a dead engine's graph and
        # caches for process lifetime (engines churn per solve_many
        # call); only the tiny closure accumulates, like label cells.
        self_ref = weakref.ref(self)

        def _collect_health():
            eng = self_ref()
            if eng is None:
                return False  # engine collected: unregister this hook
            eng.health.state()
            eng._res_cells.breaker_gauge.set(
                BREAKER_STATE_CODES[eng._breaker.state]
            )
            return True

        REGISTRY.add_collector(_collect_health)
        self._pending: list[_Pending] = []
        # registry-backed view; keys unchanged from the pre-obs dict:
        # queries, trivial (src == dst, answered inline), cache_served,
        # device_batches, device_queries / host_queries (unique queries
        # solved per route), inserts_skipped (forest-bank inserts skipped
        # by flush-time hygiene)
        self.counters = _engine_counter_bank(self.obs_label)
        # the pluggable route set + fallback ladder (serve/routes):
        # oracle/overlay answer from their own seams, the batch ladder
        # runs mesh -> device -> host with serial reached per-query
        # through the host isolator
        from bibfs_tpu.serve.routes import (
            KindResultCache,
            QueryKindCells,
            build_routes,
        )

        # taxonomy query accounting + result cache (serve/routes/
        # taxonomy.py): minted BEFORE the routes so every family the
        # kind routes touch renders at zero from construction
        self._query_cells = QueryKindCells(self.obs_label)
        self._kind_cache = KindResultCache()
        self.routes, self._ladder = build_routes(
            self, self._mesh_cfg, mesh_pre, self._blocked_cfg
        )
        # telemetry-driven adaptive routing (serve/policy.py): learned
        # per-digest ladder ordering, persisted as a sidecar next to
        # the store's checkpoints when the store is durable so a
        # respawned replica serves its first flush on the learned route
        self._policy = None
        if adaptive:
            from bibfs_tpu.serve.policy import (
                POLICY_SIDECAR,
                AdaptiveRouter,
            )

            if isinstance(adaptive, bool):
                sidecar = None
                if store is not None and getattr(
                    store, "wal_dir", None
                ) is not None:
                    sidecar = os.path.join(store.wal_dir, POLICY_SIDECAR)
                self._policy = AdaptiveRouter(
                    label=self.obs_label, routes=self._ladder,
                    path=sidecar,
                )
            else:
                self._policy = adaptive
        # direct cell handles for the per-query submit path (skips the
        # bank's read-modify-write indirection in the hot loop)
        self._c_queries = self.counters.cell("queries")
        self._c_trivial = self.counters.cell("trivial")
        self._c_oracle = self.counters.cell("oracle_served")
        self._c_cache_served = self.counters.cell("cache_served")
        self._c_host_queries = self.counters.cell("host_queries")
        self._c_overlay = self.counters.cell("overlay_queries")
        # per-query cost attribution (obs/dtrace.py): the stage
        # histogram cells, pre-labeled here so serving never allocates
        # a label cell per query (render-at-zero from construction);
        # the per-route/per-stage accumulator stats() reports; and the
        # launch-context hand-off the dispatch routes read to stamp
        # cross-process descriptors (pod workers) with the flush's
        # sampled trace context
        self._stage_cells = stage_histogram()
        self._stage_acc: dict = {}
        self._launch_ctx = None

    def _note_stage(self, route: str, stage: str, dur_s: float,
                    n: int = 1, record: bool = True) -> None:
        """Record ``dur_s`` against one serving stage: the per-route/
        per-stage breakdown ``stats()['stages']`` reports, plus one
        ``bibfs_stage_seconds{stage}`` histogram sample unless
        ``record=False`` (a multi-query sum already histogrammed
        per query elsewhere). Callers on concurrent threads (the
        pipelined engine's flusher + finish worker) hold the engine
        lock."""
        if record:
            self._stage_cells[stage].record(dur_s)
        acc = self._stage_acc.setdefault(route, {})
        cell = acc.get(stage)
        if cell is None:
            acc[stage] = [n, dur_s]
        else:
            cell[0] += n
            cell[1] += dur_s

    # ---- graph resolution (the store seam) ---------------------------
    def _graph_rt(self, name) -> _GraphRuntime:
        """The runtime serving ``name``'s CURRENT snapshot — on a
        version change (hot-swap), build a fresh runtime and release the
        superseded one (its distance-cache namespace is invalidated; its
        snapshot retires once in-flight flush pins drop)."""
        if self._store is None:
            return self._runtimes[None]
        rt = self._runtimes.get(name)
        if self._rts_released:  # post-close stats(): no new pins
            if rt is None:
                raise ValueError("engine is closed")
            return rt
        if rt is not None and rt.snapshot is self._store.current(name):
            return rt  # the hot path: same version, no lock
        with self._rt_lock:
            rt = self._runtimes.get(name)
            snap = self._store.acquire(name)
            if rt is not None and rt.snapshot is snap:
                snap.release()
                return rt
            new = _GraphRuntime(
                snap, layout=self.layout, device=self._device,
                host_backend=self._host_backend,
            )
            self._runtimes[name] = new
            if rt is not None:
                old_id = rt.graph_id
                rt.snapshot.release()
                if old_id != new.graph_id:
                    # version-scoped invalidation: digest keys already
                    # make version-k entries unreachable for version-k+1
                    # queries; reclaim their rows now instead of waiting
                    # for LRU churn
                    self.dist_cache.invalidate(old_id)
                    self._kind_cache.invalidate(old_id)
            return new

    def _resolve_graph(self, graph) -> tuple:
        """``(name, runtime)`` for a submit-time graph argument; client
        mistakes (unknown name, a name without a store) surface as
        ``ValueError`` so ``return_errors`` mode tags them invalid."""
        if graph is None:
            name = self._default_name
        elif self._store is None:
            raise ValueError(
                "per-query graph names need an attached store (store=)"
            )
        else:
            name = str(graph)
        try:
            return name, self._graph_rt(name)
        except KeyError as e:
            raise ValueError(str(e)) from e

    def _pin_rt(self, name) -> _GraphRuntime:
        """Resolve AND pin in one step, under the runtime lock — a
        concurrent swap cannot retire the snapshot between the resolve
        and the pin. The caller owes one ``snapshot.release()`` (or
        hands the pin to :meth:`_bound`)."""
        with self._rt_lock:
            rt = self._graph_rt(name)
            rt.snapshot.retain()
        if self._store is not None:
            # recency for the residency accountant: the hot resolve
            # path above never re-acquires, so without this a served
            # graph keeps its first acquire's stamp forever
            self._store.touch(name)
        return rt

    @contextmanager
    def _bound(self, rt: _GraphRuntime):
        """Make ``rt`` the calling thread's flush target: everything in
        the with-block (device launch, host solves, banking, cache
        namespacing) reads THIS runtime through the engine's graph
        properties, whatever the store swaps to meanwhile — the swap
        barrier at the flush seams. Consumes one snapshot pin
        (:meth:`_pin_rt`)."""
        tls = self._flush_tls
        prev = getattr(tls, "rt", None)
        tls.rt = rt
        try:
            yield rt
        finally:
            tls.rt = prev
            rt.snapshot.release()

    def _current_rt(self) -> _GraphRuntime:
        """The thread's bound flush runtime, else the default graph's
        current one — what the ``n``/``graph``/``graph_id`` properties
        (and every solver seam) read."""
        rt = getattr(self._flush_tls, "rt", None)
        return rt if rt is not None else self._graph_rt(self._default_name)

    def _overlay_pending(self, name):
        """The graph's pending delta overlay (None when absent): while
        one exists, queries answer exactly through it and the distance
        cache stands aside — its entries describe the base snapshot,
        not the overlaid graph."""
        if self._store is None:
            return None
        return self._store.overlay(name)

    def _oracle_for(self, name):
        """The distance oracle serving ``name`` right now, or None.
        Store-backed engines read the store's per-graph oracle (whose
        follow-the-graph gen check guarantees it describes the CURRENT
        live edge state, pending overlay included — which is why the
        consult may run BEFORE the overlay route); inline engines use
        their construction-time oracle over the one immutable graph."""
        if self._store is None:
            return self._oracle
        return self._store.oracle(name)

    def _consult_oracle(self, t: _Pending, name) -> bool:
        """Consult the oracle tier for one submitted query (delegates
        to the :class:`~bibfs_tpu.serve.routes.OracleRoute`). True =
        served exactly (``t.result`` set, ``route="oracle"``); False =
        fall through (with ``t.cutoff`` armed when the consult produced
        a usable upper bound)."""
        return self.routes["oracle"].consult(t, name)

    @property
    def n(self) -> int:
        """Vertex count of the bound flush graph (outside a flush: the
        default graph's current snapshot)."""
        return self._current_rt().n

    @property
    def graph(self):
        """The bucketed device-resident graph (built on first use)."""
        return self._current_rt().graph

    @property
    def graph_id(self):
        return self._current_rt().graph_id

    @property
    def _bucket_key(self):
        return self._current_rt().bucket_key

    @property
    def _host_native_graph(self):
        return self._current_rt().host_native_graph

    @property
    def host_backend_resolved(self):
        return self._current_rt().host_backend_resolved

    # ---- submission --------------------------------------------------
    def submit(self, src: int, dst: int, graph: str | None = None,
               ctx=None) -> _Pending:
        """Queue one query (``graph`` names a store graph on a
        store-backed engine; None = the default graph). Cache hits and
        trivial queries resolve immediately; everything else resolves at
        the next flush (an overfull queue flushes itself at
        ``max_batch``). ``ctx`` is a sampled distributed-trace context
        (:mod:`bibfs_tpu.obs.dtrace`): it rides the ticket so the
        flush's dispatch routes can propagate it across process hops
        (pod descriptors) — None (the default) adds no work."""
        if self._rts_released:
            # the snapshot pins are gone: a later flush could neither
            # pin nor solve — fail HERE with a clear error instead of
            # stranding the ticket on a retired-snapshot RuntimeError
            raise ValueError("engine is closed")
        if self._draining:
            # draining-replica contract (rolling swaps): new work is
            # refused with a STRUCTURED capacity error — retryable on a
            # peer replica — while tickets already queued still resolve
            # at flush. Deliberately not counted as an engine error:
            # refusing admissions is the drain working, not a failure.
            raise QueryError(
                "engine is draining", kind="capacity",
                query=(int(src), int(dst)),
            )
        src, dst = int(src), int(dst)
        name, rt = self._resolve_graph(graph)
        if not (0 <= src < rt.n and 0 <= dst < rt.n):
            raise ValueError(f"src/dst out of range for n={rt.n}")
        t = _Pending(src, dst, name, ctx)
        self._c_queries.inc()
        if src == dst:
            self._c_trivial.inc()
            t.result = BFSResult(True, 0, [src], src, 0.0, 0, 0)
            return t
        # the oracle tier answers BEFORE the distance cache (and before
        # the overlay route: a store oracle is only ever returned when
        # its index describes the current live graph, overlay included)
        if self._consult_oracle(t, name):
            self._c_oracle.inc()
            return t
        if self._overlay_pending(name) is not None:
            hit = None
        else:
            # re-resolve AFTER the overlay read: a compaction commits
            # (overlay -> None, snapshot -> k+1) atomically, so an rt
            # resolved before the commit plus an overlay read after it
            # would serve a stale version-k cache entry to a query
            # submitted after the update. Overlay-read THEN resolve is
            # safe in both directions (same argument as _flush_graph).
            rt = self._graph_rt(name)
            hit = self.dist_cache.lookup(rt.graph_id, src, dst)
        if hit is not None:
            found, hops, path = hit
            self._c_cache_served.inc()
            t.result = BFSResult(
                found, hops if found else None, path if found else None,
                None, 0.0, 0, 0,
            )
            return t
        self._pending.append(t)
        if len(self._pending) >= self.max_batch:
            self.flush()
        return t

    def query(self, src: int, dst: int, graph: str | None = None
              ) -> BFSResult:
        """Submit + flush one query (the low-latency path: a cache hit
        never touches a solver; a miss dispatches alone, host-side when
        the crossover says so). Raises the ticket's
        :class:`QueryError` if every fallback rung failed it."""
        t = self.submit(src, dst, graph)
        if t.result is None and t.error is None:
            self.flush()
        if t.error is not None:
            raise t.error
        return t.result

    @staticmethod
    def _query_rep_pair(q: Query) -> tuple[int, int]:
        """A representative ``(src, dst)`` for a taxonomy query — what
        error messages and pair-targeted chaos rules key on."""
        from bibfs_tpu.query.types import AsOf, MultiSource

        rep = getattr(q, "rep_pair", None)
        if rep is not None:  # whole-graph analytics kinds declare one
            return rep()
        if isinstance(q, AsOf):
            return QueryEngine._query_rep_pair(q.inner)
        if isinstance(q, MultiSource):
            return int(q.sources[0]), int(q.dst)
        return int(q.src), int(q.dst)

    def submit_query(self, q, graph: str | None = None) -> _Pending:
        """Queue one TYPED query (:mod:`bibfs_tpu.query`): the
        taxonomy counterpart of :meth:`submit`. A
        :class:`~bibfs_tpu.query.PointToPoint` (or a bare pair)
        delegates to the classic ladder unchanged; the other kinds
        (msbfs/weighted/kshortest/asof) queue for their kind routes
        and resolve at the next flush — grouped per kind, packed
        sweeps shared across the flush's MultiSource queries, results
        cached per (snapshot digest, query key)."""
        q = coerce_query(q)
        if isinstance(q, PointToPoint):
            self._query_cells.cell("pt", "ladder").inc()
            return self.submit(q.src, q.dst, graph)
        if self._rts_released:
            raise ValueError("engine is closed")
        src, dst = self._query_rep_pair(q)
        if self._draining:
            raise QueryError(
                "engine is draining", kind="capacity", query=(src, dst),
            )
        name, rt = self._resolve_graph(graph)
        q.validate(rt.n)
        t = _Pending(src, dst, name)
        t.query = q
        self._c_queries.inc()
        if self._overlay_pending(name) is None:
            # overlay-read-then-resolve, the swap-race-safe ordering
            # (see submit); while updates are pending the cache stands
            # aside — its entries describe the base snapshot
            rt = self._graph_rt(name)
            hit = self._kind_cache.lookup(rt.graph_id, q.cache_key())
            if hit is not None:
                self._query_cells.cell(q.kind, "cache").inc()
                t.result = hit
                return t
            res = self._consult_analytics_store(name, rt, q)
            if res is not None:
                t.result = res
                return t
        self._pending.append(t)
        if len(self._pending) >= self.max_batch:
            self.flush()
        return t

    def query_one(self, q, graph: str | None = None):
        """Submit + flush one typed query; returns its kind's result
        type (``BFSResult`` / ``MultiSourceResult`` /
        ``WeightedResult`` / ``KShortestResult``). Raises the
        ticket's :class:`QueryError` on failure."""
        t = self.submit_query(q, graph)
        if t.result is None and t.error is None:
            self.flush()
        if t.error is not None:
            raise t.error
        return t.result

    def query_many(self, pairs, *, graph: str | None = None,
                   return_errors: bool = False) -> list:
        """Serve a whole query list through one (chunked) flush.

        ``return_errors=True`` switches to partial-failure mode: the
        returned list holds one entry per pair — a
        :class:`~bibfs_tpu.solvers.api.BFSResult` where the query
        resolved, a :class:`QueryError` where it (alone) failed,
        including queries rejected at submit time (``kind='invalid'``).
        The default re-raises the first failure, matching the
        pre-resilience contract."""
        tickets = self._submit_collect(pairs, return_errors, graph)
        if not tickets:
            return []  # nothing queued: skip the flush entirely
        if any(isinstance(t, _Pending) for t in tickets):
            self.flush()
        out = []
        for t in tickets:
            if isinstance(t, QueryError):
                out.append(t)
            elif t.error is not None:
                if not return_errors:
                    raise t.error
                out.append(to_query_error(t.error, (t.src, t.dst)))
            else:
                out.append(t.result)
        return out

    def _submit_collect(self, pairs, return_errors: bool,
                        graph: str | None = None) -> list:
        """Submit every pair; in ``return_errors`` mode a rejected
        submit becomes a ``kind='invalid'`` :class:`QueryError` slot
        (submit-time validation is the ONE place that knows it is
        looking at client input) instead of aborting the whole list.
        Accepts bare ``(s, d)`` pairs and typed taxonomy queries,
        mixed freely. Shared by both engines' ``query_many``."""
        tickets: list = []
        for item in pairs:
            try:
                if isinstance(item, Query):
                    tickets.append(self.submit_query(item, graph))
                else:
                    s, d = item
                    tickets.append(self.submit(int(s), int(d), graph))
            except (ValueError, TypeError) as e:
                if not return_errors:
                    raise
                try:
                    if isinstance(item, Query):
                        q = self._query_rep_pair(item)
                    else:
                        s, d = item
                        q = (int(s), int(d))
                except (ValueError, TypeError, IndexError):
                    q = None
                err = to_query_error(e, q, kind="invalid")
                self._count_error(err)
                tickets.append(err)
        return tickets

    # ---- flushing ----------------------------------------------------
    def flush(self) -> None:
        """Resolve every pending query — grouped per graph, each group
        bound to the snapshot it resolved at flush start (the swap
        barrier): batched device dispatch at or above the calibrated
        crossover, per-query host dispatch below, exact overlay solves
        while the graph has pending live updates."""
        pend, self._pending = self._pending, []
        if not pend:
            return
        if self._store is None:
            self._flush_graph(None, pend)
            return
        groups: dict = {}
        for t in pend:
            groups.setdefault(t.graph, []).append(t)
        for name, group in groups.items():
            self._flush_graph(name, group)

    def _flush_graph(self, name, pend) -> None:
        # overlay BEFORE pin: a compaction commits (snapshot', overlay
        # =None) atomically under the store lock, so pin-then-read could
        # pin the pre-update snapshot yet read no overlay — serving the
        # batch without the folded delta. Read-then-pin is safe in both
        # directions: a non-None overlay answers exactly on its own
        # base whatever gets swapped meanwhile, and a None read means
        # any pin taken after it is the post-compaction (or newer)
        # snapshot.
        overlay = self._overlay_pending(name)
        rt = self._pin_rt(name)
        with self._bound(rt), span("flush", queued=len(pend)):
            tax = [t for t in pend if t.query is not None]
            if tax:
                pend = [t for t in pend if t.query is None]
                self._flush_taxonomy(name, tax, overlay)
                if not pend:
                    return
            # dedupe exact repeats within one flush: serving traffic
            # repeats, and a batch slot per duplicate would be pure waste
            unique: dict[tuple[int, int], list[_Pending]] = {}
            for t in pend:
                unique.setdefault((t.src, t.dst), []).append(t)
            pairs = list(unique)
            if overlay is not None:
                self._flush_overlay(overlay, pairs, unique)
                return
            # hand the flush's sampled trace context (the first sampled
            # ticket's — one descriptor per batch, not per query) to the
            # dispatch routes for the duration of the ladder walk; pod
            # descriptors stamp it so worker spans join the trace
            self._launch_ctx = next(
                (t.ctx for t in pend if t.ctx is not None), None
            )
            try:
                for i in range(0, len(pairs), self.max_batch):
                    self._flush_ladder(pairs[i: i + self.max_batch],
                                       unique)
            finally:
                self._launch_ctx = None

    def _flush_overlay(self, overlay, pairs, unique) -> None:
        """The exact-answering route while live edge updates are
        pending (:class:`~bibfs_tpu.serve.routes.OverlayRoute`): every
        query solves against base+delta on the host, isolated per
        query. No cache lookup or banking — distance-cache entries are
        namespaced by snapshot digest, and the overlaid graph is not
        (yet) any snapshot."""
        with span("overlay_batch", batch=len(pairs)):
            for key, res in self.routes["overlay"].solve_iter(
                overlay, pairs
            ):
                if isinstance(res, QueryError):
                    self._resolve_error(unique[key], res)
                    continue
                self._c_overlay.inc()
                for t in unique[key]:
                    t.result = res

    # ---- taxonomy flushing (serve/routes/taxonomy.py) ----------------
    def _flush_taxonomy(self, name, tickets, overlay) -> None:
        """Resolve this flush's typed taxonomy tickets against the
        flush-bound truth: the snapshot's memoized CSR normally, the
        overlay-merged live CSR while edge updates are pending (every
        kind answers EXACTLY on the live edge set — the overlay-route
        contract, extended to the whole taxonomy; caching stands aside
        there). Kinds are grouped so the msbfs rung packs the whole
        flush's sources into shared sweeps."""
        from bibfs_tpu.serve.routes import KindCtx

        rt = self._current_rt()
        if overlay is not None:
            from bibfs_tpu.graph.csr import build_csr

            row_ptr, col_ind = build_csr(rt.n, overlay.merged_edges())
            ctx = KindCtx(rt.n, row_ptr, col_ind, base=False,
                          name=name, graph_id=rt.graph_id)
        else:
            row_ptr, col_ind = rt.snapshot.csr()
            ctx = KindCtx(rt.n, row_ptr, col_ind, base=True,
                          name=name, graph_id=rt.graph_id)
        groups: dict[str, list[_Pending]] = {}
        for t in tickets:
            groups.setdefault(t.query.kind, []).append(t)
        for kind in sorted(groups):
            self._flush_kind(kind, groups[kind], rt, ctx)

    def _flush_kind(self, kind, tickets, rt, ctx) -> None:
        """One kind group through its resilient rung ladder
        (:data:`~bibfs_tpu.serve.routes.taxonomy.KIND_LADDERS` — the
        device rung ahead of the host-tier kind rung): each eligible
        rung gets a resilient
        :meth:`~bibfs_tpu.serve.routes.base.Route.attempt` (bounded
        retries behind its own breaker), an ineligible rung is skipped
        silently (a routing decision), and an unavailable one degrades
        to the next — counted in ``bibfs_route_fallbacks_total{from=
        <rung>,to=<next>}`` — down to the kind's per-query-isolated
        ``fallback``, so an injected (or real) fault on any rung costs
        throughput, never availability. The walk order is the adaptive
        policy's per-(digest, kind) decision when the engine runs
        adaptive."""
        from bibfs_tpu.serve.routes import KIND_LADDERS, KIND_ROUTES

        ladder = KIND_LADDERS[kind]
        # dedupe identical queries within the flush (cache_key is the
        # exact-repeat identity, same motivation as the pt flush)
        unique: dict[tuple, list[_Pending]] = {}
        for t in tickets:
            unique.setdefault(t.query.cache_key(), []).append(t)
        queries = [unique[k][0].query for k in unique]
        if self._policy is not None:
            ladder, _why = self._policy.order(
                rt.snapshot.digest, len(queries), ladder, kind=kind
            )
        results = None
        used = "host"
        t0 = time.perf_counter()
        for i, rung in enumerate(ladder):
            if rung == "host":
                break
            route = self.routes[rung]
            if not route.kind_eligible(rt, queries, ctx):
                continue
            results = route.attempt(rt, queries, ctx)
            if results is not None:
                used = rung
                break
            self._note_fallback(
                rung, self._next_kind_rung(ladder, i, rt, queries, ctx)
            )
        if results is None:
            results = self.routes[KIND_ROUTES[kind]].fallback(
                rt, queries, ctx
            )
        elapsed = time.perf_counter() - t0
        if self._policy is not None:
            # whole-rung wall time (the taxonomy rungs are host-tier:
            # there is no solver-stamped dispatch clock to prefer)
            self._policy.note(
                rt.snapshot.digest, used, len(queries), elapsed,
                kind=kind,
            )
        cell = self._query_cells.cell(kind, used)
        for key, res in zip(unique, results):
            ts = unique[key]
            if isinstance(res, QueryError):
                self._resolve_error(ts, res)
                continue
            cell.inc(len(ts))
            if ctx.base:
                self._kind_cache.put(ctx.graph_id, key, res)
                self._analytics_store_put(kind, rt, ctx, key, res)
            for t in ts:
                t.result = res

    def _analytics_store_put(self, kind, rt, ctx, key, res) -> None:
        """Persist a freshly computed whole-graph analytics answer into
        the store's per-digest result store (analytics/results.py) —
        the counterpart of the submit-time consult. Base-snapshot
        answers only (the caller gates on ``ctx.base``); inline engines
        have no store and skip."""
        if self._store is None:
            return
        from bibfs_tpu.analytics.queries import ANALYTICS_KINDS

        if kind not in ANALYTICS_KINDS:
            return
        from bibfs_tpu.analytics.results import result_to_payload

        arrays, scalars = result_to_payload(kind, res)
        self._store.analytics.put(
            ctx.name, key, rt.snapshot.digest, kind, arrays, scalars
        )

    def _consult_analytics_store(self, name, rt, q):
        """The submit-time whole-graph result-store consult (after a
        kind-cache miss, only while no overlay is pending — stored
        entries describe settled snapshots). An exact-digest entry is
        served as ``route="store"``; an entry whose digest reaches the
        current one through adds-only deltas is incrementally
        maintained (decrease-only SSSP relaxation / component
        re-merge), committed back retagged, and served — the bench's
        no-recompute witness. Returns the result, or None to fall
        through to the normal flush."""
        if self._store is None:
            return None
        from bibfs_tpu.analytics.queries import ANALYTICS_KINDS

        if q.kind not in ANALYTICS_KINDS:
            return None
        store = self._store.analytics
        digest = rt.snapshot.digest
        found = store.lookup(name, q.cache_key(), digest)
        if found is None:
            return None
        from bibfs_tpu.analytics.queries import ComponentsResult, SsspResult
        from bibfs_tpu.analytics.results import (
            maintain_components,
            maintain_sssp,
            result_from_payload,
            result_to_payload,
        )

        t0 = time.perf_counter()
        if found[0] == "hit":
            res = result_from_payload(
                q.kind, found[1].arrays, found[1].scalars
            )
        else:
            _tag, entry, adds = found
            row_ptr, col_ind = rt.snapshot.csr()
            if q.kind == "sssp":
                seed = int(q.weight_seed)
                w = rt.weights_for(seed, row_ptr, col_ind)
                dist, _relaxed = maintain_sssp(
                    entry.arrays["dist"], adds, rt.n, row_ptr, col_ind,
                    w, seed,
                )
                res = SsspResult(
                    found=True, dist=dist,
                    reached=int(np.isfinite(dist).sum()),
                    rounds=int(entry.scalars.get("rounds", 0)),
                    time_s=time.perf_counter() - t0,
                )
            else:  # components — lookup() only offers maintainable kinds
                labels, count = maintain_components(
                    entry.arrays["labels"], adds, rt.n
                )
                res = ComponentsResult(
                    found=True, labels=labels, count=count,
                    rounds=int(entry.scalars.get("rounds", 0)),
                    time_s=time.perf_counter() - t0,
                )
            arrays, scalars = result_to_payload(q.kind, res)
            store.commit_maintained(
                name, q.cache_key(), digest, q.kind, arrays, scalars
            )
        self._query_cells.cell(q.kind, "store").inc()
        self._kind_cache.put(rt.graph_id, q.cache_key(), res)
        return res

    def _next_kind_rung(self, ladder, i: int, rt, queries, ctx) -> str:
        """The rung a failed kind-ladder step actually degrades TO
        (the ``to`` label of the fallback counter — the kind-ladder
        twin of :meth:`_next_rung`)."""
        for name in ladder[i + 1:]:
            if name == "host" or self.routes[name].kind_eligible(
                rt, queries, ctx
            ):
                return name
        return "host"

    def _next_rung(self, i: int, rt, pairs, ladder=None) -> str:
        """The rung a failed/ineligible ladder step actually degrades
        TO: the next ladder name that is terminal (``host``) or
        eligible for this batch — the ``to`` label of the fallback
        counter must name where the batch really went."""
        ladder = self._ladder if ladder is None else ladder
        for name in ladder[i + 1:]:
            if name == "host" or self.routes[name].eligible(rt, pairs):
                return name
        return "host"

    def _ladder_for(self, rt, pairs):
        """The ladder this flush walks: the adaptive policy's per-digest
        ordering when the engine runs adaptive
        (:meth:`~bibfs_tpu.serve.policy.AdaptiveRouter.order` — counted
        in ``bibfs_routes_adaptive_total``), else the static ladder."""
        if self._policy is None:
            return self._ladder
        order, _reason = self._policy.order(
            rt.snapshot.digest, len(pairs), self._ladder
        )
        return order

    def _note_route_time(self, rt, route: str, pairs, seconds) -> None:
        """Feed the adaptive policy one resolved batch's measurement,
        plus its periodic level-shape sample: one telemetry-enabled
        serial solve of the batch's first pair (~1.5% of flushes),
        recording push/pull choices and frontier fractions into the
        per-digest policy and the ``bibfs_level_frontier_fraction``
        histogram. The sample runs on a BACKGROUND thread with its own
        snapshot pin — a full serial BFS on a big graph must not stall
        the flush (or the pipelined engine's one finish worker) for a
        diagnostic."""
        if self._policy is None:
            return
        digest = rt.snapshot.digest
        if not self._policy.note(digest, route, len(pairs), seconds):
            return
        try:
            snap = rt.snapshot.retain()
        except RuntimeError:
            # racing retirement: skip this sample (and release the
            # claimed one-in-flight slot, or sampling stops forever)
            self._policy.sample_done()
            return
        policy = self._policy
        n = rt.n
        src, dst = (int(v) for v in pairs[0])

        def _sample():
            try:
                from bibfs_tpu.obs.telemetry import LevelTelemetry
                from bibfs_tpu.solvers.serial import solve_serial_csr

                tel = LevelTelemetry(n=n)
                row_ptr, col_ind = snap.csr()
                solve_serial_csr(n, row_ptr, col_ind, src, dst,
                                 telemetry=tel)
                policy.observe_levels(digest, tel.as_dict(), n)
            except Exception:
                pass  # a diagnostic sample must never fail anything
            finally:
                snap.release()
                policy.sample_done()  # release the one-in-flight slot

        threading.Thread(
            target=_sample, name="bibfs-policy-sample", daemon=True
        ).start()

    def _note_crossover(self) -> None:
        """A below-crossover batch skipped the mesh rung — a routing
        decision, counted apart from failures."""
        mesh = self.routes.get("mesh")
        if mesh is not None:
            mesh.cells.reroutes.inc()

    def _flush_ladder(self, pairs, unique) -> None:
        """Walk the fallback ladder for one chunk: each eligible rung
        gets a resilient :meth:`~bibfs_tpu.serve.routes.Route.attempt`
        (bounded retries behind its own breaker); an unavailable rung
        degrades to the next (counted in
        ``bibfs_route_fallbacks_total``), and the terminal host rung
        absorbs whatever is left behind its bisection isolator. A
        sub-crossover chunk (including the tail after full device
        chunks) skips straight past the ineligible dispatch rungs —
        host latency beats padding a whole batch rung for a few
        stragglers."""
        rt = self._current_rt()
        ladder = self._ladder_for(rt, pairs)
        for i, name in enumerate(ladder):
            if name == "host":
                break
            route = self.routes[name]
            if not route.eligible(rt, pairs):
                if name == "mesh":
                    self._note_crossover()
                continue
            results = route.attempt(
                rt, pairs, self._cutoffs_for(pairs, unique)
            )
            if results is not None:
                # the solver-stamped whole-batch wall clock of the
                # SUCCESSFUL attempt (launch t0 -> finish), not the
                # attempt() wall time: retry backoff sleeps in a
                # transient-failure flush would otherwise double the
                # learned latency of a healthy route (the pipelined
                # engine's launch_s + finish split makes the same
                # exclusion)
                self._note_route_time(
                    rt, name, pairs, results[0].time_s
                )
                for j, (src, dst) in enumerate(pairs):
                    self._resolve(unique[(src, dst)], src, dst, results[j])
                return
            # every retry burned (or the breaker is open): degrade down
            # the ladder instead of failing the batch
            self._note_fallback(name, self._next_rung(i, rt, pairs, ladder))
        # _flush_host returns its SOLVE time (delivery/banking
        # excluded), comparable to the dispatch rungs' solver-stamped
        # batch clocks — wall-timing the whole call would bias the
        # learned crossover against host
        self._note_route_time(
            rt, "host", pairs, self._flush_host(pairs, unique)
        )

    def _device_launch(self, pairs):
        """Stage 1 of a device flush: enqueue ONE batched program for
        ``pairs`` and return ``(out, finish, t0)`` without reading any
        value back. On the tunneled runtime this returns as soon as the
        dispatch is in flight, which is exactly the seam the pipelined
        engine overlaps: batch k+1 launches here while batch k is still
        inside :meth:`_device_finish` on the finish worker."""
        from bibfs_tpu.solvers.batch_minor import auto_batch_mode
        from bibfs_tpu.solvers.dense import _batch_dispatch

        with span("device_launch", batch=len(pairs)):
            if self._faults is not None:
                self._faults.fire("device", pairs)
            graph = self.graph  # lazy build; also sets self._bucket_key
            rung = min(bucket_batch(len(pairs)), self.max_batch)
            # pad the flush to its batch rung with inert (0, 0) queries so
            # every queue depth maps onto a handful of compiled programs
            padded = np.zeros((rung, 2), dtype=np.int64)
            padded[: len(pairs)] = pairs
            mode = self.mode
            if mode == "auto":
                mode = auto_batch_mode(graph, rung)
            self.exec_cache.note((self._bucket_key, mode, rung))
            _p, dispatch, finish = _batch_dispatch(graph, padded, mode)
            t0 = time.perf_counter()
            out = dispatch()
            return out, finish, t0

    def _device_finish(self, out, finish, t0, pairs) -> list[BFSResult]:
        """Stage 2 of a device flush: force execution, run the host-side
        finish hook (minor8 parent decode, capped-query refills),
        materialize per-query results and bank the parent forests.
        Everything here is host work — the pipelined engine runs it on a
        worker thread while the flusher dispatches the next batch."""
        from bibfs_tpu.solvers.dense import _materialize_batch
        from bibfs_tpu.solvers.timing import force_scalar

        with span("device_finish", batch=len(pairs)):
            if self._faults is not None:
                self._faults.fire("device_finish", pairs)
            force_scalar(out)  # lazy runtimes execute at the value read
            elapsed = time.perf_counter() - t0
            outs = finish(out)
            results = _materialize_batch(outs, len(pairs), elapsed)
            self.counters["device_batches"] += 1
            self.counters["device_queries"] += len(pairs)
            self._bank_forests(
                pairs, np.asarray(outs[2]), np.asarray(outs[3])
            )
            return results

    def _bank_forests(self, pairs, par_s, par_t) -> None:
        """Bank both sides' parent forests: level-synchronous searches
        stamp TRUE distances, so each forest answers future queries
        about its root (and reverse twins) without any dispatch.

        Flush-time hygiene: each forest insert copies one int32[n] row,
        so blindly banking 2 rows per query (~200 MB per 256-query flush
        at n=100k) mostly feeds inserts the LRU (default 64 entries vs
        512 rows) evicts before anything reads them. Instead, dedupe
        repeated roots within the flush (newest plane wins — it is the
        most recently solved) and bank only the newest
        ``dist_cache.entries`` roots; everything skipped lands in the
        ``inserts_skipped`` counter."""
        with span("bank_forests", batch=len(pairs)):
            self._bank_forests_inner(pairs, par_s, par_t)

    def _bank_forests_inner(self, pairs, par_s, par_t) -> None:
        planes: dict[int, tuple[np.ndarray, int]] = {}
        rank: dict[int, int] = {}
        k = 0
        for i, (src, dst) in enumerate(pairs):
            for root, plane in ((src, par_s), (dst, par_t)):
                planes[root] = (plane, i)
                rank[root] = k  # later occurrence = newer
                k += 1
        cap = max(self.dist_cache.entries, 0)
        newest = sorted(planes, key=rank.__getitem__)
        keep = newest[-cap:] if cap else []
        self.counters["inserts_skipped"] += 2 * len(pairs) - len(keep)
        for root in keep:
            plane, i = planes[root]
            self.dist_cache.put_forest(self.graph_id, root, plane[i], self.n)

    def _use_device(self) -> bool:
        """Whether above-crossover flushes go to the device program:
        auto-routed by substrate (module docstring — the dispatch tax
        batching amortizes is ~67 ms through the tunneled TPU and ~9 us
        on the CPU backend, calibration.json), overridable at
        construction."""
        if self._device_batches is not None:
            return self._device_batches
        import jax

        return jax.default_backend() != "cpu"

    @staticmethod
    def _cutoffs_for(pairs, unique):
        """Per-pair oracle cutoffs for a host flush (None when no
        ticket in the flush carried one — the common case costs one
        list pass). Duplicate tickets of one pair share the tightest
        bound any of them was armed with."""
        cutoffs = [
            min(
                (t.cutoff for t in unique[key] if t.cutoff is not None),
                default=None,
            )
            for key in pairs
        ]
        return cutoffs if any(c is not None for c in cutoffs) else None

    def _flush_host(self, pairs, unique) -> float:
        """Solve + deliver one host batch; returns the SOLVE seconds
        (the adaptive policy's comparable measurement)."""
        t0 = time.perf_counter()
        results = self._solve_host_isolated(
            pairs, self._cutoffs_for(pairs, unique)
        )
        solve_s = time.perf_counter() - t0
        n_ok = self._deliver_host_results(
            pairs, results,
            lambda key, res: self._resolve(unique[key], *key, res),
            lambda key, err: self._resolve_error(unique[key], err),
        )
        self._c_host_queries.inc(n_ok)
        return solve_s

    def _deliver_host_results(self, pairs, results,
                              resolve_ok, resolve_err) -> int:
        """One host batch's delivery skeleton, shared by the sync flush
        and the pipelined finish-worker paths (which differ only in HOW
        a ticket resolves/fails): partition the isolator's mixed
        ``BFSResult | QueryError`` list, remap the banking-hygiene
        indices (computed over successes only) back onto batch
        positions, bank, and hand each entry to the right callback.
        Returns the success count (the ``host_queries`` increment —
        failures are counted by the error path).

        No parent planes exist on the host route, but each found
        shortest path is itself a valid forest fragment for both
        endpoints — so repeated-source traffic stays cache-servable."""
        ok_idx = [
            i for i, r in enumerate(results)
            if not isinstance(r, QueryError)
        ]
        bank = self._paths_to_bank([results[i] for i in ok_idx])
        bank_idx = {ok_idx[j] for j in bank}
        for i, ((src, dst), res) in enumerate(zip(pairs, results)):
            if isinstance(res, QueryError):
                resolve_err((src, dst), res)
                continue
            if i in bank_idx:
                self.dist_cache.put_path(self.graph_id, res.path, self.n)
            resolve_ok((src, dst), res)
        return len(ok_idx)

    def _solve_host_isolated(self, pairs, cutoffs=None):
        """The host route with failure isolation: the whole batch first
        (``_solve_host``, zero extra cost when nothing fails); on
        failure, BISECT — halves re-solve independently, so a poison
        batch converges in O(log B) extra solves to exactly the queries
        that are actually bad. A failing singleton gets one last rung
        (the NumPy serial oracle, independent of both the native
        runtime and the device stack) and only then a structured
        :class:`QueryError`. ``cutoffs`` (oracle upper bounds, aligned
        with ``pairs``) ride the recursion. Returns one ``BFSResult |
        QueryError`` per pair; never raises."""
        try:
            return self._solve_host(pairs, cutoffs)
        except Exception as exc:
            if len(pairs) == 1:
                self._note_fallback("host", "serial")
                try:
                    src, dst = pairs[0]
                    return [self._solve_serial_one(
                        src, dst, cutoffs[0] if cutoffs else None
                    )]
                except Exception as exc2:
                    return [to_query_error(exc2, pairs[0])]
            self._res_cells.bisections.inc()
            mid = len(pairs) // 2
            del exc  # halves re-derive their own failure (or succeed)
            c_lo = cutoffs[:mid] if cutoffs else None
            c_hi = cutoffs[mid:] if cutoffs else None
            return (
                self._solve_host_isolated(pairs[:mid], c_lo)
                + self._solve_host_isolated(pairs[mid:], c_hi)
            )

    def _solve_serial_one(self, src: int, dst: int,
                          cutoff: int | None = None) -> BFSResult:
        """The bottom of the fallback ladder
        (:class:`~bibfs_tpu.serve.routes.SerialRoute`): the pure-NumPy
        serial oracle over the bound graph's CSR — no native runtime,
        no device stack, nothing left to be broken but the graph
        itself. (A thin seam over the route so chaos tests can break
        this rung per engine.)"""
        return self.routes["serial"].solve_one(
            self._current_rt(), src, dst, cutoff
        )

    def _resolve_error(self, tickets, err: QueryError) -> None:
        """Fail exactly these tickets with a structured error (their
        batch peers resolve normally) and feed the error telemetry."""
        self._count_error(err, len(tickets))
        for t in tickets:
            t.error = err

    def _count_error(self, err: BaseException, n: int = 1) -> None:
        from bibfs_tpu.serve.resilience import HEALTH_ERROR_KINDS

        kind = getattr(err, "kind", "internal")
        cell = self._res_cells.errors.get(kind)
        if cell is None:
            cell = self._res_cells.errors["internal"]
        cell.inc(n)
        # only SERVER-side failures degrade health: a client submitting
        # malformed queries or abandoning tickets must not be able to
        # flip a healthy node's /healthz
        if kind in HEALTH_ERROR_KINDS:
            self.health.note_error(n)

    def _note_fallback(self, frm: str, to: str) -> None:
        self._res_cells.fallback_cell(frm, to).inc()

    def _paths_to_bank(self, results) -> set:
        """Flush-time banking hygiene, host edition: of this flush's
        found paths, bank only the newest ``dist_cache.entries`` — a
        flush deeper than the LRU would evict the rest before anything
        could read them, and each banking is a Python chain-merge the
        serving hot loop should not pay for nothing. Returns the result
        indices to bank; the skipped count lands in
        ``inserts_skipped``."""
        found = [i for i, r in enumerate(results) if r.found]
        cap = max(self.dist_cache.entries, 0)
        bank = set(found[-cap:]) if cap else set()
        self.counters["inserts_skipped"] += len(found) - len(bank)
        return bank

    # below this many queries, one threaded-batch call costs more in
    # thread spin-up + ctypes marshalling than it saves; per-query
    # dispatch is the measured latency winner there
    HOST_BATCH_MIN = 4

    def _solve_host(self, pairs, cutoffs=None) -> list[BFSResult]:
        """Solve ``pairs`` on the host route: the threaded native C
        batch (one GIL-free ctypes call, queries striped over C worker
        threads — ``solvers/native.solve_batch_native_graph``) when the
        native runtime carries the route and the flush is big enough to
        amortize it, else the per-query solver loop. ``cutoffs``
        (oracle upper bounds) reach the per-query solvers; the C batch
        ignores them (no seed seam in the C search loop)."""
        with span("host_batch", batch=len(pairs)):
            if self._faults is not None:
                self._faults.fire("host_batch", pairs)
            solver = self._get_host_solver()
            ng = self._host_native_graph
            if ng is not None and len(pairs) >= self.HOST_BATCH_MIN:
                from bibfs_tpu.solvers.native import solve_batch_native_graph

                results = solve_batch_native_graph(
                    ng, np.asarray(pairs, dtype=np.int64)
                )
                # the batch's per-query path buffer is capped (default
                # 512; a full n+1 per lane would cost B*(n+1) ints per
                # flush) — a found result with no path hit that cap, so
                # re-solve just those per-query, which always carries
                # the full buffer
                return [
                    solver(src, dst) if (r.found and r.path is None) else r
                    for (src, dst), r in zip(pairs, results)
                ]
            if cutoffs is None:
                return [solver(src, dst) for src, dst in pairs]
            return [
                solver(src, dst, cutoff=c)
                for (src, dst), c in zip(pairs, cutoffs)
            ]

    def _resolve(self, tickets, src, dst, res: BFSResult) -> None:
        self.dist_cache.put_result(
            self.graph_id, src, dst, res.found, res.hops, res.path
        )
        for t in tickets:
            t.result = res

    def _get_host_solver(self):
        """The sub-crossover per-query path of the bound graph: the
        native C++ runtime when it loads (the measured latency winner,
        PERF_NOTES §3), else the NumPy serial oracle
        (:meth:`_GraphRuntime.get_host_solver`)."""
        return self._current_rt().get_host_solver()

    # ---- lifecycle ---------------------------------------------------
    def begin_drain(self) -> None:
        """Enter the draining state: ``/healthz`` flips to draining (a
        router stops sending traffic), NEW submits are refused with a
        ``kind='capacity'`` :class:`QueryError`, and everything already
        queued still resolves at the next :meth:`flush`. Reversible via
        :meth:`end_drain` — this is the replica-at-a-time seam a fleet
        rolling swap drains through; ``close()`` remains the terminal
        drain."""
        self._draining = True
        self.health.set_draining()

    def end_drain(self) -> None:
        """Leave the draining state (rolling-swap re-admit): submits
        are accepted again and health goes back to ready/degraded from
        its live inputs."""
        self._draining = False
        self.health.clear_draining()

    def kill(self) -> None:
        """Crash-semantics teardown for chaos drills: tickets still
        QUEUED fail NOW with a structured ``kind='internal'``
        :class:`QueryError` (a crashed replica cannot solve them — its
        router reroutes the failures to a peer) instead of being
        drained, health flips to draining, and the snapshot pins drop.
        Later submits raise ``engine is closed``. Contrast
        :meth:`close`, which resolves everything queued first."""
        self._draining = True
        pend, self._pending = self._pending, []
        if pend:
            self._resolve_error(pend, QueryError(
                "replica killed: engine torn down with queries queued",
                kind="internal",
            ))
        self.health.set_draining()
        self._release_runtimes()

    def close(self) -> None:
        """Resolve anything still queued, then mark the engine draining
        (``/healthz`` flips to 503) and drop the engine's snapshot pins
        (store-backed snapshots retire once the last pin lands). Later
        ``submit``/``query`` calls raise a clear ``engine is closed``
        (post-close ``stats()`` stays readable). The synchronous engine
        owns no threads, so this is otherwise just a drain — it exists
        so load drivers and ``with`` blocks treat both engine flavors
        uniformly (the pipelined subclass tears down its worker threads
        here)."""
        self.flush()
        self.health.set_draining()
        self._release_runtimes()

    def _release_runtimes(self) -> None:
        """Drop the engine's per-runtime snapshot pins, once. Runtimes
        stay readable afterwards (post-close ``stats()``) but are never
        re-resolved against the store."""
        with self._rt_lock:
            if self._rts_released:
                return
            self._rts_released = True
            rts = list(self._runtimes.values())
        for rt in rts:
            rt.snapshot.release()
        if self._policy is not None:
            try:
                self._policy.save()  # the learned-policy sidecar is
                # best-effort at teardown: a full disk must not turn a
                # clean close (or a kill() chaos drill) into a raise
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---- introspection ----------------------------------------------
    @property
    def pending(self) -> int:
        return len(self._pending)

    def stats(self) -> dict:
        """Machine-readable serving counters (the bench artifact's
        ``stats`` block)."""
        c = dict(self.counters)
        rt = self._current_rt()
        solved = (
            c["device_queries"] + c["host_queries"]
            + c["overlay_queries"] + c["mesh_queries"]
            + c["blocked_queries"]
        )
        kinds = self._query_cells.snapshot()
        # taxonomy queries resolved by a solver rung (anything but the
        # kind cache) count as solved for the dispatch-free figure
        solved += sum(
            v for kind, routes in kinds.items() if kind != "pt"
            for route, v in routes.items() if route != "cache"
        )
        return {
            **c,
            "solver_dispatch_free": c["queries"] - solved,
            "stages": {
                route: {
                    stage: {"n": cell[0], "s": round(cell[1], 6)}
                    for stage, cell in sorted(acc.items())
                }
                for route, acc in sorted(self._stage_acc.items())
            },
            "query_kinds": kinds,
            "kind_cache": self._kind_cache.stats(),
            "ladder": list(self._ladder),
            "routes": {
                name: route.stats() for name, route in self.routes.items()
            },
            "dist_cache": self.dist_cache.stats(),
            "exec_cache": self.exec_cache.stats(),
            "flush_threshold": self.flush_threshold,
            "max_batch": self.max_batch,
            "bucket": (
                list(self._bucket_key[1:3]) if self._bucket_key else None
            ),
            "device_batches_enabled": self._use_device(),
            "host_backend": getattr(self, "host_backend_resolved", None),
            "graph": {
                "n": rt.n,
                "digest": rt.snapshot.digest,
                "version": rt.snapshot.version,
                "store_graph": self._default_name,
                "graphs_resolved": (
                    None if self._store is None
                    else sorted(self._runtimes)
                ),
            },
            # the engine-local inline oracle (store-backed engines
            # report per-graph oracles through store.stats() instead)
            "oracle": (
                None if self._oracle is None else self._oracle.stats()
            ),
            "adaptive": (
                None if self._policy is None else self._policy.stats()
            ),
            "resilience": {
                **self._res_cells.snapshot(),
                "breaker": self._breaker.snapshot(),
                "retry": self._retry.snapshot(),
                "faults": (
                    None if self._faults is None else self._faults.stats()
                ),
            },
            "health": self.health.snapshot(),
        }

    def health_snapshot(self) -> dict:
        """The ``/healthz`` payload: the health state machine's view
        (state, reasons, breaker, recent errors)."""
        return self.health.snapshot()
