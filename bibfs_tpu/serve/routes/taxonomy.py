"""The taxonomy query routes — ``msbfs`` / ``weighted`` / ``kshortest``
/ ``asof`` as peer Route rungs.

Each non-point-to-point query kind (:mod:`bibfs_tpu.query`) is served
by a :class:`~bibfs_tpu.serve.routes.base.Route` subclass with the
full resilience contract the dispatch rungs carry: its own retry
policy and circuit breaker (``Route.attempt``), its own chaos seam in
:data:`bibfs_tpu.serve.faults.KNOWN_SITES` (``msbfs`` / ``weighted`` /
``kshortest`` / ``asof_replay``), and a ``fallback`` rung that solves
per query through INDEPENDENT machinery with failure isolation — an
injected (or real) fault on the primary degrades the kind to its
fallback exactly the way a dead accelerator degrades to the host
ladder, counted in ``bibfs_route_fallbacks_total{from=<kind>,
to=host}``:

- ``msbfs`` primary: the bitmask-packed sweep
  (:mod:`bibfs_tpu.query.msbfs` — 64 sources per sweep, one sweep set
  per flush); fallback: one host BFS per source (the very per-query
  solves the packed sweep exists to beat — availability over
  throughput).
- ``weighted`` primary: delta-stepping; fallback: the binary-heap
  Dijkstra oracle, the independent implementation the tests validate
  against.
- ``kshortest`` primary: Yen's; fallback: Yen's again but isolated
  per query with no chaos seam in the way (the algorithm IS the
  bottom rung — what degrades here is batching and the seam, not the
  math).
- ``asof`` primary: historical-snapshot reconstruction
  (:mod:`bibfs_tpu.store.history`) + host solves of the inner
  queries, with a per-engine reconstruction cache; fallback:
  re-reconstruction per query, isolated.

Queries solve against a :class:`KindCtx` — the flush-bound CSR truth:
the snapshot's memoized CSR normally, the overlay-merged CSR while
live updates are pending (every kind answers EXACTLY on the live edge
set, the same contract the overlay route gives point-to-point), in
which case result caching stands aside. Executable accounting: packed
sweeps are noted in the engine's ExecutableCache under
``placement_bucket_key(kind="msbfs")`` keys so msBFS "programs" (host
sweeps, keyed by padded word geometry) never collide with device
executables of the same graph.

Metrics (README "Query taxonomy"): ``bibfs_query_total{engine,kind,
route}`` counts every taxonomy query by resolving route (kind ``pt``
counts its delegation to the classic ladder under ``route="ladder"``
— the per-rung split of that ladder already lives in
``bibfs_queries_routed_total``), ``bibfs_query_asof_replay_seconds``
is the last historical reconstruction's cost, and
``bibfs_msbfs_breaker_state`` mirrors the msbfs rung's breaker the
way the mesh/blocked gauges mirror theirs. All minted at route-set
construction so a scrape renders the whole group at zero.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict

import numpy as np

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.obs.trace import span
from bibfs_tpu.query.types import MSBFS_WORD, QUERY_KINDS
from bibfs_tpu.serve.buckets import placement_bucket_key
from bibfs_tpu.serve.resilience import (
    BREAKER_STATE_CODES,
    QueryError,
    to_query_error,
)
from bibfs_tpu.serve.routes.base import Route

#: kind -> the Route name serving it (the HOST-tier primary rung; its
#: ``fallback`` is every kind's terminal answering machinery, and
#: ``host`` is the terminal rung name in the ladder/fallback counters)
KIND_ROUTES = {
    "msbfs": "msbfs",
    "weighted": "weighted",
    "kshortest": "kshortest",
    "asof": "asof",
    # the whole-graph analytics kinds (serve/routes/analytics.py) —
    # same contract, answers are vectors/scalars instead of paths
    "sssp": "sssp",
    "pagerank": "pagerank",
    "components": "components",
    "triangles": "triangles",
}

#: the per-kind ladder ``QueryEngine._flush_kind`` walks: the device
#: rung (serve/routes/taxonomy_device.py) ahead of the host-tier kind
#: rung, ``host`` terminal — an ineligible device rung is skipped
#: silently (a routing decision), an UNAVAILABLE one (breaker open /
#: retries burned) degrades with a counted fallback. Per-kind adaptive
#: policies reorder the non-terminal rungs per graph digest.
KIND_LADDERS = {
    "msbfs": ("msbfs_device", "msbfs", "host"),
    "weighted": ("weighted_device", "weighted", "host"),
    "kshortest": ("kshortest_device", "kshortest", "host"),
    "asof": ("asof", "host"),
    "sssp": ("sssp_blocked", "sssp", "host"),
    "pagerank": ("pagerank_blocked", "pagerank", "host"),
    "components": ("components_blocked", "components", "host"),
    "triangles": ("triangles_blocked", "triangles", "host"),
}

#: eagerly minted (kind, route) label pairs — the render-at-zero set
KIND_ROUTE_LABELS = (
    ("pt", "ladder"),
    ("msbfs", "msbfs"), ("msbfs", "msbfs_device"),
    ("msbfs", "host"), ("msbfs", "cache"),
    ("weighted", "weighted"), ("weighted", "weighted_device"),
    ("weighted", "host"), ("weighted", "cache"),
    ("kshortest", "kshortest"), ("kshortest", "kshortest_device"),
    ("kshortest", "host"), ("kshortest", "cache"),
    ("asof", "asof"), ("asof", "host"), ("asof", "cache"),
    # the analytics kinds add a "store" route: answers served from the
    # per-digest whole-graph result store (analytics/results.py)
    ("sssp", "sssp"), ("sssp", "sssp_blocked"),
    ("sssp", "host"), ("sssp", "cache"), ("sssp", "store"),
    ("pagerank", "pagerank"), ("pagerank", "pagerank_blocked"),
    ("pagerank", "host"), ("pagerank", "cache"), ("pagerank", "store"),
    ("components", "components"), ("components", "components_blocked"),
    ("components", "host"), ("components", "cache"),
    ("components", "store"),
    ("triangles", "triangles"), ("triangles", "triangles_blocked"),
    ("triangles", "host"), ("triangles", "cache"),
    ("triangles", "store"),
)


class QueryKindCells:
    """The taxonomy metric cells of ONE engine, minted at route-set
    construction (module docstring names)."""

    def __init__(self, label: str):
        family = REGISTRY.counter(
            "bibfs_query_total",
            "Taxonomy queries resolved, by query kind and serving "
            "route (kind=pt counts its delegation to the classic "
            "ladder; the per-rung split lives in "
            "bibfs_queries_routed_total)",
            ("engine", "kind", "route"),
        )
        self._family = family
        self._label = label
        self._cells = {
            (k, r): family.labels(engine=label, kind=k, route=r)
            for k, r in KIND_ROUTE_LABELS
        }
        self.asof_replay_gauge = REGISTRY.gauge(
            "bibfs_query_asof_replay_seconds",
            "Duration of the engine's last as-of historical "
            "reconstruction (WAL + versioned manifests replay)",
            ("engine",),
        ).labels(engine=label)

    def cell(self, kind: str, route: str):
        c = self._cells.get((kind, route))
        if c is None:
            c = self._family.labels(
                engine=self._label, kind=kind, route=route
            )
            self._cells[(kind, route)] = c
        return c

    def snapshot(self) -> dict:
        out: dict = {k: {} for k in QUERY_KINDS}
        for (k, r), c in self._cells.items():
            if c.value:
                out.setdefault(k, {})[r] = c.value
        return {k: v for k, v in out.items() if v}


class KindCtx:
    """The CSR truth one taxonomy flush group solves against: the
    bound snapshot's memoized CSR (``base=True`` — results cacheable),
    or the overlay-merged live CSR (``base=False`` — exact answers,
    caching stands aside). ``name`` is the store graph name (None on
    an inline engine)."""

    __slots__ = ("n", "row_ptr", "col_ind", "base", "name", "graph_id")

    def __init__(self, n, row_ptr, col_ind, *, base, name, graph_id):
        self.n = int(n)
        self.row_ptr = row_ptr
        self.col_ind = col_ind
        self.base = bool(base)
        self.name = name
        self.graph_id = graph_id


@guarded_by("_lock", "_entries", "hits", "misses")
class KindResultCache:
    """A small per-engine LRU over taxonomy results, keyed
    ``(graph_id, query.cache_key())`` — the snapshot digest namespace
    makes cross-version aliasing impossible, the same argument as the
    distance cache. Results are immutable once resolved, so sharing
    the object between tickets is safe."""

    def __init__(self, entries: int = 256):
        self.entries = int(entries)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0

    def lookup(self, graph_id, key):
        k = (graph_id, key)
        with self._lock:
            res = self._entries.get(k)
            if res is None:
                self.misses += 1
                return None
            self._entries.move_to_end(k)
            self.hits += 1
            return res

    def put(self, graph_id, key, result) -> None:
        if self.entries <= 0:
            return
        k = (graph_id, key)
        with self._lock:
            self._entries[k] = result
            self._entries.move_to_end(k)
            while len(self._entries) > self.entries:
                self._entries.popitem(last=False)

    def invalidate(self, graph_id) -> int:
        with self._lock:
            dead = [k for k in self._entries if k[0] == graph_id]
            for k in dead:
                del self._entries[k]
            return len(dead)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.entries,
                "hits": self.hits,
                "misses": self.misses,
            }


class TaxonomyRoute(Route):
    """Shared shape of the four kind routes: never eligible from the
    point-to-point ladder (the engines dispatch by kind), a resilient
    primary behind ``Route.attempt``, and a per-query-isolated
    ``fallback`` that never raises and never returns unavailable."""

    kind: str = "taxonomy"

    def eligible(self, rt, pairs) -> bool:
        return False  # kind-dispatched, never from the pt ladder

    def kind_eligible(self, rt, queries, ctx) -> bool:
        """The kind-ladder routing predicate (``_flush_kind`` skips an
        ineligible rung silently — a routing decision, not a failure).
        Host-tier kind rungs carry anything; the device rungs
        (serve/routes/taxonomy_device.py) gate on substrate, snapshot
        base, layout, and their calibrated crossovers."""
        return True

    def solve(self, rt, queries, ctx=None):
        out, fin, t0 = self.launch(rt, queries, ctx)
        return self.finish(out, fin, t0, queries)

    def launch(self, rt, queries, ctx=None):
        raise NotImplementedError

    def finish(self, out, fin, t0, queries):
        return out

    # base Route.attempt() calls solve(rt, pairs, cutoffs) — the ctx
    # rides the cutoffs position, so attempt(rt, queries, ctx) works
    # unchanged: bounded retries behind this route's own breaker.

    def fallback(self, rt, queries, ctx):
        """The kind's terminal rung: solve each query independently
        (failure isolation — one poisoned query costs one slot, never
        its batch). Returns one result-or-``QueryError`` per query."""
        out = []
        for q in queries:
            try:
                out.append(self._fallback_one(rt, q, ctx))
            except Exception as exc:
                out.append(to_query_error(
                    exc, self._query_pair(q),
                ))
        return out

    def _fallback_one(self, rt, q, ctx):
        raise NotImplementedError

    def _query_pair(self, q):
        """The engine's representative-pair rule — ONE implementation
        (``QueryEngine._query_rep_pair``) keys fault targeting and
        error reporting alike."""
        return self.engine._query_rep_pair(q)

    def _fire(self, site: str, queries) -> None:
        faults = self.engine._faults
        if faults is not None:
            pairs = [
                p for p in (self._query_pair(q) for q in queries)
                if p is not None
            ]
            faults.fire(site, pairs or None)


class MsbfsRoute(TaxonomyRoute):
    """The multi-source rung: one bitmask-packed sweep per 64 distinct
    sources across the whole flush group (module docstring). Owns the
    ``bibfs_msbfs_breaker_state`` gauge the way mesh/blocked rungs own
    theirs; sweeps are noted in the ExecutableCache under
    ``placement_bucket_key(kind="msbfs")`` keys."""

    name = "msbfs"
    kind = "msbfs"

    def __init__(self, engine, *, retry, breaker, label: str):
        super().__init__(engine, retry=retry, breaker=breaker)
        self.sweeps = 0  # single-mutator: the flushing thread
        gauge = REGISTRY.gauge(
            "bibfs_msbfs_breaker_state",
            "msbfs-route circuit breaker (0=closed 1=half_open 2=open)",
            ("engine",),
        ).labels(engine=label)
        self.breaker_gauge = gauge
        # weakly bound through the route (registry cells themselves
        # are not weakref-able): a shared breaker must not pin a dead
        # engine's route — the mesh/blocked contract
        self_ref = weakref.ref(self)

        def _on_transition(state):
            route = self_ref()
            if route is None:
                return False
            route.breaker_gauge.set(BREAKER_STATE_CODES[state])
            return True

        breaker.add_listener(_on_transition)
        gauge.set(BREAKER_STATE_CODES[breaker.state])

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.query.msbfs import solve_multi_source

        with span("msbfs_batch", batch=len(queries)):
            self._fire("msbfs", queries)
            t0 = time.perf_counter()
            distinct = len({
                int(s) for q in queries for s in q.sources
            })
            sweeps = -(-distinct // MSBFS_WORD)
            # host-sweep "program" identity: padded word geometry per
            # graph — keyed apart from any device executable
            self.engine.exec_cache.note(placement_bucket_key(
                ("msbfs", ctx.n), kind="msbfs", shards=1,
                extra=(min(distinct, MSBFS_WORD),),
            ))
            results = solve_multi_source(
                ctx.n, ctx.row_ptr, ctx.col_ind, queries
            )
            self.sweeps += sweeps
            return results, None, t0

    def _fallback_one(self, rt, q, ctx):
        """Per-source host BFS — the independent machinery the packed
        sweep is measured against, availability-shaped."""
        from bibfs_tpu.query.types import MultiSourceResult
        from bibfs_tpu.solvers.serial import solve_serial_csr

        t0 = time.perf_counter()
        per = []
        best = None
        best_path = None
        for i, s in enumerate(q.sources):
            r = solve_serial_csr(
                ctx.n, ctx.row_ptr, ctx.col_ind, int(s), int(q.dst)
            )
            per.append(r.hops if r.found else None)
            if r.found and (best is None or r.hops < per[best]):
                best = i
                best_path = r.path
        return MultiSourceResult(
            found=best is not None,
            per_source=tuple(per),
            best=best,
            hops=per[best] if best is not None else None,
            path=best_path,
            time_s=time.perf_counter() - t0,
            sweeps=0,
        )

    def stats(self) -> dict:
        out = super().stats()
        out["sweeps"] = self.sweeps
        return out


class WeightedRoute(TaxonomyRoute):
    """The weighted rung: delta-stepping over bucketed frontiers,
    weights derived per (snapshot, seed) by the symmetric hash
    (cached on the flush runtime for the no-overlay case)."""

    name = "weighted"
    kind = "weighted"

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.query.weighted import delta_stepping

        with span("weighted_batch", batch=len(queries)):
            self._fire("weighted", queries)
            t0 = time.perf_counter()
            out = []
            for q in queries:
                w = self._weights(rt, ctx, int(q.weight_seed))
                out.append(delta_stepping(
                    ctx.n, ctx.row_ptr, ctx.col_ind, w,
                    int(q.src), int(q.dst),
                ))
            return out, None, t0

    def _weights(self, rt, ctx, seed: int):
        from bibfs_tpu.query.weighted import synthetic_weights

        if ctx.base:
            return rt.weights_for(seed, ctx.row_ptr, ctx.col_ind)
        # overlay-merged CSR: derive fresh (the merged shape is not
        # the snapshot's; memoizing it would alias across updates)
        return synthetic_weights(ctx.row_ptr, ctx.col_ind, seed)

    def _fallback_one(self, rt, q, ctx):
        """The binary-heap Dijkstra oracle — the independent
        implementation the property tests pin delta-stepping to."""
        from bibfs_tpu.query.types import WeightedResult
        from bibfs_tpu.query.weighted import dijkstra_numpy

        t0 = time.perf_counter()
        w = self._weights(rt, ctx, int(q.weight_seed))
        dist, parent = dijkstra_numpy(
            ctx.n, ctx.row_ptr, ctx.col_ind, w, int(q.src), int(q.dst)
        )
        found = bool(np.isfinite(dist[int(q.dst)]))
        path = None
        if found:
            path = [int(q.dst)]
            while path[-1] != int(q.src):
                path.append(int(parent[path[-1]]))
            path.reverse()
        return WeightedResult(
            found=found,
            dist=float(dist[int(q.dst)]) if found else None,
            hops=len(path) - 1 if found else None,
            path=path,
            time_s=time.perf_counter() - t0,
        )


class KShortestRoute(TaxonomyRoute):
    """The k-shortest rung: Yen's over the restricted-BFS machinery, a
    host-tier kind by nature (module docstring)."""

    name = "kshortest"
    kind = "kshortest"

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.query.kshortest import yen_k_shortest

        with span("kshortest_batch", batch=len(queries)):
            self._fire("kshortest", queries)
            t0 = time.perf_counter()
            out = [
                yen_k_shortest(
                    ctx.n, ctx.row_ptr, ctx.col_ind,
                    int(q.src), int(q.dst), int(q.k),
                )
                for q in queries
            ]
            return out, None, t0

    def _fallback_one(self, rt, q, ctx):
        from bibfs_tpu.query.kshortest import yen_k_shortest

        return yen_k_shortest(
            ctx.n, ctx.row_ptr, ctx.col_ind,
            int(q.src), int(q.dst), int(q.k),
        )


@guarded_by("_snap_lock", "_snaps")
class AsOfRoute(TaxonomyRoute):
    """The time-travel rung: reconstruct the graph as of a historical
    store version (``store/history.py`` — WAL + versioned manifests),
    cache the reconstructed CSR per (graph, version) for the engine's
    lifetime (history is immutable — a committed version's edge set
    never changes), and solve the inner queries against it on the
    host tier. The chaos seam is the reconstruction itself
    (``asof_replay``): the disk read + replay is what a dying disk
    breaks."""

    name = "asof"
    kind = "asof"

    #: reconstructed (n, row_ptr, col_ind) CSRs kept per engine — each
    #: costs one CSR, bounded to keep a version-scanning client from
    #: holding every historical graph in memory at once
    MAX_SNAPS = 8

    def __init__(self, engine, *, retry, breaker):
        super().__init__(engine, retry=retry, breaker=breaker)
        self._snap_lock = threading.Lock()
        self._snaps: OrderedDict = OrderedDict()
        self.replays = 0  # single-mutator: the flushing thread

    def launch(self, rt, queries, ctx=None):
        with span("asof_batch", batch=len(queries)):
            t0 = time.perf_counter()
            # group by version so each historical CSR reconstructs
            # once per batch — but results land back at their query's
            # INPUT position (a batch may mix versions)
            out: list = [None] * len(queries)
            by_version: dict[int, list] = {}
            for i, q in enumerate(queries):
                by_version.setdefault(int(q.version), []).append((i, q))
            for version, group in sorted(by_version.items()):
                try:
                    hist = self._historical(
                        rt, ctx, version, [q for _i, q in group]
                    )
                except QueryError as e:
                    if e.kind != "invalid":
                        raise
                    # an unknown/unprovable version is the CLIENT's
                    # input: it becomes those queries' per-slot error
                    # RESULT, never a route failure — raising it out
                    # of launch would burn retries and open the asof
                    # breaker on bad input, degrading valid traffic
                    for i, _q in group:
                        out[i] = e
                    continue
                for i, q in group:
                    out[i] = self._solve_inner(q.inner, hist)
            return out, None, t0

    def _historical(self, rt, ctx, version: int, queries) -> KindCtx:
        """The CSR as of ``version`` — cached per (graph, version);
        a miss fires the ``asof_replay`` chaos seam and pays the
        reconstruction, timed into
        ``bibfs_query_asof_replay_seconds``."""
        key = (ctx.name, version)
        with self._snap_lock:
            hist = self._snaps.get(key)
            if hist is not None:
                self._snaps.move_to_end(key)
                return hist
        self._fire("asof_replay", queries)
        t0 = time.perf_counter()
        snap = self._reconstruct(rt, ctx, version)
        row_ptr, col_ind = snap.csr()
        elapsed = time.perf_counter() - t0
        eng = self.engine
        eng._query_cells.asof_replay_gauge.set(elapsed)
        self.replays += 1
        hist = KindCtx(
            snap.n, row_ptr, col_ind, base=True, name=ctx.name,
            # historical results are cached under the historical
            # snapshot's OWN digest — immune to live-graph swaps
            graph_id=snap.digest,
        )
        with self._snap_lock:
            self._snaps[key] = hist
            self._snaps.move_to_end(key)
            while len(self._snaps) > self.MAX_SNAPS:
                self._snaps.popitem(last=False)
        return hist

    def _reconstruct(self, rt, ctx, version: int):
        store = self.engine._store
        if store is not None:
            try:
                return store.reconstruct_version(ctx.name, version)
            except ValueError as e:
                # an unknown/unprovable version is the CLIENT's input
                # being wrong (or history retention being off), not a
                # server failure: tag it invalid so retries don't burn
                # on it and health stays clean
                raise QueryError(
                    str(e), kind="invalid",
                ) from e
        # inline engine: the one immutable graph IS every version it
        # has — only its own stamp answers
        snap = rt.snapshot
        if version != snap.version:
            raise QueryError(
                f"as_of version {version} unknown: engine has no "
                f"store (inline graph is version {snap.version})",
                kind="invalid",
            )
        return snap

    def _solve_inner(self, q, hist: KindCtx):
        """One inner query against the historical CSR, on the host
        tier (no device table is ever built for a historical
        version — time-travel is a read path, not a serving tier)."""
        from bibfs_tpu.query.host import solve_query_csr

        return solve_query_csr(hist.n, hist.row_ptr, hist.col_ind, q)

    def _fallback_one(self, rt, q, ctx):
        """Per-query re-reconstruction with the chaos seam behind us —
        degraded time-travel pays the replay per query instead of per
        version group, but still answers exactly."""
        snap = self._reconstruct(rt, ctx, int(q.version))
        row_ptr, col_ind = snap.csr()
        from bibfs_tpu.query.host import solve_query_csr

        return solve_query_csr(snap.n, row_ptr, col_ind, q.inner)

    def stats(self) -> dict:
        out = super().stats()
        with self._snap_lock:
            out["historical_snapshots"] = len(self._snaps)
        out["replays"] = self.replays
        return out


def build_taxonomy_routes(engine, label: str) -> dict:
    """The kind-route set every engine carries (``build_routes`` calls
    this unconditionally — the taxonomy is part of the serving
    contract, not an opt-in), each rung with its OWN retry policy and
    circuit breaker. The device rungs ride along as ladder peers
    (serve/routes/taxonomy_device.py) — ineligible until the engine
    routes device at all, so a CPU-substrate engine's behavior is
    unchanged until it opts in."""
    from bibfs_tpu.serve.resilience import CircuitBreaker, RetryPolicy
    from bibfs_tpu.serve.routes.taxonomy_device import (
        build_taxonomy_device_routes,
    )

    routes = {
        "msbfs": MsbfsRoute(
            engine, retry=RetryPolicy(), breaker=CircuitBreaker(),
            label=label,
        ),
        "weighted": WeightedRoute(
            engine, retry=RetryPolicy(), breaker=CircuitBreaker(),
        ),
        "kshortest": KShortestRoute(
            engine, retry=RetryPolicy(), breaker=CircuitBreaker(),
        ),
        "asof": AsOfRoute(
            engine, retry=RetryPolicy(), breaker=CircuitBreaker(),
        ),
    }
    routes.update(build_taxonomy_device_routes(engine, label))
    # the whole-graph analytics kinds (host + blocked rungs) ride every
    # engine the same way — kind-dispatched, never from the pt ladder
    from bibfs_tpu.serve.routes.analytics import build_analytics_routes

    routes.update(build_analytics_routes(engine, label))
    return routes
