"""``route="device"`` — the single-device batched program as a Route.

The launch/finish split (enqueue one batched program; force + decode +
bank later) is the seam the pipelined engine overlaps, so the route
exposes exactly that: ``launch`` delegates to the engine's
``_device_launch`` (which pads the flush to a batch rung, resolves the
batch mode, and notes the compiled-program identity) and ``finish`` to
``_device_finish`` (forced value read, minor8 decode, result
materialization, forest banking) — both read the thread-bound flush
runtime, which is how the swap barrier reaches this route.

Eligibility is the calibrated batch-vs-latency crossover plus the
substrate check: batching exists to amortize the per-dispatch tax
(~67 ms through the tunneled TPU, ~9 µs on the CPU backend —
``calibration.json``), so on a CPU substrate the host route wins every
regime and this route stands aside unless ``device_batches=True``
forces it.
"""

from __future__ import annotations

from bibfs_tpu.serve.routes.base import Route


class DeviceRoute(Route):
    """The batched single-device dispatch rung of the ladder."""

    name = "device"
    is_dispatch = True

    def eligible(self, rt, pairs) -> bool:
        return (len(pairs) >= self.engine.flush_threshold
                and self.engine._use_device())

    def launch(self, rt, pairs):
        return self.engine._device_launch(pairs)

    def finish(self, out, fin, t0, pairs):
        return self.engine._device_finish(out, fin, t0, pairs)
