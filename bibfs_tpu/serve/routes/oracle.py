"""``route="oracle"`` — the landmark distance-oracle tier as a Route.

The consult itself (two int16 row reads over an immutable index) lives
in :mod:`bibfs_tpu.oracle`; this route is the dispatch seam: it answers
at SUBMIT time (no queueing, no solver), which is why it sits outside
the flush ladder — both engines consult it before the distance cache
and before the overlay route (a store oracle is only ever returned when
its index describes the CURRENT live graph, pending overlay included).
A non-exact consult arms the ticket's ``cutoff`` with the proven upper
bound for the host rungs.
"""

from __future__ import annotations

from bibfs_tpu.serve.routes.base import Route


class OracleRoute(Route):
    """Submit-time exact answering from the landmark index."""

    name = "oracle"

    def eligible(self, rt, pairs) -> bool:
        # consulted per ticket at submit time, never from the ladder
        return False

    def consult(self, ticket, graph_name) -> bool:
        """Consult the oracle tier for one submitted query. True =
        served exactly (``ticket.result`` set, ``route="oracle"``);
        False = fall through (with ``ticket.cutoff`` armed when the
        consult produced a usable upper bound)."""
        orc = self.engine._oracle_for(graph_name)
        if orc is None:
            return False
        ans = orc.consult(ticket.src, ticket.dst)
        if ans is None:
            return False
        if ans.result is not None:
            ticket.result = ans.result
            return True
        ticket.cutoff = ans.ub
        return False
