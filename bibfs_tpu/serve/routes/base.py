"""The pluggable Route seam — one dispatch contract for every way a
query batch can resolve.

Before this package the route ladder was hand-woven through both query
engines and ``serve/resilience.py``: the device path carried its own
retry/breaker loop (``QueryEngine._device_attempt``), the host path its
own bisection isolator, the overlay path two near-identical batch loops
(sync + pipelined), and adding a route meant re-threading all of it.
A :class:`Route` object owns one way of solving a ``(src, dst)`` batch
against a bound :class:`~bibfs_tpu.serve.engine._GraphRuntime`, plus
the failure policy that wraps it:

- ``eligible(rt, pairs)`` — the routing predicate (calibrated
  crossovers, substrate checks, batch-depth thresholds);
- ``launch(rt, pairs)`` / ``finish(out, fin, t0, pairs)`` — the
  two-stage solve seam. Dispatch-shaped routes (device, mesh) return a
  lazily-executing handle from ``launch`` so the pipelined engine can
  overlap batch k's ``finish`` with batch k+1's ``launch``; host-shaped
  routes solve in ``launch`` and make ``finish`` the identity.
- ``attempt(rt, pairs, cutoffs)`` — the resilient synchronous wrapper:
  bounded retries with backoff behind the route's own
  :class:`~bibfs_tpu.serve.resilience.CircuitBreaker`. Returns the
  batch results, or None when the route is unavailable (breaker open /
  retries exhausted) — the caller degrades down the fallback ladder.

The engines keep the orchestration (swap barriers, ticket resolution,
banking, the pipelined finish workers); routes own *how a batch
solves* and *when that way is worth trying*. ``oracle`` and ``overlay``
are routes too (submit-time consult / exact base+delta answering), so
every ``bibfs_queries_routed_total{route=...}`` label value now names a
Route object behind one seam.
"""

from __future__ import annotations

import time

from bibfs_tpu.solvers.api import BFSResult


class Route:
    """One way of resolving a query batch (module docstring).

    ``engine`` is the owning engine (routes live and die with it);
    ``retry``/``breaker`` are the route's failure policy (None = the
    route is not retried / not breaker-gated). ``is_dispatch`` marks
    routes whose ``launch`` returns a lazily-executing handle worth
    overlapping (device, mesh); the pipelined engine runs their
    ``finish`` on its worker thread.
    """

    name: str = "route"
    is_dispatch = False

    def __init__(self, engine, *, retry=None, breaker=None):
        self.engine = engine
        self.retry = retry
        self.breaker = breaker

    # ---- selection ---------------------------------------------------
    def eligible(self, rt, pairs) -> bool:
        """Whether this route should carry ``pairs`` against ``rt``
        right now (calibrated crossovers, substrate, batch depth). An
        ineligible route is skipped silently — it is a routing
        decision, not a failure."""
        return True

    # ---- the two-stage solve seam ------------------------------------
    def launch(self, rt, pairs):
        """Stage 1: start solving ``pairs``. Returns ``(out, fin, t0)``
        for :meth:`finish`. Dispatch routes only enqueue here."""
        raise NotImplementedError

    def finish(self, out, fin, t0, pairs) -> list[BFSResult]:
        """Stage 2: force execution and materialize per-query results
        (host-side work — the pipelined engine runs it on a worker)."""
        raise NotImplementedError

    def solve(self, rt, pairs, cutoffs=None) -> list[BFSResult]:
        """One synchronous launch+finish (no retry policy applied)."""
        out, fin, t0 = self.launch(rt, pairs)
        return self.finish(out, fin, t0, pairs)

    # ---- the resilient synchronous wrapper ---------------------------
    def attempt(self, rt, pairs, cutoffs=None) -> list[BFSResult] | None:
        """Bounded retries with backoff behind the route breaker —
        the generalization of the old ``QueryEngine._device_attempt``.
        Returns the batch results, or None when the route is
        unavailable (breaker open / retries exhausted) and the caller
        should degrade down the ladder. The fault-free fast path is one
        ``allow()``/``record_success()`` pair per batch."""
        breaker = self.breaker
        retry = self.retry
        if breaker is not None and not breaker.allow():
            return None
        n_try = 0
        try:
            while True:
                try:
                    results = self.solve(rt, pairs, cutoffs)
                except Exception:
                    if breaker is not None:
                        breaker.record_failure()
                    n_try += 1
                    # gate BEFORE counting/sleeping (exactly one allow()
                    # per launch, every True followed by a record): when
                    # this failure just opened the breaker there is no
                    # retry to count and no backoff worth blocking for
                    if (retry is not None and n_try < retry.attempts
                            and (breaker is None or breaker.allow())):
                        self._note_retry()
                        time.sleep(retry.delay_s(n_try - 1))
                        continue
                    return None
                if breaker is not None:
                    breaker.record_success()
                return results
        except BaseException:
            # an escape past the Exception handler (KeyboardInterrupt
            # mid-launch, or during the backoff sleep whose allow() is
            # already claimed) must not leave the admitting allow()
            # unrecorded — a leaked half-open probe claim makes allow()
            # return False forever and the route never recovers (an
            # extra record_failure after a counted one is harmless)
            if breaker is not None:
                breaker.record_failure()
            raise

    def _note_retry(self) -> None:
        self.engine._res_cells.retry_cell(self.name).inc()

    # ---- introspection -----------------------------------------------
    def stats(self) -> dict:
        out: dict = {"name": self.name, "dispatch": self.is_dispatch}
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        if self.retry is not None:
            out["retry"] = self.retry.snapshot()
        return out
