"""``route="mesh"`` across PROCESS boundaries — the pod-mesh rung.

:class:`PodMeshRoute` subclasses :class:`~bibfs_tpu.serve.routes.mesh.
MeshRoute` to drive the primary's half of the pod lockstep
(:mod:`bibfs_tpu.parallel.podmesh`): every mesh-routed batch is
broadcast to the worker processes, every process dispatches the
identical vertex-sharded SPMD program over the GLOBAL mesh, and the
bitpacked dual-frontier exchange crosses real process boundaries.

Two deliberate deviations from the single-process rung:

- **No dp sub-path.** The dp batch's global best array is sharded over
  the query mesh: in a multi-process job no process can address all of
  it, so ``_use_dp`` is pinned False and every pod batch takes the
  vertex-sharded program — whose best/meet/levels/edges outputs are
  REPLICATED (addressable on every host; ``tests/test_multihost.py``
  documents the split).
- **Replicated-only materialization.** The base route's
  ``_materialize_batch`` pulls ALL outputs to host, including the
  vertex-SHARDED parent planes — a crash across processes. The pod
  finish reads only the replicated outputs and returns path-less
  results (``BFSResult(found, hops, None, ...)``), which is exactly
  what the network front door serves anyway (found/hops; the REPL's
  path printing was never part of the wire contract).

Failure story: any pod control-plane fault (worker refused the digest,
died, timed out) raises :class:`~bibfs_tpu.parallel.podmesh.PodError`
out of launch/finish BEFORE or AFTER the collective — never inside it
(the join barrier, podmesh docstring) — and the engine's resilience
ladder re-runs the batch on the local single-device rungs: exact
answers at degraded throughput, the same degradation contract every
other rung honors. The ``done`` ack carries each worker's replicated
``best`` vector and finish asserts it equals the primary's — the
cross-process exactness gate runs on every served batch, not just in
the soak.
"""

from __future__ import annotations

import time

import numpy as np

from bibfs_tpu.parallel.podmesh import PodError
from bibfs_tpu.serve.buckets import bucket_batch, placement_bucket_key
from bibfs_tpu.serve.routes.mesh import MeshRoute
from bibfs_tpu.solvers.api import BFSResult


def _materialize_replicated(out, num: int, elapsed: float):
    """Per-query results from the REPLICATED outputs only (best, meet,
    levels, edges — indices 0/1/4/5 of the sharded program's output
    tuple); the sharded parent planes are never touched, so this works
    when they are not fully addressable (multi-process meshes)."""
    from bibfs_tpu.solvers.dense import INF32

    best = np.asarray(out[0])
    meet = np.asarray(out[1])
    levels = np.asarray(out[4])
    edges = np.asarray(out[5])
    results = []
    for i in range(num):
        b = int(best[i])
        if b >= int(INF32):
            results.append(BFSResult(
                False, None, None, None, elapsed,
                int(levels[i]), int(edges[i]),
            ))
        else:
            results.append(BFSResult(
                True, b, None, int(meet[i]), elapsed,
                int(levels[i]), int(edges[i]),
            ))
    return results


class PodMeshRoute(MeshRoute):
    """The multi-process mesh rung (module docstring). Same route name
    and metrics families as :class:`MeshRoute` — to the engine, the
    router and the dashboards it IS the mesh rung, just wider."""

    name = "mesh"
    is_dispatch = True

    def __init__(self, engine, cfg, vmesh, qmesh, *, retry, breaker,
                 label: str, pod, ack_timeout_s: float = 120.0):
        super().__init__(engine, cfg, vmesh, qmesh, retry=retry,
                         breaker=breaker, label=label)
        self._pod = pod
        self._ack_timeout_s = float(ack_timeout_s)

    def _use_dp(self, rt, pairs) -> bool:
        # dp's global best array is not fully addressable across
        # processes (module docstring): every pod batch goes sharded
        return False

    def _launch_sharded(self, rt, pairs):
        from bibfs_tpu.solvers import sharded as _sharded

        snap = rt.snapshot
        # heartbeat sweep FIRST: a worker that stopped heartbeating is
        # marked dead here, so the batch aborts via the join barrier
        # (PodError before the collective) instead of timing out inside
        # it — the engine's ladder then degrades to the local rungs
        self._pod.check_heartbeats()
        # broadcast the snapshot if the workers don't hold it yet (the
        # hot-swap seam: a store roll shows up here as a new digest),
        # building the primary's sharded graph BETWEEN the broadcast
        # and the ack barrier — device placement onto the global mesh
        # is collective, so primary and workers must build concurrently
        sg = self._pod.ensure_graph(
            snap, build=lambda: rt.mesh_graph(self),
            timeout=self._ack_timeout_s,
        )
        rung = min(bucket_batch(len(pairs)), self.engine.max_batch)
        padded = np.zeros((rung, 2), dtype=np.int64)
        padded[: len(pairs)] = pairs
        # the batch's sampled trace context (set by the engine's ladder
        # walk for the duration of this launch): the pod broadcast
        # carries it to every worker process
        seq = self._pod.post_solve(
            snap.digest, self.config.mode, padded, len(pairs),
            ctx=getattr(self.engine, "_launch_ctx", None),
        )
        # the join barrier, phase 1: every worker validated the batch
        # and parked for the verdict
        try:
            self._pod.await_phase(
                seq, "join", timeout=self._ack_timeout_s
            )
        except PodError:
            # phase 2, abort verdict: parked workers skip the
            # collective instead of entering it short the primary
            self._pod.abort_solve(seq)
            raise
        # phase 2, go verdict: only now does anyone enter the
        # collective
        self._pod.commit_solve(seq)
        self.engine.exec_cache.note(placement_bucket_key(
            rt.mesh_bucket_key, kind="mesh1d", shards=self.ndev,
            extra=(self.config.mode, rung),
        ))
        _p, dispatch = _sharded._batch_dispatch(
            sg, padded, self.config.mode
        )
        t0 = time.perf_counter()
        out = dispatch()
        return out, ("pod", seq, sg), t0

    def finish(self, out, fin, t0, pairs):
        from bibfs_tpu.obs.trace import span
        from bibfs_tpu.solvers.timing import force_scalar

        _kind, seq, sg = fin
        with span("pod_mesh_finish", batch=len(pairs)):
            eng = self.engine
            if eng._faults is not None:
                eng._faults.fire("mesh_finish", pairs)
            force_scalar(out)
            elapsed = time.perf_counter() - t0
            best = np.asarray(out[0])
            rung = int(best.shape[0])
            results = _materialize_replicated(
                out, rung, elapsed)[: len(pairs)]
            acks = self._pod.await_phase(
                seq, "done", timeout=self._ack_timeout_s
            )
            mine = [int(b) for b in best]
            for pidx, msg in acks.items():
                theirs = msg.get("best")
                if theirs is not None and list(theirs) != mine:
                    raise PodError(
                        f"pod worker {pidx} diverged on seq {seq}: "
                        f"its replicated best != the primary's"
                    )
            self._note_exchange(sg, rung, results)
            self.cells.batches["sharded"].inc()
            eng.counters["mesh_queries"] += len(pairs)
            return results


def attach_pod(engine, pod, *, ack_timeout_s: float = 120.0):
    """Swap a mesh-configured engine's mesh rung for the pod rung,
    reusing the existing rung's config, meshes, retry policy and
    breaker (so calibrated crossovers and breaker history carry over).
    Raises ValueError on an engine built without ``mesh=``."""
    base = engine.routes.get("mesh")
    if base is None:
        raise ValueError(
            "pod serving needs a mesh-configured engine (mesh=...)"
        )
    route = PodMeshRoute(
        engine, base.config, base.mesh, base.qmesh,
        retry=base.retry, breaker=base.breaker,
        label=engine.obs_label, pod=pod, ack_timeout_s=ack_timeout_s,
    )
    engine.routes["mesh"] = route
    return route
