"""Pluggable serving routes (see :mod:`bibfs_tpu.serve.routes.base`).

``build_routes`` is the one place the engines assemble their route set
and fallback ladder: oracle and overlay answer from their own seams
(submit time / the overlay-read barrier), the ladder proper runs
``mesh -> blocked -> device -> host`` with ``serial`` reached per-query
through the host isolator. The mesh and blocked rungs only exist when
the engine was configured with ``mesh=`` / ``blocked=`` — and then
each carries its OWN circuit breaker and retry policy, so a dead rung
degrades to the ones below it exactly the way a dead accelerator
degrades to the host ladder. When the engine runs adaptive routing
(``adaptive=``), the per-flush walk order over these rungs is the
:class:`~bibfs_tpu.serve.policy.AdaptiveRouter`'s decision; the static
ladder stays the default and the fallback semantics are unchanged.
"""

from __future__ import annotations

from bibfs_tpu.serve.routes.analytics import (
    AnalyticsBlockedRoute,
    AnalyticsHostRoute,
    build_analytics_routes,
)
from bibfs_tpu.serve.routes.base import Route
from bibfs_tpu.serve.routes.blocked import BlockedConfig, BlockedRoute
from bibfs_tpu.serve.routes.device import DeviceRoute
from bibfs_tpu.serve.routes.host import HostRoute, SerialRoute
from bibfs_tpu.serve.routes.mesh import MeshConfig, MeshRoute, mesh_prebuild
from bibfs_tpu.serve.routes.oracle import OracleRoute
from bibfs_tpu.serve.routes.overlay import OverlayRoute
from bibfs_tpu.serve.routes.taxonomy import (
    KIND_LADDERS,
    KIND_ROUTES,
    AsOfRoute,
    KindCtx,
    KindResultCache,
    KShortestRoute,
    MsbfsRoute,
    QueryKindCells,
    WeightedRoute,
    build_taxonomy_routes,
)
from bibfs_tpu.serve.routes.taxonomy_device import (
    KShortestDeviceRoute,
    MsbfsDeviceRoute,
    WeightedDeviceRoute,
)

__all__ = [
    "Route",
    "AnalyticsBlockedRoute",
    "AnalyticsHostRoute",
    "BlockedConfig",
    "BlockedRoute",
    "DeviceRoute",
    "HostRoute",
    "SerialRoute",
    "MeshConfig",
    "MeshRoute",
    "OracleRoute",
    "OverlayRoute",
    "KIND_LADDERS",
    "KIND_ROUTES",
    "AsOfRoute",
    "KindCtx",
    "KindResultCache",
    "KShortestRoute",
    "KShortestDeviceRoute",
    "MsbfsRoute",
    "MsbfsDeviceRoute",
    "QueryKindCells",
    "WeightedRoute",
    "WeightedDeviceRoute",
    "build_analytics_routes",
    "build_routes",
    "build_taxonomy_routes",
    "mesh_prebuild",
]


def build_routes(engine, mesh_cfg=None, mesh_pre=None, blocked_cfg=None):
    """The engine's route set and fallback ladder.

    ``mesh_cfg``/``mesh_pre`` come from the engine ctor's early
    validation (:func:`mesh_prebuild` runs BEFORE the store snapshot is
    pinned, so a bad mesh argument cannot leak a pin); ``blocked_cfg``
    adds the blocked rung ahead of device. Returns ``(routes, ladder)``
    — ``ladder`` is the ordered batch rungs (``host`` terminal);
    oracle/overlay/serial sit outside it.
    """
    from bibfs_tpu.serve.resilience import CircuitBreaker, RetryPolicy

    routes = {
        "oracle": OracleRoute(engine),
        "overlay": OverlayRoute(engine),
        "device": DeviceRoute(
            engine, retry=engine._retry, breaker=engine._breaker
        ),
        "host": HostRoute(engine),
        "serial": SerialRoute(engine),
    }
    # the taxonomy kind routes (msbfs/weighted/kshortest/asof) ride
    # every engine — kind-dispatched at flush time, never from the
    # point-to-point ladder below
    routes.update(build_taxonomy_routes(engine, engine.obs_label))
    ladder = ("device", "host")
    if blocked_cfg is not None:
        routes["blocked"] = BlockedRoute(
            engine, blocked_cfg,
            retry=RetryPolicy(), breaker=CircuitBreaker(),
            label=engine.obs_label,
        )
        ladder = ("blocked",) + ladder
    if mesh_cfg is not None:
        vmesh, qmesh = mesh_pre
        routes["mesh"] = MeshRoute(
            engine, mesh_cfg, vmesh, qmesh,
            retry=RetryPolicy(), breaker=CircuitBreaker(),
            label=engine.obs_label,
        )
        ladder = ("mesh",) + ladder
    return routes, ladder
