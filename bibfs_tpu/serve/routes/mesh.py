"""``route="mesh"`` — mesh-sharded serving over the device mesh.

The multichip solvers have passed 8-device dryruns since round 3 (1D
vertex-sharded, 2D 2x4, the dp-batch query mesh) and the bitpacked
frontier exchange measures ~8x fewer wire bytes than bool
(BENCH_r02.json), but until this route the query engines only ever
dispatched to one device. :class:`MeshRoute` puts the mesh behind the
same Route seam as every other rung, with two sub-paths chosen per
batch:

- **dp** (query-sharded, graph replicated): the flush's batch axis is
  sharded over the query mesh and each device runs the whole
  batch-minor search on its slice — zero collectives, so throughput
  scales with chips (``solvers/batch_minor.dp_batch_dispatch``). This
  is the throughput path; it is lane-efficient only once every shard's
  128-lane group fills, which is exactly the measured crossover
  (``dp_min_batch``, default ``ndev * 128``).
- **sharded** (vertex-sharded, 1D mesh): the graph's ELL rows are
  1D-sharded across the mesh (``solvers/sharded.ShardedGraph``) and the
  per-level frontier exchange crosses the ICI BITPACKED — uint32 words,
  32 vertices each, n/8 wire bytes instead of n bool bytes
  (``parallel/collectives.all_gather_bits_dual``). This is the
  graphs-bigger-than-one-device path (``shard_min_n``); the
  ``bibfs_mesh_exchange_bytes_total{encoding}`` cells account the
  packed payload against its bool counterfactual per served batch.

Below-crossover traffic is NOT a mesh failure: ``eligible()`` returns
False, the engine counts ``bibfs_mesh_crossover_reroutes_total`` and
the ladder falls through to the single-device rungs. The crossover
constants are calibrated per substrate (``calibration.json``, the
platform entry's ``mesh`` block — written by ``bench.py
--serve-mesh``); the committed CPU-substrate numbers put the dp
crossover at batch 1024 on graphs of ≥ 5000 vertices (measured 1.5-1.8x
the single-device device route there, bench_mesh.json).

Snapshot identity is untouched: the mesh route serves the SAME content
digest (the store/WAL/oracle machinery carries over), and only the
``ExecutableCache`` bucket keys grow the shard geometry
(:func:`bibfs_tpu.serve.buckets.placement_bucket_key`) so a mesh
program can never collide with a single-device program of the same
padded shape.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass

import numpy as np

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.obs.trace import span
from bibfs_tpu.serve.buckets import bucket_batch, placement_bucket_key
from bibfs_tpu.serve.resilience import BREAKER_STATE_CODES
from bibfs_tpu.serve.routes.base import Route

#: committed dryrun-substrate defaults, overridden by the calibrated
#: ``mesh`` block of the platform's calibration.json entry. dp_min_n is
#: the measured graph-size crossover at the lane-efficient batch depth
#: (bench_mesh.json: at n=3000 the dp mesh only reaches ~1.45x the
#: single-device route; at n>=10000 it clears 1.5x). shard_min_n keeps
#: the vertex-sharded path for graphs beyond comfortable single-device
#: residence — on the CPU dryrun substrate that path is for parity and
#: exchange accounting, not speed, so the default keeps it out of the
#: way until a deployment calibrates it down.
DEFAULT_DP_MIN_N = 5000
DEFAULT_SHARD_MIN_N = 1 << 20


@dataclass(frozen=True)
class MeshConfig:
    """Mesh-route configuration (``QueryEngine(mesh=...)``).

    ``devices`` — mesh size (None = every visible device);
    ``dp_min_batch`` / ``dp_min_n`` / ``shard_min_n`` — crossover
    overrides (None = the calibrated constants, see module docstring);
    ``dt8`` — force the int8-plane dp kernel on/off (None = auto: int8
    when the minor8 geometry fits, int32 otherwise);
    ``mode`` — the vertex-sharded path's collective schedule.
    """

    devices: int | None = None
    dp_min_batch: int | None = None
    dp_min_n: int | None = None
    shard_min_n: int | None = None
    dt8: bool | None = None
    mode: str = "sync"

    @classmethod
    def coerce(cls, mesh) -> "MeshConfig":
        """Normalize the engine's ``mesh=`` argument: a ready config,
        a device count, or ``"auto"`` (all visible devices)."""
        if isinstance(mesh, cls):
            return mesh
        if mesh == "auto":
            return cls()
        if isinstance(mesh, bool):  # bool is an int; reject explicitly
            raise ValueError(
                "mesh= takes a device count, 'auto', or a MeshConfig"
            )
        if isinstance(mesh, int):
            if mesh < 1:
                raise ValueError(f"mesh devices must be >= 1, got {mesh}")
            return cls(devices=mesh)
        raise ValueError(
            f"mesh= takes a device count, 'auto', or a MeshConfig; "
            f"got {mesh!r}"
        )


def mesh_calibration() -> dict:
    """The current platform's calibrated ``mesh`` crossover block
    (empty when absent — callers fall back to the committed
    defaults)."""
    from bibfs_tpu.utils.calibrate import load_calibration

    cal = load_calibration()
    if not cal:
        return {}
    block = cal.get("mesh")
    return block if isinstance(block, dict) else {}


class _MeshCells:
    """The mesh route's registry cells (stable names in README "Mesh
    serving"), minted at route construction so a /metrics scrape shows
    the families at zero before any mesh traffic."""

    def __init__(self, label: str):
        self.shards = REGISTRY.gauge(
            "bibfs_mesh_shards",
            "Devices in the serving mesh (0 = mesh route not configured)",
            ("engine",),
        ).labels(engine=label)
        batches = REGISTRY.counter(
            "bibfs_mesh_batches_total",
            "Mesh-route batch dispatches by sub-path (dp/sharded)",
            ("engine", "path"),
        )
        self.batches = {
            "dp": batches.labels(engine=label, path="dp"),
            "sharded": batches.labels(engine=label, path="sharded"),
        }
        exch = REGISTRY.counter(
            "bibfs_mesh_exchange_bytes_total",
            "Frontier-exchange wire bytes by encoding (packed = the "
            "bitpacked payload actually shipped; bool = the unpacked "
            "counterfactual)",
            ("engine", "encoding"),
        )
        self.exchange = {
            "packed": exch.labels(engine=label, encoding="packed"),
            "bool": exch.labels(engine=label, encoding="bool"),
        }
        self.breaker_gauge = REGISTRY.gauge(
            "bibfs_mesh_breaker_state",
            "Mesh-route circuit breaker (0=closed 1=half_open 2=open)",
            ("engine",),
        ).labels(engine=label)
        self.reroutes = REGISTRY.counter(
            "bibfs_mesh_crossover_reroutes_total",
            "Below-crossover batches routed to the single-device path",
            ("engine",),
        ).labels(engine=label)

    def snapshot(self) -> dict:
        return {
            "shards": self.shards.value,
            "batches": {k: c.value for k, c in self.batches.items()},
            "exchange_bytes": {
                k: c.value for k, c in self.exchange.items()
            },
            "crossover_reroutes": self.reroutes.value,
        }


def mesh_prebuild(cfg: MeshConfig):
    """Build the vertex mesh and query mesh for ``cfg`` — separated
    from :class:`MeshRoute` construction so the engine ctor can fail a
    bad device count BEFORE it pins a store snapshot (a post-pin raise
    would leak the pin)."""
    from bibfs_tpu.parallel.mesh import make_1d_mesh
    from bibfs_tpu.solvers.batch_minor import QUERY_AXIS

    vmesh = make_1d_mesh(cfg.devices)
    qmesh = make_1d_mesh(cfg.devices, axis=QUERY_AXIS)
    return vmesh, qmesh


@guarded_by("_lock", "_dt8_by_key")
class MeshRoute(Route):
    """The mesh-sharded rung of the fallback ladder (module
    docstring). Owns its own circuit breaker and retry policy — a dead
    mesh degrades to the single-device rungs, never to unavailability."""

    name = "mesh"
    is_dispatch = True

    def __init__(self, engine, cfg: MeshConfig, vmesh, qmesh, *,
                 retry, breaker, label: str):
        super().__init__(engine, retry=retry, breaker=breaker)
        self.config = cfg
        self.mesh = vmesh
        self.qmesh = qmesh
        self.ndev = int(vmesh.devices.size)
        from bibfs_tpu.solvers.batch_minor import LANES

        cal = mesh_calibration()
        try:
            cal_devs = int(cal.get("devices", -1))
        except (TypeError, ValueError):
            cal_devs = -1
        if cal_devs != self.ndev:
            # the crossover constants are mesh-size-specific (the dp
            # lane crossover is ndev * LANES by construction): a mesh
            # sized differently from the calibrating run falls back to
            # the committed defaults instead of inheriting a wrong
            # dp_min_batch
            cal = {}
        self.dp_min_batch = int(
            cfg.dp_min_batch if cfg.dp_min_batch is not None
            else cal.get("dp_min_batch", self.ndev * LANES)
        )
        self.dp_min_n = int(
            cfg.dp_min_n if cfg.dp_min_n is not None
            else cal.get("dp_min_n", DEFAULT_DP_MIN_N)
        )
        self.shard_min_n = int(
            cfg.shard_min_n if cfg.shard_min_n is not None
            else cal.get("shard_min_n", DEFAULT_SHARD_MIN_N)
        )
        self._lock = threading.Lock()
        # bucket key -> resolved dp plane dtype (True = int8): the
        # minor8 geometry probe raises per shape, and re-raising it on
        # every flush would turn a static property into per-batch cost
        self._dt8_by_key: dict = {}
        self.cells = _MeshCells(label)
        self.cells.shards.set(self.ndev)
        # weakly-bound breaker gauge listener, same contract as the
        # engine's device-breaker subscription: a shared breaker must
        # not pin dead cells (returning False unsubscribes)
        cells_ref = weakref.ref(self.cells)

        def _on_transition(state):
            cells = cells_ref()
            if cells is None:
                return False
            cells.breaker_gauge.set(BREAKER_STATE_CODES[state])
            return True

        breaker.add_listener(_on_transition)
        self.cells.breaker_gauge.set(BREAKER_STATE_CODES[breaker.state])

    # ---- selection ---------------------------------------------------
    def eligible(self, rt, pairs) -> bool:
        """Above-crossover only: dp once the batch fills the mesh's
        lane groups on a big-enough graph, sharded once the graph
        itself is mesh-scale. Anything below falls to the single-device
        rungs (counted as a crossover reroute by the engine)."""
        return rt.n >= self.shard_min_n or (
            len(pairs) >= self.dp_min_batch and rt.n >= self.dp_min_n
        )

    def _use_dp(self, rt, pairs) -> bool:
        # a mesh-scale graph (n >= shard_min_n) always takes the
        # vertex-sharded path: the dp sub-path replicates the full
        # table on every device, which is exactly what such graphs
        # cannot afford
        return (rt.n < self.shard_min_n
                and len(pairs) >= self.dp_min_batch
                and rt.n >= self.dp_min_n)

    # ---- the two-stage solve seam ------------------------------------
    def launch(self, rt, pairs):
        eng = self.engine
        with span("mesh_launch", batch=len(pairs), shards=self.ndev):
            if eng._faults is not None:
                eng._faults.fire("mesh", pairs)
            if self._use_dp(rt, pairs):
                return self._launch_dp(rt, pairs)
            return self._launch_sharded(rt, pairs)

    def _resolve_dt8(self, g, key, b_loc: int) -> bool:
        """Whether this graph/batch geometry runs the int8-plane dp
        kernel (the measured winner: the [n_pad, B] planes at int8 keep
        a shard's working set cache-resident). Explicit ``dt8`` config
        wins; auto probes the minor8 geometry once per bucket key."""
        if self.config.dt8 is not None:
            return self.config.dt8
        memo_key = (key, b_loc)
        with self._lock:
            hit = self._dt8_by_key.get(memo_key)
        if hit is not None:
            return hit
        from bibfs_tpu.solvers.batch_minor import _minor_geometry

        try:
            _minor_geometry(g, b_loc, True)
            fits = True
        except ValueError:
            fits = False
        with self._lock:
            self._dt8_by_key[memo_key] = fits
        return fits

    def _launch_dp(self, rt, pairs):
        from bibfs_tpu.solvers.batch_minor import (
            dp_batch_dispatch,
            pad_batch,
        )

        # the fine-ladder replicated table (NOT the geometric serving
        # bucket: buckets.dp_aligned_ell documents the measured why)
        g = rt.dp_graph()
        b_loc = pad_batch(-(-len(pairs) // self.ndev))
        dt8 = self._resolve_dt8(g, rt.dp_bucket_key, b_loc)
        self.engine.exec_cache.note(placement_bucket_key(
            rt.dp_bucket_key, kind="dp", shards=self.ndev,
            extra=("dt8" if dt8 else "i32", b_loc),
        ))
        arr = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
        _p, run, fin = dp_batch_dispatch(g, arr, self.qmesh, dt8)
        t0 = time.perf_counter()
        out = run()  # lazy on tunneled runtimes; finish forces the read
        return out, ("dp", fin, None), t0

    def _launch_sharded(self, rt, pairs):
        from bibfs_tpu.solvers import sharded as _sharded

        sg = rt.mesh_graph(self)
        rung = min(bucket_batch(len(pairs)), self.engine.max_batch)
        # pad to the batch rung with inert (0, 0) queries so arbitrary
        # queue depths reuse a handful of compiled mesh programs (the
        # vmapped program specializes on B; the single-device route
        # does the same)
        padded = np.zeros((rung, 2), dtype=np.int64)
        padded[: len(pairs)] = pairs
        self.engine.exec_cache.note(placement_bucket_key(
            rt.mesh_bucket_key, kind="mesh1d", shards=self.ndev,
            extra=(self.config.mode, rung),
        ))
        _p, dispatch = _sharded._batch_dispatch(
            sg, padded, self.config.mode
        )
        t0 = time.perf_counter()
        out = dispatch()
        return out, ("sharded", None, sg), t0

    def finish(self, out, fin, t0, pairs):
        from bibfs_tpu.solvers.dense import _materialize_batch
        from bibfs_tpu.solvers.timing import force_scalar

        kind, hook, sg = fin
        with span("mesh_finish", batch=len(pairs), path=kind):
            eng = self.engine
            if eng._faults is not None:
                eng._faults.fire("mesh_finish", pairs)
            force_scalar(out)  # lazy runtimes execute at the value read
            elapsed = time.perf_counter() - t0
            if kind == "dp":
                results = _materialize_batch(hook(out), len(pairs), elapsed)
            else:
                rung = int(np.asarray(out[0]).shape[0])
                results = _materialize_batch(out, rung, elapsed)[: len(pairs)]
                # account the PADDED rung: the vmapped program ships
                # every lane's plane each round, pad lanes included
                self._note_exchange(sg, rung, results)
            # counters are single-mutator here by construction: the
            # sync engine finishes on the flushing thread, the
            # pipelined engine on its one finish worker
            self.cells.batches[kind].inc()
            eng.counters["mesh_queries"] += len(pairs)
            return results

    def _note_exchange(self, sg, rung: int, results) -> None:
        """Account the sharded batch's frontier-exchange wire traffic:
        the lock-step program ships both sides' BITPACKED planes once
        per round (``all_gather_bits_dual``), so per round each of the
        ``rung`` query lanes (the PADDED batch — pad lanes ship their
        plane too) pays ``2 * ceil(n_loc/32) * 4`` bytes per device —
        against the ``2 * n_loc`` bool counterfactual the round-1
        exchange shipped. The dp path contributes nothing here: it has
        ZERO collectives, which is its whole point."""
        from bibfs_tpu.parallel.collectives import frontier_exchange_bytes

        n_loc = sg.n_pad // self.ndev
        rounds = max(
            (-(-r.levels // 2) for r in results if r.levels), default=0
        )
        lanes = rounds * rung * 2 * self.ndev
        self.cells.exchange["packed"].inc(
            lanes * frontier_exchange_bytes(n_loc, True)
        )
        self.cells.exchange["bool"].inc(
            lanes * frontier_exchange_bytes(n_loc, False)
        )

    # ---- introspection -----------------------------------------------
    def stats(self) -> dict:
        out = super().stats()
        out.update(self.cells.snapshot())
        out["crossover"] = {
            "dp_min_batch": self.dp_min_batch,
            "dp_min_n": self.dp_min_n,
            "shard_min_n": self.shard_min_n,
        }
        return out
