"""Device-tier rungs for the taxonomy query kinds — ``msbfs_device``
/ ``weighted_device`` / ``kshortest_device`` as peer ladder rungs
above their host-tier kinds.

PR 13 opened the kind-route seam with every non-point-to-point kind
solving on the HOST tier; these routes are the data-plane completion:
each kind's device solver (:mod:`bibfs_tpu.ops.msbfs_device`,
:mod:`bibfs_tpu.solvers.query_device`) behind the full resilience
contract the dispatch rungs carry — its own retry policy and circuit
breaker (mirrored into ``bibfs_query_device_breaker_state{engine,
kind}`` the way the mesh/blocked gauges mirror theirs), its own chaos
seam (``msbfs_device`` / ``weighted_device`` / ``kshortest_device`` in
:data:`bibfs_tpu.serve.faults.KNOWN_SITES`), and a place in the kind
ladder (:data:`bibfs_tpu.serve.routes.taxonomy.KIND_LADDERS`) walked
by ``QueryEngine._flush_kind``: a faulted/broken device rung degrades
to the existing host kind rung (counted in
``bibfs_route_fallbacks_total{from=<kind>_device,to=<kind>}``) with
zero lost tickets, exactly the way a dead accelerator degrades the
point-to-point ladder.

Eligibility is the device ladder's rule set: the engine must route
device at all (``_use_device()`` — substrate-auto, forced by
``device_batches=True``), the flush must be bound to a BASE snapshot
(device tables are built from snapshots; overlay-merged truth stays on
the host rungs), the layout plain ELL (hub tiers carry edges the mask
gather would miss), and the batch above the kind's calibrated
crossover — the ``queries`` block of the platform's calibration entry,
written by ``bench.py --serve-queries``, read through
:func:`queries_calibration`. Per-kind adaptive ladders
(``AdaptiveRouter.order(kind=)``) reorder the walk per graph digest on
top of the static gates, unchanged.
"""

from __future__ import annotations

import time
import weakref

from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.obs.trace import span
from bibfs_tpu.serve.buckets import placement_bucket_key
from bibfs_tpu.serve.resilience import BREAKER_STATE_CODES
from bibfs_tpu.serve.routes.taxonomy import TaxonomyRoute

#: committed crossover defaults, overridden by the calibrated
#: ``queries`` block of the platform's calibration entry (written by
#: ``bench.py --serve-queries``). msbfs: the jitted sweep's dispatch
#: overhead amortizes over distinct sources — below a handful the
#: NumPy sweep's zero-dispatch start wins. weighted/kshortest: the
#: per-query programs pay one dispatch per solve (kshortest one per
#: Yen iteration), measured worthwhile from the first query / any
#: multi-path request.
DEFAULT_MSBFS_DEVICE_MIN_SOURCES = 8
DEFAULT_WEIGHTED_DEVICE_MIN_BATCH = 1
DEFAULT_KSHORTEST_DEVICE_MIN_K = 2


def queries_calibration() -> dict:
    """The current platform's calibrated ``queries`` crossover block
    (empty when absent — callers fall back to the committed
    defaults)."""
    from bibfs_tpu.utils.calibrate import load_calibration

    cal = load_calibration()
    if not cal:
        return {}
    block = cal.get("queries")
    return block if isinstance(block, dict) else {}


class TaxonomyDeviceRoute(TaxonomyRoute):
    """Shared shape of the three device kind rungs: the substrate /
    snapshot-base / layout gates, the per-kind breaker gauge, and the
    ladder contract (an unavailable rung returns None from
    ``attempt`` and the kind degrades to its host rung — the device
    rungs never own a ``fallback`` of their own)."""

    def __init__(self, engine, *, retry, breaker, label: str):
        super().__init__(engine, retry=retry, breaker=breaker)
        gauge = REGISTRY.gauge(
            "bibfs_query_device_breaker_state",
            "Device-tier query-kind rung circuit breakers "
            "(0=closed 1=half_open 2=open)",
            ("engine", "kind"),
        ).labels(engine=label, kind=self.kind)
        self.breaker_gauge = gauge
        # weakly bound through the route (registry cells are not
        # weakref-able): a shared breaker must not pin a dead engine's
        # route — the mesh/blocked/msbfs contract
        self_ref = weakref.ref(self)

        def _on_transition(state):
            route = self_ref()
            if route is None:
                return False
            route.breaker_gauge.set(BREAKER_STATE_CODES[state])
            return True

        breaker.add_listener(_on_transition)
        gauge.set(BREAKER_STATE_CODES[breaker.state])

    def kind_eligible(self, rt, queries, ctx) -> bool:
        """The device ladder's gates, kind edition (module
        docstring); subclasses add their calibrated crossover."""
        if ctx is None or not ctx.base:
            return False  # overlay-merged truth: host rungs answer
        if not self.engine._use_device():
            return False
        if rt.layout != "ell":
            return False  # hub tiers carry edges the sweep would miss
        return self._crossover(queries)

    def _crossover(self, queries) -> bool:
        return True

    def _fallback_one(self, rt, q, ctx):
        raise NotImplementedError(
            "device kind rungs degrade to their host kind route"
        )


class MsbfsDeviceRoute(TaxonomyDeviceRoute):
    """The device multi-source rung: the whole flush's distinct
    sources ride ONE jitted multi-word sweep over the uploaded ELL
    table (:func:`bibfs_tpu.ops.msbfs_device.msbfs_plane_graph`),
    unpacked into the same per-query reads the host sweep serves."""

    name = "msbfs_device"
    kind = "msbfs"

    def __init__(self, engine, *, retry, breaker, label: str):
        super().__init__(engine, retry=retry, breaker=breaker,
                         label=label)
        cal = queries_calibration()
        self.min_sources = int(cal.get(
            "msbfs_min_sources", DEFAULT_MSBFS_DEVICE_MIN_SOURCES
        ))
        self.sweeps = 0  # single-mutator: the flushing thread

    def _crossover(self, queries) -> bool:
        distinct = len({int(s) for q in queries for s in q.sources})
        return distinct >= self.min_sources

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.ops.msbfs_device import (
            msbfs_plane_graph,
            plane_words,
        )
        from bibfs_tpu.query.msbfs import solve_multi_source

        with span("msbfs_device_batch", batch=len(queries)):
            self._fire("msbfs_device", queries)
            t0 = time.perf_counter()
            g = rt.graph  # the uploaded serving table (lazy build)
            distinct = len({int(s) for q in queries for s in q.sources})
            self.engine.exec_cache.note(placement_bucket_key(
                ("msbfs", g.n_pad, g.width), kind="msbfs_device",
                shards=1, extra=(plane_words(distinct),),
            ))

            def dist_fn(sources):
                return msbfs_plane_graph(g, sources)

            results = solve_multi_source(
                ctx.n, ctx.row_ptr, ctx.col_ind, queries,
                dist_fn=dist_fn,
            )
            self.sweeps += 1
            return results, None, t0

    def stats(self) -> dict:
        out = super().stats()
        out["sweeps"] = self.sweeps
        out["crossover"] = {"min_sources": self.min_sources}
        return out


class WeightedDeviceRoute(TaxonomyDeviceRoute):
    """The device weighted rung: delta-stepping as one jitted bucket-
    relaxation program per query
    (:func:`bibfs_tpu.solvers.query_device.delta_stepping_device`),
    the ELL-aligned weight tables memoized per (runtime, seed)."""

    name = "weighted_device"
    kind = "weighted"

    def __init__(self, engine, *, retry, breaker, label: str):
        super().__init__(engine, retry=retry, breaker=breaker,
                         label=label)
        cal = queries_calibration()
        self.min_batch = int(cal.get(
            "weighted_min_batch", DEFAULT_WEIGHTED_DEVICE_MIN_BATCH
        ))

    def _crossover(self, queries) -> bool:
        return len(queries) >= self.min_batch

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.solvers.query_device import delta_stepping_device

        with span("weighted_device_batch", batch=len(queries)):
            self._fire("weighted_device", queries)
            t0 = time.perf_counter()
            out = []
            for q in queries:
                seed = int(q.weight_seed)
                # ctx.base holds, so the flush CSR IS the snapshot CSR
                # and the memoized derivations line up
                w = rt.weights_for(seed, ctx.row_ptr, ctx.col_ind)
                tables = rt.weighted_device_tables(seed)
                self.engine.exec_cache.note(placement_bucket_key(
                    ("weighted", int(tables[0].shape[0]),
                     int(tables[0].shape[1])),
                    kind="weighted_device", shards=1,
                ))
                out.append(delta_stepping_device(
                    ctx.n, ctx.row_ptr, ctx.col_ind, w, tables,
                    int(q.src), int(q.dst),
                ))
            return out, None, t0

    def stats(self) -> dict:
        out = super().stats()
        out["crossover"] = {"min_batch": self.min_batch}
        return out


class KShortestDeviceRoute(TaxonomyDeviceRoute):
    """The device k-shortest rung: Yen's with each iteration's spur
    candidates batched through ONE restricted-BFS device program
    (:func:`bibfs_tpu.solvers.query_device.restricted_batch_paths`),
    per-candidate node masks on the plane, banned spur edges folded
    into the seeding — paths IDENTICAL to the host rung's by the
    shared canonical descent."""

    name = "kshortest_device"
    kind = "kshortest"

    def __init__(self, engine, *, retry, breaker, label: str):
        super().__init__(engine, retry=retry, breaker=breaker,
                         label=label)
        cal = queries_calibration()
        self.min_k = int(cal.get(
            "kshortest_min_k", DEFAULT_KSHORTEST_DEVICE_MIN_K
        ))

    def _crossover(self, queries) -> bool:
        # k=1 has no spur candidates to batch — nothing for the
        # device program to amortize
        return any(int(q.k) >= self.min_k for q in queries)

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.query.kshortest import yen_k_shortest
        from bibfs_tpu.solvers.query_device import restricted_batch_paths

        with span("kshortest_device_batch", batch=len(queries)):
            self._fire("kshortest_device", queries)
            t0 = time.perf_counter()
            g = rt.graph
            self.engine.exec_cache.note(placement_bucket_key(
                ("kshortest", g.n_pad, g.width),
                kind="kshortest_device", shards=1,
            ))
            out = []
            for q in queries:
                dst = int(q.dst)

                def spur_batch(cands, _dst=dst):
                    return restricted_batch_paths(
                        g, ctx.n, ctx.row_ptr, ctx.col_ind, _dst, cands
                    )

                out.append(yen_k_shortest(
                    ctx.n, ctx.row_ptr, ctx.col_ind,
                    int(q.src), dst, int(q.k),
                    spur_batch=spur_batch,
                ))
            return out, None, t0

    def stats(self) -> dict:
        out = super().stats()
        out["crossover"] = {"min_k": self.min_k}
        return out


def build_taxonomy_device_routes(engine, label: str) -> dict:
    """The device kind rungs every engine carries (ladder peers of the
    host kind routes — ineligible until the engine routes device at
    all), each with its OWN retry policy and circuit breaker."""
    from bibfs_tpu.serve.resilience import CircuitBreaker, RetryPolicy

    return {
        "msbfs_device": MsbfsDeviceRoute(
            engine, retry=RetryPolicy(), breaker=CircuitBreaker(),
            label=label,
        ),
        "weighted_device": WeightedDeviceRoute(
            engine, retry=RetryPolicy(), breaker=CircuitBreaker(),
            label=label,
        ),
        "kshortest_device": KShortestDeviceRoute(
            engine, retry=RetryPolicy(), breaker=CircuitBreaker(),
            label=label,
        ),
    }
