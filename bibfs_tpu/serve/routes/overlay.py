"""``route="overlay"`` — exact answering while live edge updates are
pending, as a Route.

While a graph has a pending delta overlay, queries answer exactly
against base+delta on the host (:meth:`DeltaOverlay.solve`), isolated
per query, and the distance cache stands aside — its entries describe
the base snapshot, not the overlaid graph. Both engines used to carry
their own copy of this loop (sync ``_flush_overlay`` + pipelined
``_launch_overlay``); the route is now the ONE implementation, yielding
per-key outcomes so each engine applies its own ticket-resolution
mechanics (inline result fields vs. finish-ticket broadcasts).
"""

from __future__ import annotations

from bibfs_tpu.serve.resilience import to_query_error
from bibfs_tpu.serve.routes.base import Route


class OverlayRoute(Route):
    """Exact base+delta answering for graphs with pending updates."""

    name = "overlay"

    def eligible(self, rt, pairs) -> bool:
        # the engines route to the overlay from the overlay-read seam
        # (ordering vs the snapshot pin is load-bearing; see
        # QueryEngine._flush_graph), never from the fallback ladder
        return False

    def solve_iter(self, overlay, keys):
        """Solve each ``(src, dst)`` key against base+delta, yielding
        ``(key, BFSResult | QueryError)`` — failure is isolated per
        query, the batch never sinks. One O(delta) correction capture
        serves the whole batch."""
        corr = overlay.correction()
        for key in keys:
            try:
                res = overlay.solve(*key, correction=corr)
            except Exception as exc:
                yield key, to_query_error(exc, key)
                continue
            yield key, res
