"""``route="blocked"`` — MXU-native blocked-adjacency expansion as a
peer rung of the fallback ladder (``blocked -> device -> host``).

The compute story lives in ``graph/blocked.py`` /
``ops/blocked_expand.py`` / the blocked bodies in ``solvers/dense.py``
+ ``solvers/batch_minor.py``: a flush's whole ``[n_pad, 2B]`` dual-side
frontier plane advances per level as masked block matmuls over the
tiled int8 adjacency — the MXU's native workload where the ELL device
route issues element-at-a-time gathers, and measured 1.4-8x the device
route on dense-ish and grid graphs on the CPU substrate too
(bench_blocked.json; the plane dtype is resolved per substrate,
``ops/blocked_expand.resolve_plane_dtype``).

Routing: the blocked table trades arithmetic for locality, so it loses
on graphs whose tile structure is NOT compact (high-diameter sparse
random graphs light up nearly every tile at ~3 edges each). The static
gate is the candidate-waste ratio — stored tile candidates per true
directed edge — under ``waste_cap``, plus the batch crossover and the
working-set fit; all three are calibrated (``calibration.json``, the
platform entry's ``blocked`` block, written by ``bench.py
--serve-blocked``) and the per-graph ordering on top of the static
gate is owned by the :class:`~bibfs_tpu.serve.policy.AdaptiveRouter`
when the engine runs adaptive. The route carries its own circuit
breaker and retry policy — a broken blocked rung degrades to
device/host exactly like a dead mesh — and its own chaos sites
(``blocked`` / ``blocked_finish``).

Executable identity: blocked programs are keyed through
``placement_bucket_key(kind="blocked")`` over the blocked shape key
(``graph/blocked.blocked_bucket_key``), so a blocked program can never
count as a hit on a device or mesh executable of the same padded
vertex shape.
"""

from __future__ import annotations

import time
import weakref
from dataclasses import dataclass

import numpy as np

from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.obs.trace import span
from bibfs_tpu.serve.buckets import bucket_batch, placement_bucket_key
from bibfs_tpu.serve.resilience import BREAKER_STATE_CODES
from bibfs_tpu.serve.routes.base import Route

#: committed defaults, overridden by the calibrated ``blocked`` block
#: of the platform's calibration.json entry (written by the soak).
#: min_batch: the plane layout pads to 128 lanes per side, so the
#: measured win starts once a flush fills a lane group. waste_cap:
#: stored tile candidates per true directed edge — the measured wins
#: (grid ~99, dense-ish gnp ~32-96) sit under 128; the sparse random
#: regime where blocked loses badly sits in the thousands.
DEFAULT_BLOCKED_MIN_BATCH = 128
DEFAULT_BLOCKED_WASTE_CAP = 128.0


@dataclass(frozen=True)
class BlockedConfig:
    """Blocked-route configuration (``QueryEngine(blocked=...)``).

    ``min_batch`` / ``waste_cap`` override the calibrated crossover
    constants (None = calibration, else the committed defaults);
    ``dt`` forces the frontier-plane dtype (None = auto per substrate:
    int8 on the MXU, f32 on the CPU dryrun)."""

    min_batch: int | None = None
    waste_cap: float | None = None
    dt: str | None = None

    @classmethod
    def coerce(cls, blocked) -> "BlockedConfig":
        if isinstance(blocked, cls):
            return blocked
        if blocked is True:
            return cls()
        raise ValueError(
            f"blocked= takes True or a BlockedConfig; got {blocked!r}"
        )


def blocked_calibration() -> dict:
    """The current platform's calibrated ``blocked`` crossover block
    (empty when absent — callers fall back to the committed
    defaults)."""
    from bibfs_tpu.utils.calibrate import load_calibration

    cal = load_calibration()
    if not cal:
        return {}
    block = cal.get("blocked")
    return block if isinstance(block, dict) else {}


class _BlockedCells:
    """The blocked route's registry cells (stable names in README
    "Blocked expansion & adaptive routing"), minted at route
    construction so a /metrics scrape shows the family at zero before
    any blocked traffic."""

    def __init__(self, label: str):
        self.batches = REGISTRY.counter(
            "bibfs_blocked_batches_total",
            "Blocked-route batch dispatches (masked block-matmul "
            "expansion)",
            ("engine",),
        ).labels(engine=label)
        self.breaker_gauge = REGISTRY.gauge(
            "bibfs_blocked_breaker_state",
            "Blocked-route circuit breaker (0=closed 1=half_open 2=open)",
            ("engine",),
        ).labels(engine=label)

    def snapshot(self) -> dict:
        return {"batches": self.batches.value}


class BlockedRoute(Route):
    """The MXU-tile rung of the fallback ladder (module docstring).
    Owns its own circuit breaker and retry policy — a broken blocked
    rung degrades to the single-device rungs, never to
    unavailability."""

    name = "blocked"
    is_dispatch = True

    def __init__(self, engine, cfg: BlockedConfig, *, retry, breaker,
                 label: str):
        super().__init__(engine, retry=retry, breaker=breaker)
        from bibfs_tpu.ops.blocked_expand import resolve_plane_dtype

        self.config = cfg
        cal = blocked_calibration()
        self.min_batch = int(
            cfg.min_batch if cfg.min_batch is not None
            else cal.get("min_batch", DEFAULT_BLOCKED_MIN_BATCH)
        )
        self.waste_cap = float(
            cfg.waste_cap if cfg.waste_cap is not None
            else cal.get("waste_cap", DEFAULT_BLOCKED_WASTE_CAP)
        )
        self.dt = resolve_plane_dtype(cfg.dt)
        self.cells = _BlockedCells(label)
        # weakly-bound breaker gauge listener, the mesh route's exact
        # contract: a shared breaker must not pin dead cells
        cells_ref = weakref.ref(self.cells)

        def _on_transition(state):
            cells = cells_ref()
            if cells is None:
                return False
            cells.breaker_gauge.set(BREAKER_STATE_CODES[state])
            return True

        breaker.add_listener(_on_transition)
        self.cells.breaker_gauge.set(BREAKER_STATE_CODES[breaker.state])

    # ---- selection ---------------------------------------------------
    def eligible(self, rt, pairs) -> bool:
        """Above the batch crossover, on a graph whose tile structure
        is compact enough to pay for itself, within the working-set
        fit. The meta check reads counts only — the blocked table
        itself is built lazily on the first routed flush."""
        if len(pairs) < self.min_batch:
            return False
        from bibfs_tpu.graph.blocked import TILE
        from bibfs_tpu.ops.blocked_expand import blocked_fits

        nblocks, bwidth, _nnz = rt.blocked_meta()
        edges2 = 2 * rt.snapshot.num_edges
        if edges2 == 0:
            return False
        waste = bwidth * TILE * nblocks * TILE / edges2
        if waste > self.waste_cap:
            return False
        return blocked_fits(
            nblocks, bwidth, bucket_batch(len(pairs)),
            itemsize=self.dt.itemsize,
        )

    # ---- the two-stage solve seam ------------------------------------
    def launch(self, rt, pairs):
        from bibfs_tpu.solvers.batch_minor import blocked_batch_dispatch

        with span("blocked_launch", batch=len(pairs)):
            eng = self.engine
            if eng._faults is not None:
                eng._faults.fire("blocked", pairs)
            g = rt.blocked_graph()
            rung = min(bucket_batch(len(pairs)), eng.max_batch)
            # pad to the batch rung with inert (0, 0) queries so every
            # queue depth reuses a handful of compiled blocked programs
            padded = np.zeros((rung, 2), dtype=np.int64)
            padded[: len(pairs)] = pairs
            eng.exec_cache.note(placement_bucket_key(
                rt.blocked_bucket_key, kind="blocked", shards=1,
                extra=(self.dt.name, rung),
            ))
            _p, thunk = blocked_batch_dispatch(g, padded, dt=self.dt)
            t0 = time.perf_counter()
            out = thunk()  # lazy on tunneled runtimes; finish forces
            return out, rung, t0

    def finish(self, out, rung, t0, pairs):
        from bibfs_tpu.solvers.dense import _materialize_blocked_batch
        from bibfs_tpu.solvers.timing import force_scalar

        with span("blocked_finish", batch=len(pairs)):
            eng = self.engine
            if eng._faults is not None:
                eng._faults.fire("blocked_finish", pairs)
            force_scalar(out)  # lazy runtimes execute at the value read
            elapsed = time.perf_counter() - t0
            # the bound flush runtime's memoized CSR carries the path
            # walk — the same snapshot the planes were solved on
            csr = eng._current_rt().snapshot.csr()
            results = _materialize_blocked_batch(
                out, pairs, elapsed, *csr
            )
            # single-mutator by construction (sync: flushing thread;
            # pipelined: the one finish worker), like the mesh cells
            self.cells.batches.inc()
            eng.counters["blocked_queries"] += len(pairs)
            return results

    # ---- introspection -----------------------------------------------
    def stats(self) -> dict:
        out = super().stats()
        out.update(self.cells.snapshot())
        out["crossover"] = {
            "min_batch": self.min_batch,
            "waste_cap": self.waste_cap,
            "plane_dtype": self.dt.name,
        }
        return out
