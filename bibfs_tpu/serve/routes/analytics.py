"""The whole-graph analytics rungs — ``sssp`` / ``pagerank`` /
``components`` / ``triangles`` through the kind ladder.

Each analytics kind is served exactly like the PR 13/14 taxonomy
kinds: a host-tier primary (the CSR semiring iteration,
:mod:`bibfs_tpu.analytics.semiring`) with its own retry policy and
circuit breaker, a per-query-isolated terminal ``fallback``, and a
BLOCKED rung above it (:mod:`bibfs_tpu.ops.semiring_plane` over the
``BlockedGraph`` tile tables) that an adaptive per-digest ladder
reorders and a faulted device degrades out of with zero lost tickets.

The blocked rungs differ from the device kind rungs in one gate: they
do NOT require ``_use_device()`` — the blocked semiring product is the
same jitted program on the CPU substrate (f32 planes, the
``blocked_expand`` measurement) and wins on dense-ish graphs there
too, so eligibility is snapshot-base + ELL layout + the tile-table
budgets + an EXACTNESS bound (integer-valued planes stay exact in f32
below 2^24) + the calibrated ``analytics`` crossover block
(``bench.py --serve-analytics`` writes it; committed defaults below).

Chaos seams: every analytics launch fires ``analytics`` going in and
``analytics_finish`` on the way out (both rungs — the seam is the
kind, not the tier), so one spec line degrades the whole tier to its
fallbacks. Metrics: ``bibfs_analytics_rounds_total{engine,kind}``
(relaxation sweeps / power iterations / label rounds / column chunks)
and ``bibfs_analytics_breaker_state{engine,kind}`` for the blocked
rungs, all minted at route-set construction.
"""

from __future__ import annotations

import time
import weakref

import numpy as np

from bibfs_tpu.graph.blocked import TILE as TILE_EDGE
from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.obs.trace import span
from bibfs_tpu.serve.buckets import placement_bucket_key
from bibfs_tpu.serve.resilience import BREAKER_STATE_CODES
from bibfs_tpu.serve.routes.taxonomy import TaxonomyRoute

#: committed host->blocked crossovers (edge counts), overridden by the
#: calibrated ``analytics`` block (``bench.py --serve-analytics``).
#: The blocked fixpoints pay one dispatch + (first time) one compile;
#: below a few thousand edges the NumPy scatter iteration wins.
DEFAULT_ANALYTICS_MIN_EDGES = {
    "sssp": 4000,
    "pagerank": 4000,
    "components": 4000,
    "triangles": 2000,
}

#: exactness bound for float32 planes: distances / labels / counts are
#: integer-valued and exact below 2^24
_F32_EXACT = 1 << 24

#: triangle column-chunk width (static — one compiled program per graph)
_TRI_CHUNK = 256


def analytics_calibration() -> dict:
    """The current platform's calibrated ``analytics`` crossover block
    (empty when absent — committed defaults apply)."""
    from bibfs_tpu.utils.calibrate import load_calibration

    cal = load_calibration()
    if not cal:
        return {}
    block = cal.get("analytics")
    return block if isinstance(block, dict) else {}


def _rounds_cell(label: str, kind: str):
    return REGISTRY.counter(
        "bibfs_analytics_rounds_total",
        "Whole-graph analytics iteration rounds (Bellman sweeps, "
        "power iterations, label-propagation rounds, triangle column "
        "chunks), by kind",
        ("engine", "kind"),
    ).labels(engine=label, kind=kind)


class AnalyticsHostRoute(TaxonomyRoute):
    """Shared shape of the four host-tier analytics rungs: the CSR
    semiring iteration behind ``Route.attempt``, the ``analytics`` /
    ``analytics_finish`` chaos seams, and a per-query-isolated
    fallback over the same single-query machinery."""

    def __init__(self, engine, *, retry, breaker, label: str):
        super().__init__(engine, retry=retry, breaker=breaker)
        self.rounds_cell = _rounds_cell(label, self.kind)

    def launch(self, rt, queries, ctx=None):
        with span(f"{self.kind}_batch", batch=len(queries)):
            self._fire("analytics", queries)
            t0 = time.perf_counter()
            out = self._solve_batch(rt, queries, ctx, t0)
            self._fire("analytics_finish", queries)
            return out, None, t0

    def _solve_batch(self, rt, queries, ctx, t0):
        raise NotImplementedError

    def _weights(self, rt, ctx, seed: int):
        from bibfs_tpu.query.weighted import synthetic_weights

        if ctx.base:
            return rt.weights_for(seed, ctx.row_ptr, ctx.col_ind)
        return synthetic_weights(ctx.row_ptr, ctx.col_ind, seed)


class SsspRoute(AnalyticsHostRoute):
    """(min, +) Bellman sweeps to fixpoint; a flush's same-seed
    sources batch into ONE multi-column plane (the landmarks shape)."""

    name = "sssp"
    kind = "sssp"

    def _solve_batch(self, rt, queries, ctx, t0):
        from bibfs_tpu.analytics.queries import SsspResult
        from bibfs_tpu.analytics.semiring import host_sssp

        by_seed: dict[int, list] = {}
        for i, q in enumerate(queries):
            by_seed.setdefault(int(q.weight_seed), []).append((i, q))
        out: list = [None] * len(queries)
        for seed, group in sorted(by_seed.items()):
            w = self._weights(rt, ctx, seed)
            dist, rounds = host_sssp(
                ctx.n, ctx.row_ptr, ctx.col_ind, w,
                [int(q.source) for _i, q in group],
            )
            self.rounds_cell.inc(int(rounds))
            for col, (i, _q) in enumerate(group):
                d = dist[:, col]
                out[i] = SsspResult(
                    found=True, dist=d,
                    reached=int(np.isfinite(d).sum()),
                    rounds=int(rounds),
                    time_s=time.perf_counter() - t0,
                )
        return out

    def _fallback_one(self, rt, q, ctx):
        from bibfs_tpu.analytics.queries import SsspResult
        from bibfs_tpu.analytics.semiring import host_sssp

        t0 = time.perf_counter()
        w = self._weights(rt, ctx, int(q.weight_seed))
        dist, rounds = host_sssp(
            ctx.n, ctx.row_ptr, ctx.col_ind, w, [int(q.source)]
        )
        d = dist[:, 0]
        return SsspResult(
            found=True, dist=d, reached=int(np.isfinite(d).sum()),
            rounds=int(rounds), time_s=time.perf_counter() - t0,
        )


class PageRankRoute(AnalyticsHostRoute):
    """(+, x) damped power iteration with L1-tolerance termination."""

    name = "pagerank"
    kind = "pagerank"

    def _solve_batch(self, rt, queries, ctx, t0):
        return [self._fallback_one(rt, q, ctx) for q in queries]

    def _fallback_one(self, rt, q, ctx):
        from bibfs_tpu.analytics.queries import PageRankResult
        from bibfs_tpu.analytics.semiring import host_pagerank

        t0 = time.perf_counter()
        ranks, iters, delta = host_pagerank(
            ctx.n, ctx.row_ptr, ctx.col_ind,
            damping=float(q.damping), tol=float(q.tol),
            max_iters=int(q.max_iters),
        )
        self.rounds_cell.inc(int(iters))
        return PageRankResult(
            found=ctx.n > 0, ranks=ranks, iters=int(iters),
            delta=float(delta), time_s=time.perf_counter() - t0,
        )


class ComponentsRoute(AnalyticsHostRoute):
    """Min-label propagation to fixpoint."""

    name = "components"
    kind = "components"

    def _solve_batch(self, rt, queries, ctx, t0):
        return [self._fallback_one(rt, q, ctx) for q in queries]

    def _fallback_one(self, rt, q, ctx):
        from bibfs_tpu.analytics.queries import ComponentsResult
        from bibfs_tpu.analytics.semiring import host_components

        t0 = time.perf_counter()
        labels, count, rounds = host_components(
            ctx.n, ctx.row_ptr, ctx.col_ind
        )
        self.rounds_cell.inc(int(rounds))
        return ComponentsResult(
            found=True, labels=labels, count=int(count),
            rounds=int(rounds), time_s=time.perf_counter() - t0,
        )


class TrianglesRoute(AnalyticsHostRoute):
    """The masked popcount matmul count, column-chunked."""

    name = "triangles"
    kind = "triangles"

    def _solve_batch(self, rt, queries, ctx, t0):
        return [self._fallback_one(rt, q, ctx) for q in queries]

    def _fallback_one(self, rt, q, ctx):
        from bibfs_tpu.analytics.queries import TrianglesResult
        from bibfs_tpu.analytics.semiring import host_triangles

        t0 = time.perf_counter()
        count, chunks = host_triangles(ctx.n, ctx.row_ptr, ctx.col_ind)
        self.rounds_cell.inc(int(chunks))
        return TrianglesResult(
            found=True, count=int(count),
            time_s=time.perf_counter() - t0,
        )


class AnalyticsBlockedRoute(TaxonomyRoute):
    """Shared shape of the blocked analytics rungs: tile-table gates +
    calibrated crossover, the per-kind breaker gauge, and the ladder
    contract (an unavailable rung degrades to the host kind rung — no
    ``fallback`` of its own)."""

    #: extra resident bytes per int8 table byte (sssp adds the f32
    #: weight table at 4x)
    TABLE_SCALE = 1

    def __init__(self, engine, *, retry, breaker, label: str):
        super().__init__(engine, retry=retry, breaker=breaker)
        self.rounds_cell = _rounds_cell(label, self.kind)
        gauge = REGISTRY.gauge(
            "bibfs_analytics_breaker_state",
            "Blocked analytics rung circuit breakers "
            "(0=closed 1=half_open 2=open)",
            ("engine", "kind"),
        ).labels(engine=label, kind=self.kind)
        self.breaker_gauge = gauge
        # weakly bound through the route (registry cells are not
        # weakref-able) — the mesh/blocked/msbfs contract
        self_ref = weakref.ref(self)

        def _on_transition(state):
            route = self_ref()
            if route is None:
                return False
            route.breaker_gauge.set(BREAKER_STATE_CODES[state])
            return True

        breaker.add_listener(_on_transition)
        gauge.set(BREAKER_STATE_CODES[breaker.state])
        cal = analytics_calibration()
        self.min_edges = int(cal.get(
            f"{self.kind}_min_edges",
            DEFAULT_ANALYTICS_MIN_EDGES[self.kind],
        ))

    def kind_eligible(self, rt, queries, ctx) -> bool:
        if ctx is None or not ctx.base:
            return False  # overlay-merged truth: host rungs answer
        if getattr(rt, "layout", None) != "ell":
            return False
        meta = getattr(rt, "blocked_meta", None)
        if meta is None:
            return False
        nblocks, bwidth, _nnz = rt.blocked_meta()
        if nblocks * TILE_EDGE >= _F32_EXACT:
            return False  # f32 planes would lose integer exactness
        from bibfs_tpu.ops.blocked_expand import BLOCKED_TAB_BUDGET_BYTES

        tab_bytes = nblocks * bwidth * TILE_EDGE * TILE_EDGE
        if tab_bytes * self.TABLE_SCALE > BLOCKED_TAB_BUDGET_BYTES:
            return False
        num_edges = int(ctx.col_ind.size) // 2
        return num_edges >= self.min_edges

    def _note_exec(self, nblocks: int, bwidth: int, extra=()):
        self.engine.exec_cache.note(placement_bucket_key(
            ("analytics", nblocks, bwidth),
            kind=f"{self.kind}_blocked", shards=1, extra=tuple(extra),
        ))

    def _fallback_one(self, rt, q, ctx):
        raise NotImplementedError(
            "blocked analytics rungs degrade to their host kind route"
        )

    def stats(self) -> dict:
        out = super().stats()
        out["crossover"] = {"min_edges": self.min_edges}
        return out


class SsspBlockedRoute(AnalyticsBlockedRoute):
    """Multi-source (min, +) fixpoint over the float32 weight tables
    (``graph/blocked.build_blocked_weights``, memoized per (runtime,
    seed) beside the ELL weight tables)."""

    name = "sssp_blocked"
    kind = "sssp"
    TABLE_SCALE = 5  # int8 adjacency + f32 weight table

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.analytics.queries import SsspResult
        from bibfs_tpu.ops.semiring_plane import sssp_blocked

        with span("sssp_blocked_batch", batch=len(queries)):
            self._fire("analytics", queries)
            t0 = time.perf_counter()
            bg = rt.blocked_graph()
            by_seed: dict[int, list] = {}
            for i, q in enumerate(queries):
                by_seed.setdefault(int(q.weight_seed), []).append((i, q))
            out: list = [None] * len(queries)
            for seed, group in sorted(by_seed.items()):
                wtab = rt.analytics_weight_table(seed)
                init = np.full(
                    (bg.n_pad, len(group)), np.inf, dtype=np.float32
                )
                for col, (_i, q) in enumerate(group):
                    init[int(q.source), col] = 0.0
                self._note_exec(
                    bg.nblocks, bg.bwidth, extra=(len(group),)
                )
                dist, rounds = sssp_blocked(wtab, bg.bcol, init)
                dist = np.asarray(dist, dtype=np.float64)
                self.rounds_cell.inc(int(rounds))
                for col, (i, _q) in enumerate(group):
                    d = dist[: ctx.n, col]
                    out[i] = SsspResult(
                        found=True, dist=d,
                        reached=int(np.isfinite(d).sum()),
                        rounds=int(rounds),
                        time_s=time.perf_counter() - t0,
                    )
            self._fire("analytics_finish", queries)
            return out, None, t0


class PageRankBlockedRoute(AnalyticsBlockedRoute):
    """Damped power iteration as one jitted while_loop per parameter
    set (tolerance clamped to f32 resolution — ranks agree with the
    host rung to ~1e-6, the verification tolerance)."""

    name = "pagerank_blocked"
    kind = "pagerank"

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.analytics.queries import PageRankResult
        from bibfs_tpu.ops.semiring_plane import pagerank_blocked

        with span("pagerank_blocked_batch", batch=len(queries)):
            self._fire("analytics", queries)
            t0 = time.perf_counter()
            bg = rt.blocked_graph()
            out = []
            for q in queries:
                self._note_exec(bg.nblocks, bg.bwidth)
                ranks, iters, delta = pagerank_blocked(
                    bg.tab, bg.bcol, bg.deg, n=ctx.n,
                    damping=float(q.damping), tol=float(q.tol),
                    max_iters=int(q.max_iters),
                )
                self.rounds_cell.inc(int(iters))
                out.append(PageRankResult(
                    found=ctx.n > 0,
                    ranks=np.asarray(ranks, dtype=np.float64)[: ctx.n],
                    iters=int(iters), delta=float(delta),
                    time_s=time.perf_counter() - t0,
                ))
            self._fire("analytics_finish", queries)
            return out, None, t0


class ComponentsBlockedRoute(AnalyticsBlockedRoute):
    """Min-label propagation over the int8 adjacency (0/inf weights
    derived per chunk — no weight table materialized)."""

    name = "components_blocked"
    kind = "components"

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.analytics.queries import ComponentsResult
        from bibfs_tpu.ops.semiring_plane import components_blocked

        with span("components_blocked_batch", batch=len(queries)):
            self._fire("analytics", queries)
            t0 = time.perf_counter()
            bg = rt.blocked_graph()
            self._note_exec(bg.nblocks, bg.bwidth)
            init = np.arange(bg.n_pad, dtype=np.float32)[:, None]
            labels, rounds = components_blocked(bg.tab, bg.bcol, init)
            labels = np.asarray(labels)[: ctx.n, 0].astype(np.int64)
            count = int(np.unique(labels).size) if ctx.n else 0
            self.rounds_cell.inc(int(rounds))
            res = ComponentsResult(
                found=True, labels=labels, count=count,
                rounds=int(rounds), time_s=time.perf_counter() - t0,
            )
            self._fire("analytics_finish", queries)
            return [res for _q in queries], None, t0


class TrianglesBlockedRoute(AnalyticsBlockedRoute):
    """The masked popcount matmul over the tile tables, column-chunked
    at a static width (one compiled program per graph)."""

    name = "triangles_blocked"
    kind = "triangles"

    def launch(self, rt, queries, ctx=None):
        from bibfs_tpu.analytics.queries import TrianglesResult
        from bibfs_tpu.ops.semiring_plane import triangles_chunk_blocked

        with span("triangles_blocked_batch", batch=len(queries)):
            self._fire("analytics", queries)
            t0 = time.perf_counter()
            bg = rt.blocked_graph()
            self._note_exec(bg.nblocks, bg.bwidth, extra=(_TRI_CHUNK,))
            n = ctx.n
            src = (
                np.repeat(
                    np.arange(n, dtype=np.int64),
                    np.diff(ctx.row_ptr).astype(np.int64),
                )
                if n else np.zeros(0, dtype=np.int64)
            )
            total = 0
            chunks = 0
            for c0 in range(0, n, _TRI_CHUNK):
                c1 = min(n, c0 + _TRI_CHUNK)
                plane = np.zeros((bg.n_pad, _TRI_CHUNK), np.float32)
                m = (ctx.col_ind >= c0) & (ctx.col_ind < c1)
                plane[src[m], ctx.col_ind[m] - c0] = 1.0
                total += int(triangles_chunk_blocked(
                    bg.tab, bg.bcol, plane
                ))
                chunks += 1
            self.rounds_cell.inc(chunks)
            res = TrianglesResult(
                found=True, count=total // 6,
                time_s=time.perf_counter() - t0,
            )
            self._fire("analytics_finish", queries)
            return [res for _q in queries], None, t0


def build_analytics_routes(engine, label: str) -> dict:
    """The analytics rung set every engine carries (host + blocked per
    kind), each with its OWN retry policy and circuit breaker."""
    from bibfs_tpu.serve.resilience import CircuitBreaker, RetryPolicy

    routes: dict = {}
    for cls in (SsspRoute, PageRankRoute, ComponentsRoute,
                TrianglesRoute, SsspBlockedRoute, PageRankBlockedRoute,
                ComponentsBlockedRoute, TrianglesBlockedRoute):
        routes[cls.name] = cls(
            engine, retry=RetryPolicy(), breaker=CircuitBreaker(),
            label=label,
        )
    return routes
