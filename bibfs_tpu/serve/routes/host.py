"""``route="host"`` and ``route="serial"`` — the host rungs as Routes.

The host route is the ladder's terminal batch rung: it solves through
the threaded native C batch (one GIL-free ctypes call) when the native
runtime carries it, per-query otherwise, and it never returns
unavailable — failure isolation happens INSIDE it (the engine's
bisection isolator), converging a poison batch to per-query
``QueryError`` s with the serial rung as each singleton's last chance.
``serial`` is that bottom rung: the pure-NumPy oracle over the bound
snapshot's CSR — no native runtime, no device stack, nothing left to be
broken but the graph itself. It stays a first-class Route so chaos
tests can break it per engine and so the route taxonomy is complete,
but it is reached per-query through the isolator rather than batchwise
from the ladder.
"""

from __future__ import annotations

from bibfs_tpu.serve.routes.base import Route


class HostRoute(Route):
    """The terminal batch rung: native C batch / per-query host solve
    with bisection failure isolation (never unavailable)."""

    name = "host"

    def solve(self, rt, pairs, cutoffs=None):
        # the isolator returns BFSResult | QueryError per pair and
        # never raises; the engine's delivery skeleton partitions them
        return self.engine._solve_host_isolated(pairs, cutoffs)


class SerialRoute(Route):
    """The bottom rung, reached per-query through the host isolator."""

    name = "serial"

    def solve_one(self, rt, src: int, dst: int, cutoff: int | None = None):
        return rt.solve_serial_one(src, dst, cutoff)
