"""Shape buckets + executable-reuse accounting for the serving engine.

Why this layer exists: every compiled search program is keyed (via
``jax.jit``'s shape specialization and the solver-side kernel caches) by
the PADDED device geometry — ``[n_pad, width]`` tables, ``[B]`` query
vectors. A serving deployment that accepts arbitrary graphs therefore
recompiles per graph size, and ``AOT_AUDIT.json`` records single
compiles up to ~258 s: one odd-sized graph can cost more than a million
served queries. Here every incoming graph is padded UP to a small
geometric ladder of shapes (rows x2 from 128, ELL width x2 from 8, batch
x2 from 128 lanes), so any mix of graph sizes funnels into a handful of
compiled programs — the classic bucketed-serving trade (a bounded <2x
pad overhead in table reads buys an O(1) executable working set).

Padding is semantically free: bucket rows are isolated degree-0 vertices
and bucket width columns sit beyond every true degree, and all use sites
mask by ``deg`` (the same invariant ``pad_multiple`` already relies on).

:class:`ExecutableCache` is the accounting side: the engine notes the
(bucket shape, resolved mode, batch bucket) of every device dispatch,
and because the solver kernel caches key on exactly those padded shapes
(see ``batch_minor._get_minor_kernel_shape``), a noted *hit* really is a
reused compiled program, not just a reused label.
"""

from __future__ import annotations

import threading

import numpy as np

from bibfs_tpu.analysis import compilegraph as _compilegraph
from bibfs_tpu.graph.csr import EllGraph, build_ell
from bibfs_tpu.obs.metrics import REGISTRY, next_instance_label

# Geometric ladders. Rows start at 128 (one lane group) and double;
# widths start at the int32 sublane quantum 8 and double; batch buckets
# start at one 128-lane group and double (bucket_batch). Ratio 2 bounds
# pad waste at <2x while keeping the ladder ~17 rungs deep to 10M nodes.
ROW_BUCKET_BASE = 128
WIDTH_BUCKET_BASE = 8
BATCH_BUCKET_BASE = 128


def _next_rung(base: int, value: int) -> int:
    rung = base
    while rung < value:
        rung *= 2
    return rung


def bucket_rows(n_pad: int) -> int:
    """Smallest row rung (128 * 2^k) holding ``n_pad`` vertex rows."""
    return _next_rung(ROW_BUCKET_BASE, max(1, n_pad))


def bucket_width(width: int) -> int:
    """Smallest ELL-width rung (8 * 2^k) holding ``width`` slots."""
    return _next_rung(WIDTH_BUCKET_BASE, max(1, width))


def bucket_batch(num_queries: int) -> int:
    """Smallest batch rung (128 * 2^k) holding ``num_queries`` — the
    engine pads every flush to a rung so repeat traffic at any queue
    depth reuses a handful of compiled batch programs."""
    return _next_rung(BATCH_BUCKET_BASE, max(1, num_queries))


def bucket_shape(n_pad: int, width: int) -> tuple[int, int]:
    return bucket_rows(n_pad), bucket_width(width)


def ell_bucket_key(g) -> tuple:
    """The compiled-program shape identity of an (already bucketed) ELL
    device table: everything the jit caches specialize on besides batch
    mode and rung. Two graphs — or two VERSIONS of one graph — with the
    same key reuse each other's compiled programs, which is what makes
    a same-bucket hot-swap cost zero recompiles.

    This is the SINGLE-DEVICE identity. A mesh program over the same
    padded shape compiles a different executable (shard geometry is
    part of what the jit specializes on), so mesh dispatches key
    through :func:`placement_bucket_key` — a bare padded-shape key
    would silently collide the two."""
    return ("ell", g.n_pad, g.width)


def placement_bucket_key(base_key: tuple, *, kind: str, shards: int,
                         extra: tuple = ()) -> tuple:
    """Extend a shape bucket key with its mesh/shard placement.

    The compiled-program caches specialize on the SPMD partitioning as
    much as on the padded shape: a ``[n_pad, width]`` table compiled
    for one device and the same table 1D-sharded over 8 are different
    executables, and before this helper the ExecutableCache would have
    counted the second as a hit on the first. ``kind`` names the
    placement family (``"mesh1d"`` vertex-sharded, ``"dp"``
    query-sharded), ``shards`` the mesh size, ``extra`` any further
    program discriminators (collective mode, plane dtype, batch
    rung)."""
    return base_key + ((kind, int(shards)) + tuple(extra),)


#: row alignment of the dp-batch replicated table (below). 1024 rows of
#: int8 shard plane x 128 lanes = 128 KiB per rung — fine enough that
#: pad waste stays under ~10% from 10k vertices up, coarse enough that
#: the dp program ladder stays bounded (one program per 1024-row rung x
#: width rung x lane rung).
DP_ROW_ALIGN = 1024


def dp_aligned_ell(
    n: int,
    edges: np.ndarray | None = None,
    *,
    pairs: np.ndarray | None = None,
    row_align: int = DP_ROW_ALIGN,
) -> EllGraph:
    """The dp-batch (query-sharded) serving table: rows aligned to a
    FINE ladder, width bucketed to the geometric rung.

    The dp route deliberately does NOT reuse :func:`bucketed_ell`'s
    geometric row ladder: the batch-minor kernel's working set per
    shard is the ``[n_pad, b_loc]`` int8 plane, and the measured 1.5-2x
    dp advantage over the single-device device route (bench_mesh.json)
    comes precisely from that plane staying cache-resident — rounding
    rows UP to the next power-of-two rung (e.g. 10240 -> 16384) spills
    it and erases the win. Width stays on the geometric rung (measured
    free for this kernel), so the compiled-program ladder is one
    program per (1024-row rung x width rung x lane rung) — finer than
    the geometric buckets, still bounded, and every dispatch is noted
    in the ExecutableCache under its :func:`placement_bucket_key` so
    the trade stays visible in the reuse counters."""
    g = build_ell(n, edges, pairs=pairs, pad_multiple=max(int(row_align), 8))
    w = bucket_width(g.width)
    if w == g.width:
        return g
    nbr = np.zeros((g.n_pad, w), dtype=np.int32)
    nbr[:, : g.width] = g.nbr
    return EllGraph(
        n=g.n, n_pad=g.n_pad, width=w, num_edges=g.num_edges,
        nbr=nbr, deg=g.deg, overflow=g.overflow,
    )


def repad_rows(g: EllGraph, multiple: int) -> EllGraph:
    """Re-pad an ELL table's vertex rows up to a multiple (isolated
    degree-0 rows, the same semantically-free padding the buckets use)
    — the mesh route's shard-divisibility fix for meshes whose size
    does not divide the bucket rung."""
    mult = max(int(multiple), 1)
    if g.n_pad % mult == 0:
        return g
    rows = -(-g.n_pad // mult) * mult
    nbr = np.zeros((rows, g.width), dtype=np.int32)
    nbr[: g.n_pad] = g.nbr
    deg = np.zeros(rows, dtype=np.int32)
    deg[: g.n_pad] = g.deg
    return EllGraph(
        n=g.n, n_pad=rows, width=g.width, num_edges=g.num_edges,
        nbr=nbr, deg=deg, overflow=g.overflow,
    )


def bucketed_ell(
    n: int,
    edges: np.ndarray | None = None,
    *,
    pairs: np.ndarray | None = None,
) -> EllGraph:
    """`build_ell` padded up to its shape bucket.

    The returned graph reports the bucket as its ``n_pad``/``width``, so
    everything downstream (device upload, kernel geometry, chunk math)
    sees only the bucketed shape; ``n`` stays the true vertex count for
    range checks and result slicing."""
    g = build_ell(n, edges, pairs=pairs)
    rows, width = bucket_shape(g.n_pad, g.width)
    if (rows, width) == (g.n_pad, g.width):
        return g
    nbr = np.zeros((rows, width), dtype=np.int32)
    nbr[: g.n_pad, : g.width] = g.nbr
    deg = np.zeros(rows, dtype=np.int32)
    deg[: g.n_pad] = g.deg
    return EllGraph(
        n=g.n,
        n_pad=rows,
        width=width,
        num_edges=g.num_edges,
        nbr=nbr,
        deg=deg,
        overflow=g.overflow,
    )


class ExecutableCache:
    """Hit/miss accounting over compiled-program identities.

    A *program key* is everything the underlying jit caches specialize
    on for a dispatch: the bucketed table shape, the resolved batch
    mode, and the batch rung. ``note()`` returns whether that program
    was already paid for. One process-wide instance
    (:data:`DEFAULT_EXEC_CACHE`) is shared by default so engines over
    different graphs in one bucket see each other's compiles — exactly
    the reuse the buckets exist to create. Thread-safe throughout: the
    pipelined engine's flusher notes dispatches concurrently with any
    number of synchronous engines in the same process.

    All accounting lives in the process metrics registry under the
    stable documented names ``bibfs_exec_cache_events_total{cache,
    event="hit"|"miss"}``, ``bibfs_exec_programs{cache}`` and
    ``bibfs_exec_program_dispatches_total{cache,program}``;
    ``stats()``/``program_counts()`` are snapshot views over them."""

    def __init__(self, metrics_label: str | None = None):
        self._seen: dict = {}  # program key -> dispatch count
        self._lock = threading.Lock()
        self.metrics_label = (
            next_instance_label("exec") if metrics_label is None
            else metrics_label
        )
        events = REGISTRY.counter(
            "bibfs_exec_cache_events_total",
            "Compiled-program reuse accounting (hit = reused executable)",
            ("cache", "event"),
        )
        self._m_hit = events.labels(cache=self.metrics_label, event="hit")
        self._m_miss = events.labels(cache=self.metrics_label, event="miss")
        self._g_programs = REGISTRY.gauge(
            "bibfs_exec_programs",
            "Distinct compiled programs dispatched through this cache",
            ("cache",),
        ).labels(cache=self.metrics_label)
        self._m_dispatch = REGISTRY.counter(
            "bibfs_exec_program_dispatches_total",
            "Dispatches per compiled-program identity",
            ("cache", "program"),
        )
        # minted at construction so the family renders at zero: compiles
        # are a first-class scrape-time signal — in steady state
        # rate(bibfs_exec_compiles_total) must be 0, and an alert on it
        # catches a retrace leak without waiting for a bench-time
        # program_counts() diff
        self._m_compile = REGISTRY.counter(
            "bibfs_exec_compiles_total",
            "First-seen compiled programs (a steady-state serving "
            "process must not pay new compiles)",
            ("cache", "program"),
        )

    @property
    def hits(self) -> int:
        return self._m_hit.value

    @property
    def misses(self) -> int:
        return self._m_miss.value

    def note(self, key) -> bool:
        """Record a dispatch under ``key``; True iff already compiled.

        The registry cells are lock-free (obs/metrics.py's contract:
        mutators of one cell serialize externally), so every increment
        happens under THIS cache's lock — it is the shared
        DEFAULT_EXEC_CACHE that concurrent engines note into.

        Under ``BIBFS_COMPILE_CHECK=1`` a MISS also publishes the key
        to the compile sentinel thread-locally: a first-seen program's
        solve compiles synchronously on this thread, so the compile
        event it triggers attributes to this key — that is how
        ``compilegraph.json`` knows which compiles were routed
        (single-shot + expiring on the sentinel side, so a miss whose
        kernel was already warm leaves nothing claimable). A HIT
        retires any published key instead: no first compile is
        expected, and a compile that happens anyway (a retrace reusing
        a noted key) is one the accounting layer did NOT pay for —
        reporting it unrouted is the signal."""
        with self._lock:
            if key in self._seen:
                self._seen[key] += 1
                hit = True
                self._m_hit.inc()
            else:
                self._seen[key] = 1
                hit = False
                self._m_miss.inc()
                self._g_programs.inc()
                self._m_compile.labels(
                    cache=self.metrics_label, program=str(key)
                ).inc()
            self._m_dispatch.labels(
                cache=self.metrics_label, program=str(key)
            ).inc()
        if hit:
            _compilegraph.clear_routed_key()
        else:
            _compilegraph.note_routed_key(key)
        return hit

    def stats(self) -> dict:
        with self._lock:  # one atomic snapshot: a miss always inserts
            return {
                "hits": self._m_hit.value,
                "misses": self._m_miss.value,
                "programs": len(self._seen),
            }

    def program_counts(self, top: int | None = None) -> dict:
        """Per-program dispatch counts, hottest first — the load
        harness's view of which compiled executables actually carry
        traffic (keys stringified for JSON artifacts)."""
        with self._lock:
            ranked = sorted(
                self._seen.items(), key=lambda kv: kv[1], reverse=True
            )
        if top is not None:
            ranked = ranked[:top]
        return {str(k): v for k, v in ranked}


DEFAULT_EXEC_CACHE = ExecutableCache(metrics_label="default")
