"""Distance/result cache for the serving engine.

Two bounded LRU stores, both host-side and dispatch-free to read:

- **Source forests**, keyed ``(graph_id, root)``: the solved parent
  array of one side of a bidirectional search. Every search is
  level-synchronous, so any vertex inside the forest carries its TRUE
  BFS distance from the root — a follow-up query ``(root, x)`` (or its
  reverse ``(x, root)``: the graph is undirected) whose ``x`` lies in
  the forest is answered exactly by walking the parent chain, with zero
  solver dispatches. Distances are implicit (chain length), so an
  insert is just an O(n) row copy and a lookup is O(hops).

- **Pair memo**, keyed ``(graph_id, min(a,b), max(a,b))``: whole results
  including *negative* ones — a partial forest can never prove "no
  path" (the vertex might merely be unexplored), so unreachable pairs
  are only servable from this memo.

A forest is PARTIAL: the search stops at the provably-correct meet vote,
so only the explored region is present. Absence from the forest is a
cache miss, never an answer.

Every public method is THREAD-SAFE (one re-entrant lock around both
stores): the pipelined engine's flusher, finish worker, host workers
and every submitting client thread all read and write one cache.
Eviction accounting is complete — forest pops and pair-memo pops each
feed their own counter, and ``evictions`` is their sum (the pair-memo
pops used to bypass the counter entirely, so ``stats()`` under-reported
churn).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np


def walk_parents(par: np.ndarray, root: int, v: int) -> list[int] | None:
    """The forest path ``[root, ..., v]``, or None if ``v`` is outside
    the forest. Bounded by the array size, so a corrupt chain cannot
    loop forever."""
    if v == root:
        return [root]
    if not (0 <= v < par.size) or par[v] < 0:
        return None
    chain = [v]
    u = int(par[v])
    for _ in range(par.size):
        chain.append(u)
        if u == root:
            chain.reverse()
            return chain
        u = int(par[u])
        if u < 0:
            return None
    return None


class DistanceCache:
    """LRU source forests + pair memo (module docstring). ``entries``
    bounds the forest store (the memory owner: one int32[n] row each);
    ``pair_entries`` the memo (tiny tuples; defaults to 8x)."""

    def __init__(self, entries: int = 64, pair_entries: int | None = None):
        self.entries = int(entries)
        self.pair_entries = int(
            8 * entries if pair_entries is None else pair_entries
        )
        self._lock = threading.RLock()
        self._forests: OrderedDict = OrderedDict()
        self._pairs: OrderedDict = OrderedDict()
        self.forest_hits = 0
        self.pair_hits = 0
        self.misses = 0
        self.inserts = 0
        self.forest_evictions = 0
        self.pair_evictions = 0

    @property
    def evictions(self) -> int:
        """Total LRU pops across BOTH stores (the complete churn count)."""
        return self.forest_evictions + self.pair_evictions

    # ---- inserts -----------------------------------------------------
    def put_forest(self, graph_id, root: int, par: np.ndarray, n: int):
        """Bank one side's parent array (sliced to the true vertex
        count; device padding rows are never part of any chain)."""
        if self.entries <= 0:
            return
        key = (graph_id, int(root))
        row = np.asarray(par[:n], dtype=np.int32).copy()
        with self._lock:
            self._forests[key] = row
            self._forests.move_to_end(key)
            self.inserts += 1
            while len(self._forests) > self.entries:
                self._forests.popitem(last=False)
                self.forest_evictions += 1

    def put_path(self, graph_id, path, n: int):
        """Bank a solved shortest path as (partial) forests for BOTH its
        endpoints. Along a shortest path, vertex ``path[i]`` sits at true
        BFS distance ``i`` from ``path[0]`` (and ``len-1-i`` from the
        other end), so each direction of the chain is a valid
        parent-forest fragment — this is how the host dispatch path
        (which has no parent planes) still feeds the forest store.
        Merges into an existing forest when present (already-claimed
        parents stand; both chains are distance-consistent)."""
        if self.entries <= 0 or path is None or len(path) < 2:
            return
        with self._lock:
            for chain in (path, list(reversed(path))):
                key = (graph_id, int(chain[0]))
                par = self._forests.get(key)
                if par is None:
                    par = np.full(n, -1, np.int32)
                    self._forests[key] = par
                    self.inserts += 1
                for prev, v in zip(chain[:-1], chain[1:]):
                    if 0 <= v < par.size and par[v] < 0:
                        par[v] = prev
                self._forests.move_to_end(key)
            while len(self._forests) > self.entries:
                self._forests.popitem(last=False)
                self.forest_evictions += 1

    def put_result(self, graph_id, src: int, dst: int,
                   found: bool, hops, path):
        """Memoize a whole materialized result, oriented canonically."""
        if self.pair_entries <= 0 or src == dst:
            return
        a, b = (src, dst) if src < dst else (dst, src)
        if found and path is not None and path[0] != a:
            path = list(reversed(path))
        with self._lock:
            self._pairs[(graph_id, a, b)] = (found, hops, path)
            self._pairs.move_to_end((graph_id, a, b))
            while len(self._pairs) > self.pair_entries:
                self._pairs.popitem(last=False)
                self.pair_evictions += 1

    # ---- lookup ------------------------------------------------------
    def lookup(self, graph_id, src: int, dst: int):
        """``(found, hops, path src->dst)`` or None (a miss). Tries the
        pair memo, then the src forest, then the dst forest (reverse
        twin)."""
        a, b = (src, dst) if src < dst else (dst, src)
        with self._lock:
            memo = self._pairs.get((graph_id, a, b))
            if memo is not None:
                self._pairs.move_to_end((graph_id, a, b))
                self.pair_hits += 1
                found, hops, path = memo
                if found and path is not None and src != path[0]:
                    path = list(reversed(path))
                return found, hops, path
            for root, leaf, reverse in ((src, dst, False), (dst, src, True)):
                par = self._forests.get((graph_id, root))
                if par is None:
                    continue
                chain = walk_parents(par, root, leaf)
                if chain is None:
                    continue
                self._forests.move_to_end((graph_id, root))
                self.forest_hits += 1
                if reverse:
                    chain.reverse()  # walk gave [dst..src]; want src->dst
                return True, len(chain) - 1, chain
            self.misses += 1
            return None

    def stats(self) -> dict:
        with self._lock:
            return {
                "forest_hits": self.forest_hits,
                "pair_hits": self.pair_hits,
                "hits": self.forest_hits + self.pair_hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "forest_evictions": self.forest_evictions,
                "pair_evictions": self.pair_evictions,
                "forests": len(self._forests),
                "pairs": len(self._pairs),
            }
