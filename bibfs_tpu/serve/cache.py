"""Distance/result cache for the serving engine.

Two bounded LRU stores, both host-side and dispatch-free to read:

- **Source forests**, keyed ``(graph_id, root)``: the solved parent
  array of one side of a bidirectional search. Every search is
  level-synchronous, so any vertex inside the forest carries its TRUE
  BFS distance from the root — a follow-up query ``(root, x)`` (or its
  reverse ``(x, root)``: the graph is undirected) whose ``x`` lies in
  the forest is answered exactly by walking the parent chain, with zero
  solver dispatches. Distances are implicit (chain length), so an
  insert is just an O(n) row copy and a lookup is O(hops).

- **Pair memo**, keyed ``(graph_id, min(a,b), max(a,b))``: whole results
  including *negative* ones — a partial forest can never prove "no
  path" (the vertex might merely be unexplored), so unreachable pairs
  are only servable from this memo.

A forest is PARTIAL: the search stops at the provably-correct meet vote,
so only the explored region is present. Absence from the forest is a
cache miss, never an answer.

Every public method is THREAD-SAFE (one re-entrant lock around both
stores): the pipelined engine's flusher, finish worker, host workers
and every submitting client thread all read and write one cache.
Eviction accounting is complete — forest pops and pair-memo pops each
feed their own counter, and ``evictions`` is their sum (the pair-memo
pops used to bypass the counter entirely, so ``stats()`` under-reported
churn) — and every counter lives in the process metrics registry
(``bibfs_dist_cache_events_total{cache,event}``,
``bibfs_dist_cache_entries{cache,store}``), so one ``/metrics`` scrape
reads the same ledger ``stats()`` snapshots.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from bibfs_tpu.obs.metrics import REGISTRY, next_instance_label
from bibfs_tpu.obs.trace import span

# stable documented metric names (README "Observability")
_EVENTS = ("forest_hit", "pair_hit", "miss", "insert",
           "forest_eviction", "pair_eviction", "invalidation")


def _cache_cells(label: str) -> tuple[dict, dict]:
    events = REGISTRY.counter(
        "bibfs_dist_cache_events_total",
        "Distance/result cache events by kind",
        ("cache", "event"),
    )
    entries = REGISTRY.gauge(
        "bibfs_dist_cache_entries",
        "Live distance-cache entries per store",
        ("cache", "store"),
    )
    return (
        {e: events.labels(cache=label, event=e) for e in _EVENTS},
        {s: entries.labels(cache=label, store=s)
         for s in ("forests", "pairs")},
    )


def walk_parents(par: np.ndarray, root: int, v: int) -> list[int] | None:
    """The forest path ``[root, ..., v]``, or None if ``v`` is outside
    the forest. Bounded by the array size, so a corrupt chain cannot
    loop forever."""
    if v == root:
        return [root]
    if not (0 <= v < par.size) or par[v] < 0:
        return None
    chain = [v]
    u = int(par[v])
    for _ in range(par.size):
        chain.append(u)
        if u == root:
            chain.reverse()
            return chain
        u = int(par[u])
        if u < 0:
            return None
    return None


class DistanceCache:
    """LRU source forests + pair memo (module docstring). ``entries``
    bounds the forest store (the memory owner: one int32[n] row each);
    ``pair_entries`` the memo (tiny tuples; defaults to 8x).
    ``metrics_label`` is the registry ``cache=`` label value (engines
    pass their own label so one scrape separates engines; standalone
    caches get a process-unique one)."""

    def __init__(self, entries: int = 64, pair_entries: int | None = None,
                 metrics_label: str | None = None):
        self.entries = int(entries)
        self.pair_entries = int(
            8 * entries if pair_entries is None else pair_entries
        )
        self.metrics_label = (
            next_instance_label("dist") if metrics_label is None
            else metrics_label
        )
        self._m, self._g = _cache_cells(self.metrics_label)
        self._lock = threading.RLock()
        self._forests: OrderedDict = OrderedDict()
        self._pairs: OrderedDict = OrderedDict()

    # counter attributes kept as registry-cell reads (back-compat: these
    # were plain ints before the obs migration)
    @property
    def forest_hits(self) -> int:
        return self._m["forest_hit"].value

    @property
    def pair_hits(self) -> int:
        return self._m["pair_hit"].value

    @property
    def misses(self) -> int:
        return self._m["miss"].value

    @property
    def inserts(self) -> int:
        return self._m["insert"].value

    @property
    def forest_evictions(self) -> int:
        return self._m["forest_eviction"].value

    @property
    def pair_evictions(self) -> int:
        return self._m["pair_eviction"].value

    @property
    def evictions(self) -> int:
        """Total LRU pops across BOTH stores (the complete churn count)."""
        return self.forest_evictions + self.pair_evictions

    @property
    def invalidations(self) -> int:
        return self._m["invalidation"].value

    # ---- inserts -----------------------------------------------------
    def put_forest(self, graph_id, root: int, par: np.ndarray, n: int):
        """Bank one side's parent array (sliced to the true vertex
        count; device padding rows are never part of any chain)."""
        if self.entries <= 0:
            return
        key = (graph_id, int(root))
        with span("cache_put", kind="forest"):
            row = np.asarray(par[:n], dtype=np.int32).copy()
            with self._lock:
                self._forests[key] = row
                self._forests.move_to_end(key)
                self._m["insert"].inc()
                while len(self._forests) > self.entries:
                    self._forests.popitem(last=False)
                    self._m["forest_eviction"].inc()
                self._g["forests"].set(len(self._forests))

    def put_path(self, graph_id, path, n: int):
        """Bank a solved shortest path as (partial) forests for BOTH its
        endpoints. Along a shortest path, vertex ``path[i]`` sits at true
        BFS distance ``i`` from ``path[0]`` (and ``len-1-i`` from the
        other end), so each direction of the chain is a valid
        parent-forest fragment — this is how the host dispatch path
        (which has no parent planes) still feeds the forest store.
        Merges into an existing forest when present (already-claimed
        parents stand; both chains are distance-consistent)."""
        if self.entries <= 0 or path is None or len(path) < 2:
            return
        with span("cache_put", kind="path"), self._lock:
            for chain in (path, list(reversed(path))):
                key = (graph_id, int(chain[0]))
                par = self._forests.get(key)
                if par is None:
                    par = np.full(n, -1, np.int32)
                    self._forests[key] = par
                    self._m["insert"].inc()
                for prev, v in zip(chain[:-1], chain[1:]):
                    if 0 <= v < par.size and par[v] < 0:
                        par[v] = prev
                self._forests.move_to_end(key)
            while len(self._forests) > self.entries:
                self._forests.popitem(last=False)
                self._m["forest_eviction"].inc()
            self._g["forests"].set(len(self._forests))

    def put_result(self, graph_id, src: int, dst: int,
                   found: bool, hops, path):
        """Memoize a whole materialized result, oriented canonically."""
        if self.pair_entries <= 0 or src == dst:
            return
        a, b = (src, dst) if src < dst else (dst, src)
        if found and path is not None and path[0] != a:
            path = list(reversed(path))
        with self._lock:
            self._pairs[(graph_id, a, b)] = (found, hops, path)
            self._pairs.move_to_end((graph_id, a, b))
            while len(self._pairs) > self.pair_entries:
                self._pairs.popitem(last=False)
                self._m["pair_eviction"].inc()
            self._g["pairs"].set(len(self._pairs))

    def invalidate(self, graph_id) -> int:
        """Drop every forest and pair entry namespaced under
        ``graph_id`` — the version-scoped invalidation a graph-store
        hot-swap triggers. Keys are content digests, so entries of a
        superseded version are already unreachable for new-version
        queries; this reclaims their memory (one int32[n] row per
        forest) instead of waiting for LRU churn. Returns the number of
        entries dropped (also counted under the ``invalidation``
        event)."""
        with self._lock:
            fkeys = [k for k in self._forests if k[0] == graph_id]
            pkeys = [k for k in self._pairs if k[0] == graph_id]
            for k in fkeys:
                del self._forests[k]
            for k in pkeys:
                del self._pairs[k]
            dropped = len(fkeys) + len(pkeys)
            if dropped:
                self._m["invalidation"].inc(dropped)
                self._g["forests"].set(len(self._forests))
                self._g["pairs"].set(len(self._pairs))
            return dropped

    # ---- lookup ------------------------------------------------------
    def lookup(self, graph_id, src: int, dst: int):
        """``(found, hops, path src->dst)`` or None (a miss). Tries the
        pair memo, then the src forest, then the dst forest (reverse
        twin)."""
        a, b = (src, dst) if src < dst else (dst, src)
        with span("cache_lookup"), self._lock:
            memo = self._pairs.get((graph_id, a, b))
            if memo is not None:
                self._pairs.move_to_end((graph_id, a, b))
                self._m["pair_hit"].inc()
                found, hops, path = memo
                if found and path is not None and src != path[0]:
                    path = list(reversed(path))
                return found, hops, path
            for root, leaf, reverse in ((src, dst, False), (dst, src, True)):
                par = self._forests.get((graph_id, root))
                if par is None:
                    continue
                chain = walk_parents(par, root, leaf)
                if chain is None:
                    continue
                self._forests.move_to_end((graph_id, root))
                self._m["forest_hit"].inc()
                if reverse:
                    chain.reverse()  # walk gave [dst..src]; want src->dst
                return True, len(chain) - 1, chain
            self._m["miss"].inc()
            return None

    def stats(self) -> dict:
        """Snapshot view over this cache's registry cells (the same
        numbers ``/metrics`` renders under ``cache="{metrics_label}"``)."""
        with self._lock:
            return {
                "forest_hits": self.forest_hits,
                "pair_hits": self.pair_hits,
                "hits": self.forest_hits + self.pair_hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "forest_evictions": self.forest_evictions,
                "pair_evictions": self.pair_evictions,
                "invalidations": self.invalidations,
                "forests": len(self._forests),
                "pairs": len(self._pairs),
            }
