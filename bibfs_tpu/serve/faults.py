"""Fault injection for the serving stack — chaos against the REAL engine.

The serving layer's failure handling (``serve/resilience``: retry,
route fallback, circuit breaking, failure isolation) is only worth
trusting if it is exercised against the actual engine code paths, not
mocks. This module is the injection side of that bargain: a
:class:`FaultPlan` is a set of rules that fire at the engine's named
seams —

- ``device`` — the batched device dispatch
  (:meth:`~bibfs_tpu.serve.engine.QueryEngine._device_launch`), the
  seam a dead/flaky accelerator route fails at;
- ``device_finish`` — the forced value read + host-side decode
  (:meth:`~bibfs_tpu.serve.engine.QueryEngine._device_finish`), the
  seam a mid-execution runtime error surfaces at;
- ``host_batch`` — the threaded native C batch
  (``solvers/native.solve_batch_native_graph``), the native-solver
  failure seam;
- ``wal_write`` / ``wal_fsync`` — the durable store's write-ahead-log
  append and fsync (``store/wal.WalWriter``): the dying-disk seams. A
  fault here makes ``GraphStore.update`` REFUSE the ack with nothing
  committed in memory — the invariant the durability layer exists for;
- ``manifest_rename`` — the atomic ``os.replace`` committing a
  checkpoint manifest (``store/registry``): a fault here leaves the
  previous manifest governing recovery, never a half-written one.

A rule either raises :class:`InjectedFault` (kind ``error``) or sleeps
(kind ``latency``), probabilistically (``p=0.1``, seeded — chaos runs
are reproducible) or deterministically (``every=3``: every 3rd call;
``times=2``: the first 2 calls), optionally only when a specific
query is in the batch (``pair=SRC-DST`` — the poison-batch case the
bisection isolator exists for).

Spec grammar (the ``BIBFS_FAULTS`` env var and
``bibfs-serve --inject-faults``)::

    SPEC   := RULE (';' RULE)*
    RULE   := SITE ':' FIELD (',' FIELD)*
    FIELD  := 'p=' FLOAT | 'every=' INT | 'times=' INT
            | 'kind=' ('error'|'latency') | 'ms=' FLOAT
            | 'pair=' INT '-' INT

e.g. ``device:p=0.15`` (15% of device dispatches raise),
``host_batch:every=4,kind=latency,ms=20`` (every 4th native batch
stalls 20 ms), ``host_batch:pair=7-19,times=3`` (the first 3 native
batches containing query (7, 19) raise — everyone else sails through).

Injections land in the process metrics registry
(``bibfs_faults_injected_total{site,kind}``) so a chaos run's /metrics
scrape shows exactly what was thrown at the engine. An engine built
without a plan (and without ``BIBFS_FAULTS``) carries ``faults=None``
and pays exactly one attribute check per seam.
"""

from __future__ import annotations

import os
import random
import threading
import time

from bibfs_tpu.obs.metrics import REGISTRY

ENV_VAR = "BIBFS_FAULTS"

#: seams the serving engines actually fire (parse rejects anything else:
#: a typo'd site in a chaos spec must fail loudly, not silently inject
#: nothing and pass the soak)
KNOWN_SITES = ("device", "device_finish", "mesh", "mesh_finish",
               "blocked", "blocked_finish",
               "host_batch", "wal_write", "wal_fsync", "manifest_rename",
               # arrays-sidecar directory commit (store/sidecar.py):
               # fires just before the rename-last that publishes the
               # mmap-able checkpoint arrays — the crash soak's torn-
               # sidecar recovery leg targets it
               "sidecar_rename",
               # taxonomy query kinds (serve/routes/taxonomy.py): the
               # packed multi-source sweep, the delta-stepping solve,
               # the Yen's batch, and the as-of historical replay
               "msbfs", "weighted", "kshortest", "asof_replay",
               # the kinds' DEVICE rungs (serve/routes/
               # taxonomy_device.py): each degrades to its host kind
               # rung when faulted
               "msbfs_device", "weighted_device", "kshortest_device",
               # the whole-graph analytics tier (serve/routes/
               # analytics.py): fired entering / leaving EVERY
               # analytics solve, host and blocked rung alike — one
               # spec line degrades the whole tier to its fallbacks
               "analytics", "analytics_finish",
               # the distributed-trace spool append (obs/dtrace.py):
               # a failed flush drops the span, never the query
               "trace_flush")

KINDS = ("error", "latency")


class InjectedFault(RuntimeError):
    """The exception a ``kind=error`` rule raises at its seam."""

    def __init__(self, site: str, detail: str = ""):
        self.site = site
        msg = f"injected fault at {site}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


def _injected_counter():
    return REGISTRY.counter(
        "bibfs_faults_injected_total",
        "Faults injected into the serving stack, by seam and kind",
        ("site", "kind"),
    )


class FaultRule:
    """One injection rule at one site (module docstring grammar)."""

    __slots__ = (
        "site", "kind", "p", "every", "times", "latency_ms", "pair",
        "calls", "fired",
    )

    def __init__(
        self,
        site: str,
        *,
        kind: str = "error",
        p: float | None = None,
        every: int | None = None,
        times: int | None = None,
        latency_ms: float = 10.0,
        pair: tuple[int, int] | None = None,
    ):
        if site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {site!r} "
                f"(known: {', '.join(KNOWN_SITES)})"
            )
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (known: {KINDS})")
        if p is not None and not (0.0 <= p <= 1.0):
            raise ValueError(f"fault probability p={p} outside [0, 1]")
        if every is not None and every < 1:
            raise ValueError(f"every={every} must be >= 1")
        if times is not None and times < 1:
            raise ValueError(f"times={times} must be >= 1")
        if p is not None and every is not None:
            # a spec must fail loudly (KNOWN_SITES note): with both,
            # p= would win and every= would be silently dead
            raise ValueError(
                "fault rule cannot combine p= and every= triggers "
                "(pick one; times= caps either)"
            )
        if p is None and every is None and times is None:
            every = 1  # bare rule: fire on every call
        self.site = site
        self.kind = kind
        self.p = p
        self.every = every
        self.times = times
        self.latency_ms = float(latency_ms)
        self.pair = pair
        self.calls = 0
        self.fired = 0

    def describe(self) -> str:
        bits = []
        if self.p is not None:
            bits.append(f"p={self.p}")
        if self.every is not None:
            bits.append(f"every={self.every}")
        if self.times is not None:
            bits.append(f"times={self.times}")
        if self.pair is not None:
            bits.append(f"pair={self.pair[0]}-{self.pair[1]}")
        if self.kind == "latency":
            bits.append(f"latency={self.latency_ms}ms")
        return f"{self.site}:{','.join(bits) or 'always'}"


def _parse_rule(text: str) -> FaultRule:
    site, _, rest = text.partition(":")
    site = site.strip()
    kw: dict = {}
    for field in filter(None, (f.strip() for f in rest.split(","))):
        key, eq, val = field.partition("=")
        if not eq:
            raise ValueError(f"bad fault field {field!r} (expected key=value)")
        key = key.strip()
        val = val.strip()
        try:
            if key == "p":
                kw["p"] = float(val)
            elif key == "every":
                kw["every"] = int(val)
            elif key == "times":
                kw["times"] = int(val)
            elif key == "kind":
                kw["kind"] = val
            elif key == "ms":
                kw["latency_ms"] = float(val)
            elif key == "pair":
                s, _, d = val.partition("-")
                kw["pair"] = (int(s), int(d))
            else:
                raise ValueError(f"unknown fault field {key!r}")
        except ValueError as e:
            if "unknown fault field" in str(e):
                raise
            raise ValueError(f"bad fault field {field!r}: {e}") from e
    return FaultRule(site, **kw)


class FaultPlan:
    """A parsed set of :class:`FaultRule` s, fired at the engine seams.

    Thread-safe (the pipelined engine fires seams from its flusher AND
    its finish worker); ``set_active(False)`` disables every rule at
    once — the chaos harness's "fault clears" edge. ``seed`` makes the
    probabilistic rules reproducible run-to-run.
    """

    def __init__(self, rules: list[FaultRule], *, seed: int = 0):
        self._rules = list(rules)
        self._by_site: dict[str, list[FaultRule]] = {}
        for r in self._rules:
            self._by_site.setdefault(r.site, []).append(r)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._active = True
        self._counter = _injected_counter()

    # ---- construction -----------------------------------------------
    @classmethod
    def parse(cls, spec: str, *, seed: int = 0) -> "FaultPlan":
        """Parse the module-docstring grammar into a plan."""
        rules = [
            _parse_rule(part)
            for part in filter(None, (p.strip() for p in spec.split(";")))
        ]
        if not rules:
            raise ValueError(f"empty fault spec {spec!r}")
        return cls(rules, seed=seed)

    @classmethod
    def from_env(cls, environ=None) -> "FaultPlan | None":
        """The engine-construction default: a plan when ``BIBFS_FAULTS``
        is set (seeded by ``BIBFS_FAULTS_SEED``, default 0), else None —
        the no-injection fast path stays one ``is None`` check."""
        environ = os.environ if environ is None else environ
        spec = environ.get(ENV_VAR, "").strip()
        if not spec:
            return None
        return cls.parse(spec, seed=int(environ.get("BIBFS_FAULTS_SEED", 0)))

    # ---- firing ------------------------------------------------------
    def set_active(self, active: bool) -> None:
        with self._lock:
            self._active = bool(active)

    @property
    def active(self) -> bool:
        return self._active

    def fire(self, site: str, pairs=None) -> None:
        """Evaluate every rule at ``site``: may sleep (latency rules),
        may raise :class:`InjectedFault`. ``pairs`` is the flush's
        query list, for ``pair=``-targeted rules."""
        rules = self._by_site.get(site)
        if not rules or not self._active:
            return
        sleep_ms = 0.0
        boom: InjectedFault | None = None
        with self._lock:
            for r in rules:
                if r.pair is not None and (
                    pairs is None or tuple(r.pair) not in (
                        (int(s), int(d)) for s, d in pairs
                    )
                ):
                    continue
                r.calls += 1
                if r.times is not None and r.fired >= r.times:
                    continue
                hit = False
                if r.p is not None:
                    hit = self._rng.random() < r.p
                elif r.every is not None:
                    hit = r.calls % r.every == 0
                elif r.times is not None:
                    hit = True  # bounded purely by the times cap above
                if not hit:
                    continue
                r.fired += 1
                self._counter.labels(site=site, kind=r.kind).inc()
                if r.kind == "latency":
                    sleep_ms += r.latency_ms
                elif boom is None:
                    boom = InjectedFault(site, r.describe())
        if sleep_ms > 0.0 or boom is not None:
            # the flight recorder's fault hook: record the trip (and
            # dump the ring, rate-limited, when a dump path is armed)
            # BEFORE the injected error propagates — the post-mortem
            # must capture the state that led here, and must never add
            # a failure of its own
            try:
                from bibfs_tpu.obs.dtrace import flight_on_fault

                flight_on_fault(site)
            except Exception:  # pragma: no cover - defensive
                pass
        if sleep_ms > 0.0:
            time.sleep(sleep_ms / 1e3)
        if boom is not None:
            raise boom

    # ---- introspection ----------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "active": self._active,
                "rules": [
                    {
                        "rule": r.describe(),
                        "calls": r.calls,
                        "fired": r.fired,
                    }
                    for r in self._rules
                ],
                "fired_total": sum(r.fired for r in self._rules),
            }

    def __repr__(self) -> str:
        return (
            "FaultPlan("
            + "; ".join(r.describe() for r in self._rules)
            + ("" if self._active else " [inactive]")
            + ")"
        )
