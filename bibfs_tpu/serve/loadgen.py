"""Open-loop arrival-rate load harness — the latency-SLO measurement.

Closed-loop benchmarks (``bench.py --serve``) measure solver throughput:
the next query is issued only when the previous one finishes, so queue
wait — the thing users actually feel — never appears. This module
measures serving: queries arrive on a fixed OPEN-LOOP schedule (query i
at ``t0 + i/rate``, whether or not the server kept up), every query's
latency is clocked from its *scheduled* arrival to its resolution, and
sustained throughput is completed-queries over the whole span including
the drain. A server that can't keep up shows it here as queue growth
and a p95/p99 blow-up — exactly the failure mode the deadline-flushing
pipelined engine exists to bound.

Two drivers, one schedule:

- the **synchronous** :class:`~bibfs_tpu.serve.engine.QueryEngine` can
  only be driven the way its API forces: the arrival thread itself
  calls ``flush()`` (at depth, and as a caller-side emulation of the
  deadline — the sync engine has no clock), so every flush BLOCKS the
  arrivals behind it;
- the **pipelined** :class:`~bibfs_tpu.serve.pipeline.PipelinedQueryEngine`
  is just submitted to — depth and deadline flushing happen on its
  background flusher, and dispatch/finish overlap.

Every completed result is verified hop-for-hop against a precomputed
serial-oracle table (paths CSR-edge-validated), and the pipelined run's
deadline compliance is checked from the engine's own worst-case
counters: no query may wait in the queue longer than ``max_wait_ms``
plus one in-flight batch time (plus a small scheduling slack for loaded
CI boxes).
"""

from __future__ import annotations

import sys
import time

import numpy as np

# generator/GIL scheduling grace when checking the deadline bound on a
# busy box: the flusher thread can lose the CPU for a few ms to the very
# load being measured without that being an SLO-logic violation
SCHED_SLACK_MS = 25.0


def sample_query_pairs(n: int, q: int, seed: int = 0) -> np.ndarray:
    """The load workload: up to ``q`` unique non-trivial (src, dst)
    pairs in shuffled order. Unique so the measurement exercises the
    solvers, not the caches; shared by every load entry point
    (``bench.py --serve-load``, ``bibfs-serve --load``) so they measure
    the same traffic."""
    rng = np.random.default_rng(seed)
    pairs = np.unique(rng.integers(0, n, size=(3 * q, 2)), axis=0)
    pairs = pairs[pairs[:, 0] != pairs[:, 1]][:q]
    rng.shuffle(pairs)
    return pairs


def _latency_hist(lats_s: list[float]) -> dict:
    """The full per-rate latency distribution, exported through the
    shared observability histogram type
    (:class:`bibfs_tpu.obs.metrics.LogHistogram`) so rate-ladder runs
    are plottable from ``bench_load.json`` — the p50/p95/p99 scalars
    alone cannot reconstruct a CDF, and the buckets here are the SAME
    geometric ladder the engines' ``/metrics`` histograms use."""
    from bibfs_tpu.obs.metrics import LogHistogram

    h = LogHistogram()
    h.record_many(lats_s)
    return h.to_dict()


def _percentiles_ms(lats_s: list[float]) -> dict:
    if not lats_s:
        return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p95_ms": 0.0,
                "p99_ms": 0.0, "max_ms": 0.0}
    a = np.sort(np.asarray(lats_s, dtype=np.float64)) * 1e3
    pick = lambda q: float(a[min(int(q * len(a)), len(a) - 1)])  # noqa: E731
    return {
        "count": len(a),
        "mean_ms": round(float(a.mean()), 4),
        "p50_ms": round(pick(0.50), 4),
        "p95_ms": round(pick(0.95), 4),
        "p99_ms": round(pick(0.99), 4),
        "max_ms": round(float(a[-1]), 4),
    }


def _verify(pairs, results, oracle, csr) -> list[str]:
    from bibfs_tpu.solvers.api import validate_path

    errors = []
    for (s, d), res in zip(pairs, results):
        s, d = int(s), int(d)
        ref = oracle[(s, d)]
        if res is None:
            errors.append(f"{s}->{d}: unresolved")
        elif res.found != ref.found or (ref.found and res.hops != ref.hops):
            errors.append(
                f"{s}->{d}: hops {res.hops} != oracle {ref.hops}"
            )
        elif ref.found and res.path is not None and not validate_path(
            csr, res.path, s, d, hops=res.hops
        ):
            errors.append(f"{s}->{d}: path failed CSR validation")
    return errors


def _drive_pipelined(engine, pairs, rate_qps):
    """Open-loop schedule against the pipelined engine: submit() never
    blocks, so arrivals stay on time by construction; latencies read the
    per-ticket resolve stamps."""
    t0 = time.perf_counter()
    tickets = []
    for i, (s, d) in enumerate(pairs):
        delay = t0 + i / rate_qps - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        tickets.append(engine.submit(int(s), int(d)))
    engine.flush()  # drain
    elapsed = time.perf_counter() - t0
    lats = []
    for i, t in enumerate(tickets):
        t.wait(timeout=60.0)
        lats.append(t.t_done - (t0 + i / rate_qps))
    return lats, elapsed, [t.result for t in tickets]


def _drive_sync(engine, pairs, rate_qps, max_wait_ms):
    """Open-loop schedule against the synchronous engine (module
    docstring): the arrival thread flushes at depth and emulates the
    deadline between arrivals, paying each flush as arrival blockage."""
    wait_s = None if max_wait_ms is None else max(max_wait_ms, 0.0) / 1e3
    t0 = time.perf_counter()
    tickets = []
    resolve_t: dict[int, float] = {}
    head = 0  # first ticket not yet seen resolved
    first_pending_t = None  # submit time of the oldest unflushed query

    def note_resolved():
        nonlocal head, first_pending_t
        now = time.perf_counter()
        while head < len(tickets) and tickets[head].result is not None:
            resolve_t.setdefault(head, now)
            head += 1
        if engine.pending == 0:
            first_pending_t = None

    for i, (s, d) in enumerate(pairs):
        sched = t0 + i / rate_qps
        while True:
            now = time.perf_counter()
            if now >= sched:
                break
            if (wait_s is not None and first_pending_t is not None
                    and now - first_pending_t >= wait_s):
                engine.flush()
                note_resolved()
                continue
            until = sched
            if wait_s is not None and first_pending_t is not None:
                until = min(until, first_pending_t + wait_s)
            time.sleep(max(until - now, 0.0))
        t = engine.submit(int(s), int(d))
        tickets.append(t)
        if t.result is not None:
            # inline resolution (trivial / cache hit): stamp NOW — the
            # head-contiguous scan below would otherwise defer it to the
            # next flush, inflating sync latencies vs the pipelined
            # driver's per-ticket resolve stamps
            resolve_t.setdefault(len(tickets) - 1, time.perf_counter())
        elif first_pending_t is None:
            first_pending_t = time.perf_counter()
        if engine.pending >= engine.flush_threshold:
            engine.flush()
        note_resolved()
    engine.flush()
    note_resolved()
    elapsed = time.perf_counter() - t0
    lats = [resolve_t[i] - (t0 + i / rate_qps) for i in range(len(tickets))]
    return lats, elapsed, [t.result for t in tickets]


def _load_point_row(rate, sync_row, pipe_row) -> dict:
    su = None
    if sync_row["sustained_qps"] and pipe_row["sustained_qps"]:
        su = round(
            pipe_row["sustained_qps"] / sync_row["sustained_qps"], 3
        )
    return {
        "offered_qps": round(float(rate), 1),
        "sync": sync_row,
        "pipelined": pipe_row,
        "sustained_speedup": su,
    }


def run_load_point(
    make_engine, pairs, rate_qps, *, pipelined: bool,
    max_wait_ms: float | None, oracle=None, csr=None,
) -> dict:
    """One (engine flavor, offered rate) measurement on a FRESH engine
    (cold caches — the point measures solving under load, not
    memoization). Returns the machine-readable metrics row."""
    engine = make_engine()
    try:
        # setup is untimed, like every bench row's graph build: resolve
        # the host solver / device graph BEFORE the first arrival so the
        # measurement sees steady-state serving, not lazy construction
        if engine._use_device():
            engine.graph
        else:
            engine._get_host_solver()
        if pipelined:
            lats, elapsed, results = _drive_pipelined(engine, pairs, rate_qps)
        else:
            lats, elapsed, results = _drive_sync(
                engine, pairs, rate_qps, max_wait_ms
            )
        errors = (
            _verify(pairs, results, oracle, csr)
            if oracle is not None else []
        )
        out = {
            "offered_qps": round(float(rate_qps), 1),
            "completed": sum(r is not None for r in results),
            "elapsed_s": round(elapsed, 4),
            "sustained_qps": round(len(results) / elapsed, 1)
            if elapsed > 0 else None,
            "latency_ms": _percentiles_ms(lats),
            "latency_hist": _latency_hist(lats),
            "ok": not errors,
            "errors": errors[:10],
        }
        if pipelined:
            stats = engine.stats()
            pipe = stats["pipeline"]
            budget_ms = (
                None if max_wait_ms is None
                else max_wait_ms + pipe["batch_service_max_ms"]
                + SCHED_SLACK_MS
            )
            out["deadline"] = {
                "max_wait_ms": max_wait_ms,
                "queue_wait_max_ms": round(pipe["queue_wait_max_ms"], 3),
                "batch_service_max_ms": round(
                    pipe["batch_service_max_ms"], 3
                ),
                "budget_ms": None if budget_ms is None
                else round(budget_ms, 3),
                "ok": True if budget_ms is None
                else pipe["queue_wait_max_ms"] <= budget_ms,
            }
            out["engine"] = {
                "flushes": pipe["flushes"],
                "depth_flushes": pipe["depth_flushes"],
                "deadline_flushes": pipe["deadline_flushes"],
                "max_queue_depth": pipe["max_queue_depth"],
                "overlap": stats["overlap"],
                "latency_ms": stats["latency_ms"],
                "host_backend": stats["host_backend"],
                "device_batches": stats["device_batches"],
                "host_queries": stats["host_queries"],
            }
        return out
    finally:
        engine.close()


def measure_capacity(make_engine, pairs) -> float:
    """Closed-loop capacity of a fresh sync engine driven the way the
    open-loop driver saturates it — flush_threshold-sized batched
    flushes (queries/s). This is the anchor the offered-rate ladder is
    scaled from; a per-query estimate would undersell the batch-
    amortized ceiling by 2-3x and leave the 'saturating' rate
    unsaturating."""
    engine = make_engine()
    try:
        step = max(engine.flush_threshold, 1)
        engine.query_many(pairs[:step])  # warm the solver + first batch
        rest = pairs[step:]
        if len(rest) == 0:
            rest = pairs  # tiny pool: re-time the (warmed) chunk
        t0 = time.perf_counter()
        for i in range(0, len(rest), step):
            engine.query_many(rest[i: i + step])
        dt = time.perf_counter() - t0
        return len(rest) / dt if dt > 0 else float("inf")
    finally:
        engine.close()


def compare_engines(
    n, edges, pairs, rates, *, max_wait_ms: float = 5.0,
    max_queue: int | None = None, max_inflight: int = 2,
    top_repeats: int = 1, verify: bool = True, **engine_kwargs,
) -> dict:
    """Sync vs pipelined under the same open-loop schedules — the
    ``bench_load.json`` payload. ``rates`` is the offered-rate ladder
    (queries/s); each point gets a fresh engine of each flavor. The
    LAST (saturating) rate runs ``top_repeats`` times per engine and
    keeps each engine's best sustained row — the headline judgment
    should reflect each engine's ceiling, not one noisy scheduler
    window (both sides get the same treatment)."""
    from bibfs_tpu.graph.csr import build_csr, canonical_pairs
    from bibfs_tpu.serve.engine import QueryEngine
    from bibfs_tpu.serve.pipeline import PipelinedQueryEngine

    cpairs = canonical_pairs(n, edges)
    oracle = csr = None
    if verify:
        from bibfs_tpu.solvers.serial import solve_serial_csr

        csr = build_csr(n, pairs=cpairs)
        oracle = {
            (int(s), int(d)): solve_serial_csr(n, *csr, int(s), int(d))
            for s, d in {(int(s), int(d)) for s, d in pairs}
        }

    def make_sync():
        return QueryEngine(n, edges, pairs=cpairs, **engine_kwargs)

    def make_pipe():
        return PipelinedQueryEngine(
            n, edges, pairs=cpairs, max_wait_ms=max_wait_ms,
            max_queue=max_queue, max_inflight=max_inflight,
            **engine_kwargs,
        )

    points = []
    # harness-level: the default 5 ms GIL switch interval turns every
    # producer<->pipeline thread handoff into a multi-ms convoy on small
    # hosts — measured here as ~5 ms per handoff at sub-ms batch times.
    # Serving processes tune this; so does the harness (set just around
    # the driven runs, restored after).
    old_si = sys.getswitchinterval()
    sys.setswitchinterval(5e-4)
    try:
        for i, rate in enumerate(rates):
            reps = max(top_repeats, 1) if i == len(rates) - 1 else 1
            sync_row = pipe_row = None
            deadline_all_ok = True
            worst_qwait = 0.0
            for _ in range(reps):
                s = run_load_point(
                    make_sync, pairs, rate, pipelined=False,
                    max_wait_ms=max_wait_ms, oracle=oracle, csr=csr,
                )
                p = run_load_point(
                    make_pipe, pairs, rate, pipelined=True,
                    max_wait_ms=max_wait_ms, oracle=oracle, csr=csr,
                )
                d = p.get("deadline", {})
                deadline_all_ok = deadline_all_ok and d.get("ok", True)
                worst_qwait = max(
                    worst_qwait, d.get("queue_wait_max_ms", 0.0)
                )
                if (sync_row is None
                        or (s["sustained_qps"] or 0)
                        > (sync_row["sustained_qps"] or 0)):
                    sync_row = s
                if (pipe_row is None
                        or (p["sustained_qps"] or 0)
                        > (pipe_row["sustained_qps"] or 0)):
                    pipe_row = p
            if "deadline" in pipe_row:
                # an SLO claim may not select away its counterexamples:
                # the kept row is the best-throughput one, but deadline
                # compliance aggregates over EVERY repeat
                pipe_row["deadline"]["ok"] = (
                    pipe_row["deadline"]["ok"] and deadline_all_ok
                )
                pipe_row["deadline"]["queue_wait_max_ms_all_reps"] = round(
                    worst_qwait, 3
                )
            points.append(_load_point_row(rate, sync_row, pipe_row))
    finally:
        sys.setswitchinterval(old_si)
    top = points[-1] if points else None
    return {
        "n": int(n),
        "queries_per_point": len(pairs),
        "max_wait_ms": max_wait_ms,
        "max_queue": max_queue,
        "rates": points,
        # the headline claims, judged at the highest (saturating) rate
        "pipelined_beats_sync": bool(
            top and top["sustained_speedup"] and top["sustained_speedup"] > 1.0
        ),
        "deadline_ok": all(
            p["pipelined"].get("deadline", {}).get("ok", True)
            for p in points
        ),
        "verified_vs_oracle": all(
            p["sync"]["ok"] and p["pipelined"]["ok"] for p in points
        ),
    }
