"""Open-loop arrival-rate load harness — the latency-SLO measurement.

Closed-loop benchmarks (``bench.py --serve``) measure solver throughput:
the next query is issued only when the previous one finishes, so queue
wait — the thing users actually feel — never appears. This module
measures serving: queries arrive on a fixed OPEN-LOOP schedule (query i
at ``t0 + i/rate``, whether or not the server kept up), every query's
latency is clocked from its *scheduled* arrival to its resolution, and
sustained throughput is completed-queries over the whole span including
the drain. A server that can't keep up shows it here as queue growth
and a p95/p99 blow-up — exactly the failure mode the deadline-flushing
pipelined engine exists to bound.

Two drivers, one schedule:

- the **synchronous** :class:`~bibfs_tpu.serve.engine.QueryEngine` can
  only be driven the way its API forces: the arrival thread itself
  calls ``flush()`` (at depth, and as a caller-side emulation of the
  deadline — the sync engine has no clock), so every flush BLOCKS the
  arrivals behind it;
- the **pipelined** :class:`~bibfs_tpu.serve.pipeline.PipelinedQueryEngine`
  is just submitted to — depth and deadline flushing happen on its
  background flusher, and dispatch/finish overlap.

Every completed result is verified hop-for-hop against a precomputed
serial-oracle table (paths CSR-edge-validated), and the pipelined run's
deadline compliance is checked from the engine's own worst-case
counters: no query may wait in the queue longer than ``max_wait_ms``
plus one in-flight batch time (plus a small scheduling slack for loaded
CI boxes).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

import numpy as np

# generator/GIL scheduling grace when checking the deadline bound on a
# busy box: the flusher thread can lose the CPU for a few ms to the very
# load being measured without that being an SLO-logic violation
SCHED_SLACK_MS = 25.0


def sample_query_pairs(n: int, q: int, seed: int = 0) -> np.ndarray:
    """The load workload: up to ``q`` unique non-trivial (src, dst)
    pairs in shuffled order. Unique so the measurement exercises the
    solvers, not the caches; shared by every load entry point
    (``bench.py --serve-load``, ``bibfs-serve --load``) so they measure
    the same traffic."""
    rng = np.random.default_rng(seed)
    pairs = np.unique(rng.integers(0, n, size=(3 * q, 2)), axis=0)
    pairs = pairs[pairs[:, 0] != pairs[:, 1]][:q]
    rng.shuffle(pairs)
    return pairs


def sample_skewed_pairs(
    n: int, q: int, *, seed: int = 0, skew: float = 1.1,
    repeat_fraction: float = 0.25, pool: int = 64,
    degrees=None,
) -> np.ndarray:
    """The serving-shaped workload: ``q`` (src, dst) pairs whose endpoint
    popularity is Zipf-distributed and whose pair stream is repeat-heavy
    — the "millions of users" traffic the distance-oracle tier exists
    for, seeded and fully reproducible (the ``--pair-skew`` mode on
    ``bench.py --serve-load`` / ``--serve-oracle``).

    - **endpoint skew**: each endpoint is drawn by Zipf rank
      (``P(rank r) ∝ r^-skew``) over the vertices ranked by
      ``(degree desc, id)`` when ``degrees`` is given (ids alone
      otherwise) — hot traffic hammers the high-degree core, which is
      exactly the set landmark selection seeds from
      (``oracle/landmarks.py``: same ranking key, by construction);
    - **pair repeats**: ``repeat_fraction`` of the stream re-issues
      pairs from a hot pool of the first ``pool`` sampled pairs, with
      the pool itself Zipf-weighted — repeat AND near-repeat traffic
      (same hub, varying far endpoint) in one stream.

    Self-pairs are re-ranked away, so every returned pair is
    non-trivial. Returns ``int64 [q, 2]``.
    """
    if q < 1:
        return np.zeros((0, 2), dtype=np.int64)
    rng = np.random.default_rng(seed)
    order = (
        np.lexsort((np.arange(n), -np.asarray(degrees)))
        if degrees is not None else np.arange(n)
    )
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), float(skew))
    w /= w.sum()
    ranks = rng.choice(n, size=(q, 2), p=w)
    same = ranks[:, 0] == ranks[:, 1]
    while same.any():  # re-rank the colliding endpoint (stays skewed)
        ranks[same, 1] = rng.choice(n, size=int(same.sum()), p=w)
        same = ranks[:, 0] == ranks[:, 1]
    pairs = order[ranks].astype(np.int64)
    pool = int(min(pool, q))
    if pool > 0 and repeat_fraction > 0 and q > pool:
        hot = pairs[:pool].copy()
        wp = 1.0 / np.power(
            np.arange(1, pool + 1, dtype=np.float64), float(skew)
        )
        wp /= wp.sum()
        mask = rng.random(q) < float(repeat_fraction)
        mask[:pool] = False  # the pool itself stays as drawn
        m = int(mask.sum())
        if m:
            pairs[mask] = hot[rng.choice(pool, size=m, p=wp)]
    return pairs


# ---- query-mix traffic (the taxonomy workload) -----------------------

#: mix-spec aliases -> canonical query kinds (bibfs_tpu/query)
_MIX_ALIASES = {
    "pt": "pt", "p2p": "pt",
    "ms": "msbfs", "msbfs": "msbfs",
    "weighted": "weighted", "w": "weighted",
    "kshortest": "kshortest", "ks": "kshortest",
    "asof": "asof",
}


def parse_query_mix(spec: str) -> dict:
    """Parse a ``--mix`` spec (``pt=0.7,ms=0.2,weighted=0.1``) into
    normalized per-kind weights over the canonical kinds
    (``pt``/``msbfs``/``weighted``/``kshortest``/``asof``). Unknown
    kinds and non-positive totals fail loudly — a typo'd mix must not
    silently soak the wrong taxonomy."""
    weights: dict[str, float] = {}
    for field in filter(None, (f.strip() for f in str(spec).split(","))):
        key, eq, val = field.partition("=")
        kind = _MIX_ALIASES.get(key.strip().lower())
        if not eq or kind is None:
            raise ValueError(
                f"bad mix field {field!r} (expected kind=weight with "
                f"kind in {sorted(set(_MIX_ALIASES))})"
            )
        w = float(val)
        if w < 0:
            raise ValueError(f"negative mix weight in {field!r}")
        weights[kind] = weights.get(kind, 0.0) + w
    total = sum(weights.values())
    if total <= 0:
        raise ValueError(f"query mix {spec!r} sums to zero")
    return {k: w / total for k, w in weights.items() if w > 0}


def sample_query_mix(n: int, q: int, mix: dict, *, seed: int = 0,
                     ms_sources: int = 16, k: int = 3,
                     weight_seed: int = 0, versions=()) -> list:
    """``q`` typed taxonomy queries drawn from a ``parse_query_mix``
    mix — the traffic shape for mixed-taxonomy soaks (``bench.py
    --serve-queries``, ``--mix`` on the CLIs). ``ms_sources`` is each
    MultiSource query's source-set size, ``versions`` the historical
    store versions ``asof`` queries draw from (an ``asof`` weight with
    no versions falls back to ``pt`` — the mix parser cannot know the
    store's history). Self-pairs are re-drawn; fully reproducible per
    seed."""
    from bibfs_tpu.query import (
        AsOf,
        KShortest,
        MultiSource,
        PointToPoint,
        Weighted,
    )

    mix = dict(mix)
    if mix.get("asof") and not versions:
        mix["pt"] = mix.get("pt", 0.0) + mix.pop("asof")
    kinds = sorted(mix)
    probs = np.array([mix[kd] for kd in kinds], dtype=np.float64)
    probs /= probs.sum()
    rng = np.random.default_rng(seed)
    draws = rng.choice(len(kinds), size=q, p=probs)

    def pair():
        s = int(rng.integers(n))
        d = int(rng.integers(n))
        while d == s:
            d = int(rng.integers(n))
        return s, d

    out = []
    for i in range(q):
        kind = kinds[draws[i]]
        s, d = pair()
        if kind == "pt":
            out.append(PointToPoint(s, d))
        elif kind == "msbfs":
            m = min(int(ms_sources), n - 1)
            sources = rng.choice(n, size=m, replace=False)
            out.append(MultiSource(
                tuple(int(x) for x in sources), d,
            ))
        elif kind == "weighted":
            out.append(Weighted(s, d, weight_seed=int(weight_seed)))
        elif kind == "kshortest":
            out.append(KShortest(s, d, k=int(k)))
        else:  # asof
            v = int(versions[int(rng.integers(len(versions)))])
            out.append(AsOf(PointToPoint(s, d), v))
    return out


def _latency_hist(lats_s: list[float]) -> dict:
    """The full per-rate latency distribution, exported through the
    shared observability histogram type
    (:class:`bibfs_tpu.obs.metrics.LogHistogram`) so rate-ladder runs
    are plottable from ``bench_load.json`` — the p50/p95/p99 scalars
    alone cannot reconstruct a CDF, and the buckets here are the SAME
    geometric ladder the engines' ``/metrics`` histograms use."""
    from bibfs_tpu.obs.metrics import LogHistogram

    h = LogHistogram()
    h.record_many(lats_s)
    return h.to_dict()


def _percentiles_ms(lats_s: list[float]) -> dict:
    if not lats_s:
        return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p95_ms": 0.0,
                "p99_ms": 0.0, "max_ms": 0.0}
    a = np.sort(np.asarray(lats_s, dtype=np.float64)) * 1e3
    pick = lambda q: float(a[min(int(q * len(a)), len(a) - 1)])  # noqa: E731
    return {
        "count": len(a),
        "mean_ms": round(float(a.mean()), 4),
        "p50_ms": round(pick(0.50), 4),
        "p95_ms": round(pick(0.95), 4),
        "p99_ms": round(pick(0.99), 4),
        "max_ms": round(float(a[-1]), 4),
    }


def _verify(pairs, results, oracle, csr) -> list[str]:
    from bibfs_tpu.solvers.api import validate_path

    errors = []
    for (s, d), res in zip(pairs, results):
        s, d = int(s), int(d)
        ref = oracle[(s, d)]
        if res is None:
            errors.append(f"{s}->{d}: unresolved")
        elif res.found != ref.found or (ref.found and res.hops != ref.hops):
            errors.append(
                f"{s}->{d}: hops {res.hops} != oracle {ref.hops}"
            )
        elif ref.found and res.path is not None and not validate_path(
            csr, res.path, s, d, hops=res.hops
        ):
            errors.append(f"{s}->{d}: path failed CSR validation")
    return errors


def _drive_pipelined(engine, pairs, rate_qps):
    """Open-loop schedule against the pipelined engine: submit() never
    blocks, so arrivals stay on time by construction; latencies read the
    per-ticket resolve stamps."""
    t0 = time.perf_counter()
    tickets = []
    for i, (s, d) in enumerate(pairs):
        delay = t0 + i / rate_qps - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        tickets.append(engine.submit(int(s), int(d)))
    engine.flush()  # drain
    elapsed = time.perf_counter() - t0
    lats = []
    for i, t in enumerate(tickets):
        t.wait(timeout=60.0)
        lats.append(t.t_done - (t0 + i / rate_qps))
    return lats, elapsed, [t.result for t in tickets]


def _drive_sync(engine, pairs, rate_qps, max_wait_ms):
    """Open-loop schedule against the synchronous engine (module
    docstring): the arrival thread flushes at depth and emulates the
    deadline between arrivals, paying each flush as arrival blockage."""
    wait_s = None if max_wait_ms is None else max(max_wait_ms, 0.0) / 1e3
    t0 = time.perf_counter()
    tickets = []
    resolve_t: dict[int, float] = {}
    head = 0  # first ticket not yet seen resolved
    first_pending_t = None  # submit time of the oldest unflushed query

    def note_resolved():
        nonlocal head, first_pending_t
        now = time.perf_counter()
        while head < len(tickets) and tickets[head].result is not None:
            resolve_t.setdefault(head, now)
            head += 1
        if engine.pending == 0:
            first_pending_t = None

    for i, (s, d) in enumerate(pairs):
        sched = t0 + i / rate_qps
        while True:
            now = time.perf_counter()
            if now >= sched:
                break
            if (wait_s is not None and first_pending_t is not None
                    and now - first_pending_t >= wait_s):
                engine.flush()
                note_resolved()
                continue
            until = sched
            if wait_s is not None and first_pending_t is not None:
                until = min(until, first_pending_t + wait_s)
            time.sleep(max(until - now, 0.0))
        t = engine.submit(int(s), int(d))
        tickets.append(t)
        if t.result is not None:
            # inline resolution (trivial / cache hit): stamp NOW — the
            # head-contiguous scan below would otherwise defer it to the
            # next flush, inflating sync latencies vs the pipelined
            # driver's per-ticket resolve stamps
            resolve_t.setdefault(len(tickets) - 1, time.perf_counter())
        elif first_pending_t is None:
            first_pending_t = time.perf_counter()
        if engine.pending >= engine.flush_threshold:
            engine.flush()
        note_resolved()
    engine.flush()
    note_resolved()
    elapsed = time.perf_counter() - t0
    lats = [resolve_t[i] - (t0 + i / rate_qps) for i in range(len(tickets))]
    return lats, elapsed, [t.result for t in tickets]


def _load_point_row(rate, sync_row, pipe_row) -> dict:
    su = None
    if sync_row["sustained_qps"] and pipe_row["sustained_qps"]:
        su = round(
            pipe_row["sustained_qps"] / sync_row["sustained_qps"], 3
        )
    return {
        "offered_qps": round(float(rate), 1),
        "sync": sync_row,
        "pipelined": pipe_row,
        "sustained_speedup": su,
    }


def run_load_point(
    make_engine, pairs, rate_qps, *, pipelined: bool,
    max_wait_ms: float | None, oracle=None, csr=None,
) -> dict:
    """One (engine flavor, offered rate) measurement on a FRESH engine
    (cold caches — the point measures solving under load, not
    memoization). Returns the machine-readable metrics row."""
    engine = make_engine()
    try:
        # setup is untimed, like every bench row's graph build: resolve
        # the host solver / device graph BEFORE the first arrival so the
        # measurement sees steady-state serving, not lazy construction
        if engine._use_device():
            engine.graph
        else:
            engine._get_host_solver()
        if pipelined:
            lats, elapsed, results = _drive_pipelined(engine, pairs, rate_qps)
        else:
            lats, elapsed, results = _drive_sync(
                engine, pairs, rate_qps, max_wait_ms
            )
        errors = (
            _verify(pairs, results, oracle, csr)
            if oracle is not None else []
        )
        out = {
            "offered_qps": round(float(rate_qps), 1),
            "completed": sum(r is not None for r in results),
            "elapsed_s": round(elapsed, 4),
            "sustained_qps": round(len(results) / elapsed, 1)
            if elapsed > 0 else None,
            "latency_ms": _percentiles_ms(lats),
            "latency_hist": _latency_hist(lats),
            "ok": not errors,
            "errors": errors[:10],
        }
        if pipelined:
            stats = engine.stats()
            pipe = stats["pipeline"]
            budget_ms = (
                None if max_wait_ms is None
                else max_wait_ms + pipe["batch_service_max_ms"]
                + SCHED_SLACK_MS
            )
            out["deadline"] = {
                "max_wait_ms": max_wait_ms,
                "queue_wait_max_ms": round(pipe["queue_wait_max_ms"], 3),
                "batch_service_max_ms": round(
                    pipe["batch_service_max_ms"], 3
                ),
                "budget_ms": None if budget_ms is None
                else round(budget_ms, 3),
                "ok": True if budget_ms is None
                else pipe["queue_wait_max_ms"] <= budget_ms,
            }
            out["engine"] = {
                "flushes": pipe["flushes"],
                "depth_flushes": pipe["depth_flushes"],
                "deadline_flushes": pipe["deadline_flushes"],
                "max_queue_depth": pipe["max_queue_depth"],
                "overlap": stats["overlap"],
                "latency_ms": stats["latency_ms"],
                "host_backend": stats["host_backend"],
                "device_batches": stats["device_batches"],
                "host_queries": stats["host_queries"],
            }
        return out
    finally:
        engine.close()


def run_chaos(
    n,
    edges,
    *,
    queries: int = 600,
    min_fault_fraction: float = 0.10,
    fault_spec: str | None = None,
    rate_qps: float = 250.0,
    # the deadline x rate product must reach the device crossover or
    # every batch pops sub-threshold and the fault plan's device seams
    # never run: 250 q/s x 60 ms ~= 15 queries/batch >= threshold 8
    max_wait_ms: float = 60.0,
    flush_threshold: int = 8,
    max_batch: int = 128,
    breaker_reset_s: float = 0.75,
    health_window_s: float = 2.0,
    recovery_bound_s: float = 10.0,
    seed: int = 0,
    **engine_kwargs,
) -> dict:
    """The chaos/soak measurement (``bench.py --serve-chaos``): the
    open-loop load generator driven against the REAL pipelined engine
    while a :class:`~bibfs_tpu.serve.faults.FaultPlan` fails a fraction
    of its device flushes, then with the faults cleared — asserting the
    three robustness claims the resilience layer makes:

    1. **zero lost tickets** — every submitted query resolves with a
       result or a structured :class:`QueryError`; nothing strands;
    2. **oracle-correct survivors** — every non-failed result matches
       the serial oracle hop-for-hop (the fallback ladder may not trade
       correctness for availability);
    3. **bounded recovery** — after the fault schedule clears, probe
       traffic returns the health state machine to ``ready`` within
       ``recovery_bound_s`` (the breaker's half-open probe closes it,
       the error window ages out).

    Default schedule: phase 1 serves ~2/3 of the traffic (submitted
    AND drained, so the faults cover the batches' execution, not just
    their submission) with ``device:every=2; device_finish:every=3``
    active — deterministic injection at both device seams (the
    dispatch failure the flusher retries and the mid-execution failure
    the finish worker recovers), well above the ``min_fault_fraction``
    gate and reproducible run-to-run where a probabilistic rule over a
    handful of flushes is a coin toss. Phase 2 serves the rest
    fault-free, then probe batches drive the breaker's recovery.
    Returns the machine-readable ``bench_chaos.json`` payload (``ok``
    aggregates the claims; the injected device-seam fraction must
    reach ``min_fault_fraction``).
    """
    from bibfs_tpu.graph.csr import build_csr, canonical_pairs
    from bibfs_tpu.serve.buckets import ExecutableCache
    from bibfs_tpu.serve.faults import FaultPlan
    from bibfs_tpu.serve.pipeline import PipelinedQueryEngine
    from bibfs_tpu.serve.resilience import CircuitBreaker
    from bibfs_tpu.solvers.serial import solve_serial_csr

    if fault_spec is None:
        # every=2 on the launch seam: the fault phase is guaranteed at
        # least two device launches (its traffic exceeds one max_batch
        # pop), so the deterministic rule ALWAYS fires — a sparser rule
        # can land every fault-phase batch on a non-multiple call count
        # when backlog-adaptive batching collapses the phase into a
        # couple of big flushes, and a chaos gate that sometimes
        # injects nothing is itself flaky
        fault_spec = "device:every=2;device_finish:every=3"
    plan = FaultPlan.parse(fault_spec, seed=seed)
    plan.set_active(False)  # warmup runs clean

    cpairs = canonical_pairs(n, edges)
    csr = build_csr(n, pairs=cpairs)
    # traffic + probe pools, all unique so the measurement exercises the
    # solvers (and the fallback ladder), not the caches. The probe pool
    # is deep: each recovery poll burns one UNIQUE device-flush batch
    # (a cache-served probe would never drive the breaker's half-open
    # probe), and the breaker's reset window must fit inside it
    pool = sample_query_pairs(
        n, queries + 512 * flush_threshold, seed=seed
    )
    pairs = pool[:queries]
    probes = pool[queries:]
    oracle = {
        (int(s), int(d)): solve_serial_csr(n, *csr, int(s), int(d))
        for s, d in pairs
    }

    engine = PipelinedQueryEngine(
        n, edges, pairs=cpairs,
        flush_threshold=flush_threshold, max_batch=max_batch,
        device_batches=True, exec_cache=ExecutableCache(),
        max_wait_ms=max_wait_ms, faults=plan,
        breaker=CircuitBreaker(reset_s=breaker_reset_s),
        health_window_s=health_window_s,
        **engine_kwargs,
    )
    t_setup = time.perf_counter()
    try:
        # warm the device program (compile excluded, like every bench)
        engine.query_many(
            [(int(s), int(d)) for s, d in probes[:flush_threshold]]
        )
        split = max((2 * len(pairs)) // 3, 1)

        def drive(chunk, t0):
            tickets = []
            for i, (s, d) in enumerate(chunk):
                delay = t0 + i / rate_qps - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                tickets.append(engine.submit(int(s), int(d)))
            return tickets

        def drain_bounded() -> bool:
            """flush() with a bound: a stranded ticket (the bug class
            this harness exists to catch) must come back as a
            zero_lost=false verdict, never as a hang that eats the CI
            timeout with no artifact."""
            try:
                engine.flush(timeout=60.0)
                return True
            except TimeoutError:
                return False

        plan.set_active(True)
        t_fault = time.perf_counter()
        tickets = drive(pairs[:split], t_fault)
        drained = drain_bounded()  # faults cover phase 1's EXECUTION
        plan.set_active(False)
        t_clear = time.perf_counter()
        tickets += drive(pairs[split:], t_clear)
        drained = drain_bounded() and drained

        lost, failed, mismatches = [], [], []
        # a failed drain already proved stranding: collect the ticket
        # states fast instead of paying 60 s per stranded waiter
        wait_s = 60.0 if drained else 2.0
        for (s, d), t in zip(pairs, tickets):
            s, d = int(s), int(d)
            try:
                res = t.wait(timeout=wait_s)
            except TimeoutError:
                lost.append((s, d))
                wait_s = 2.0  # peers of a stranded ticket fail fast
                continue
            except Exception as e:
                failed.append(
                    {"query": [s, d], "kind": getattr(e, "kind", "?"),
                     "error": str(e)[:200]}
                )
                continue
            ref = oracle[(s, d)]
            if res.found != ref.found or (
                ref.found and res.hops != ref.hops
            ):
                mismatches.append(f"{s}->{d}: {res.hops} != {ref.hops}")
            elif ref.found and res.path is not None and not _validate(
                csr, res, s, d
            ):
                mismatches.append(f"{s}->{d}: path failed validation")

        # recovery: probe batches give the breaker its half-open probe
        # and keep the health reads honest (a dead engine would never
        # flip back to ready on its own). Guard FIRST for stranded
        # tickets: a probe's query_many would flush(), and flush()
        # blocks while anything is still outstanding — the harness must
        # report the zero_lost violation, not hang on it. The bound is
        # measured from PROBE start (probe_s): the oracle-verify pass
        # above can eat arbitrary wall time on a loaded box, and slow
        # verification must not masquerade as slow recovery (recovery_s
        # still reports wall time since the faults cleared, for the
        # record)
        recovery_s = probe_s = None
        stranded_pre = engine.stats()["pipeline"]["outstanding"]
        probe_at = flush_threshold  # first threshold pairs warmed up
        t_probe0 = time.perf_counter()
        deadline = t_probe0 + recovery_bound_s
        while not lost and stranded_pre == 0:
            state = engine.health_snapshot()["state"]
            if state == "ready":
                now = time.perf_counter()
                probe_s = now - t_probe0
                recovery_s = now - t_clear
                break
            if time.perf_counter() > deadline:
                break
            batch = probes[probe_at: probe_at + flush_threshold]
            probe_at += flush_threshold
            if probe_at + flush_threshold > len(probes):
                probe_at = flush_threshold  # wrap (cache-served repeats)
            # bounded probe (submit + flush(timeout), NOT query_many,
            # whose internal flush has no bound): a ticket stranded
            # DURING probing must end the measurement with a verdict —
            # the stats() outstanding count feeds zero_lost below —
            # never hang the harness
            for s, d in batch:
                engine.submit(int(s), int(d))
            try:
                engine.flush(timeout=10.0)
            except TimeoutError:
                break
            time.sleep(0.02)

        stats = engine.stats()
        stranded = stats["pipeline"]["outstanding"]
        fstats = plan.stats()
        device_rules = [
            r for r in fstats["rules"] if r["rule"].startswith("device")
        ]
        dev_calls = sum(r["calls"] for r in device_rules)
        dev_fired = sum(r["fired"] for r in device_rules)
        fault_fraction = dev_fired / dev_calls if dev_calls else 0.0
        recovered = probe_s is not None and probe_s <= recovery_bound_s
        out = {
            "n": int(n),
            "queries": len(pairs),
            "fault_spec": fault_spec,
            "min_fault_fraction": min_fault_fraction,
            "device_fault_fraction": round(fault_fraction, 4),
            "rate_qps": rate_qps,
            "faults": fstats,
            "tickets": {
                "submitted": len(tickets),
                "resolved": len(tickets) - len(lost) - len(failed),
                "failed": len(failed),
                "lost": len(lost),
                "stranded_outstanding": stranded,
            },
            "failed_sample": failed[:10],
            "mismatches": mismatches[:10],
            "fault_phase_s": round(t_clear - t_fault, 3),
            "recovery": {
                "bound_s": recovery_bound_s,
                "recovery_s": (
                    None if recovery_s is None else round(recovery_s, 3)
                ),
                "probe_s": (
                    None if probe_s is None else round(probe_s, 3)
                ),
                "recovered": recovered,
                "final_health": engine.health_snapshot(),
            },
            "resilience": stats["resilience"],
            "engine": {
                "device_batches": stats["device_batches"],
                "host_queries": stats["host_queries"],
                "flushes": stats["pipeline"]["flushes"],
                "latency_ms": stats["latency_ms"],
            },
            "setup_to_drain_s": round(time.perf_counter() - t_setup, 3),
            # the three claims, plus "nothing stranded in the pipeline"
            "zero_lost": not lost and stranded == 0 and drained,
            "verified_vs_oracle": not mismatches,
            "recovery_ok": recovered,
            "faults_injected": fstats["fired_total"],
        }
        out["ok"] = bool(
            out["zero_lost"] and out["verified_vs_oracle"]
            and out["recovery_ok"]
            and fault_fraction >= min_fault_fraction
        )
        return out
    finally:
        engine.close()


def run_churn(
    n,
    edges,
    *,
    epochs: int = 4,
    queries_per_epoch: int = 150,
    updates_per_epoch: int = 16,
    twin_fraction: float = 0.25,
    rate_qps: float = 200.0,
    max_wait_ms: float = 40.0,
    flush_threshold: int = 8,
    max_batch: int = 64,
    stall_bound_ms: float = 2500.0,
    seed: int = 0,
    **engine_kwargs,
) -> dict:
    """The graph-store churn soak (``bench.py --serve-update``): the
    open-loop load generator driven against a pipelined engine serving a
    LIVE :class:`~bibfs_tpu.store.GraphStore` while edge updates land
    and snapshots hot-swap under the traffic — asserting the claims the
    store makes:

    1. **exact answers under churn** — traffic runs in epochs; each
       epoch applies one batched edge update (crossing the store's
       compaction threshold, so a background rebuild + atomic hot-swap
       races the epoch's own queries; odd epochs also force a
       synchronous ``compact()`` from a side thread mid-traffic) and
       every surviving answer must match a from-scratch serial oracle
       on the POST-UPDATE edge set — whether it resolved through the
       delta overlay, the old snapshot's in-flight batch, or the
       swapped-in snapshot;
    2. **zero lost tickets across swaps** — every submitted query
       resolves (result or structured error); nothing strands in the
       pipeline through any number of hot-swaps;
    3. **bounded swap stall** — the worst submit-to-resolve latency over
       the whole churn (which brackets every swap) stays under
       ``stall_bound_ms``: a swap is a pointer flip, not a rebuild on
       the serving path;
    4. **zero recompiles** — updates are degree-capped so every rebuilt
       snapshot lands in the same ELL shape bucket, and a second graph
       (``twin``: the same graph under a vertex relabeling, so the same
       bucket by construction) serves ``twin_fraction`` of the traffic;
       the gate is derived from the compile SENTINEL
       (:mod:`bibfs_tpu.analysis.compilegraph`, installed for the
       soak): zero compilation events recorded after warmup, through
       all swaps and both graphs. The sentinel counts actual XLA
       trace+lower events, which is strictly stronger than the old
       hand-diffed :class:`~bibfs_tpu.serve.buckets.ExecutableCache`
       ``program_counts()`` snapshot — a retrace that reuses a noted
       key would pass the counter diff and still stall the serving
       path; the counter diff rides along in the artifact as the
       accounting view.

    Returns the machine-readable ``bench_update.json`` payload (``ok``
    aggregates the gates)."""
    from bibfs_tpu.graph.csr import build_csr, canonical_pairs
    from bibfs_tpu.serve.buckets import ExecutableCache
    from bibfs_tpu.serve.pipeline import PipelinedQueryEngine
    from bibfs_tpu.solvers.serial import solve_serial_csr
    from bibfs_tpu.store import GraphStore

    rng = np.random.default_rng(seed)
    cpairs = canonical_pairs(n, edges)
    und = cpairs[cpairs[:, 0] < cpairs[:, 1]]
    # the twin: the same graph under a fixed vertex relabeling — same
    # degree multiset, same ELL width bucket, different digest/answers
    perm = rng.permutation(n)
    twin_und = np.sort(perm[und], axis=1)

    # the threshold sits just ABOVE one epoch's update batch: an even
    # epoch leaves its delta pending, so the overlay answers that
    # epoch's main-graph traffic exactly (the route the soak must
    # exercise); the NEXT epoch's batch crosses the threshold and kicks
    # the background rebuild racing that epoch's queries, and odd
    # epochs additionally force a synchronous fold mid-stream.
    store = GraphStore(compact_threshold=updates_per_epoch + 1)
    store.add("main", n, pairs=cpairs)
    store.add("twin", n, twin_und)
    twin_csr = build_csr(n, twin_und)
    twin_oracle: dict = {}

    # live main-graph state, maintained edge-exactly by the harness: the
    # per-epoch oracle rebuilds from this set. Updates never touch the
    # max-degree vertex and cap every endpoint's degree strictly below
    # it, so the rebuilt ELL width bucket (and with it the compiled
    # program identity) provably cannot move.
    live = set(map(tuple, und.tolist()))
    deg = np.bincount(und.ravel(), minlength=n)
    pinned = int(np.argmax(deg))
    deg_cap = int(deg[pinned]) - 1

    def sample_updates():
        dels, adds = [], []
        attempts = 0
        while len(dels) < updates_per_epoch // 2 and attempts < 10000:
            attempts += 1
            e = tuple(map(int, rng.choice(list(live))))
            if pinned in e or e in dels:
                continue
            dels.append(e)
        pending = set(dels)
        while (len(adds) + len(dels) < updates_per_epoch
               and attempts < 20000):
            attempts += 1
            u, v = int(rng.integers(0, n)), int(rng.integers(0, n))
            if u == v:
                continue
            e = (u, v) if u < v else (v, u)
            if e in live and e not in pending:
                continue  # already present (and not being deleted)
            if e in adds:
                continue
            if pinned in e or deg[u] + 1 > deg_cap or deg[v] + 1 > deg_cap:
                continue
            if e in pending:
                continue  # adding back a same-epoch delete would cancel
            adds.append(e)
        return adds, dels

    # the retrace sentinel IS the zero-recompiles gate (docstring):
    # installed before the engine exists so warmup compiles are
    # visible — and UNINSTALLED on the way out unless something else
    # (conftest under BIBFS_COMPILE_CHECK=1) owned it first: the soak
    # must not leave jax's pxla compile logging hijacked for the rest
    # of an embedding process that never opted in
    from bibfs_tpu.analysis import compilegraph

    _owns_sentinel = not compilegraph.enabled()
    sentinel = compilegraph.install()
    engine = None
    try:
        exec_cache = ExecutableCache()
        engine = PipelinedQueryEngine(
            store=store, graph="main",
            flush_threshold=flush_threshold, max_batch=max_batch,
            device_batches=True, exec_cache=exec_cache,
            max_wait_ms=max_wait_ms,
            **engine_kwargs,
        )
        t_setup = time.perf_counter()
        epochs_out = []
        lost, failed, mismatches = [], [], []
        max_lat_s = 0.0
        # warm the (single-rung) batch program through BOTH graphs with
        # fresh unique pairs per round until the program set stabilizes;
        # the baseline taken here is what every later swap is gated
        # against. The twin warms after main: its flushes landing as
        # pure hits IS the cross-graph reuse claim.
        warm_pool = sample_query_pairs(n, 8 * max_batch, seed=seed + 99)
        warm_at = 0
        programs_after = {}
        for g in ("main", "twin"):
            for _ in range(4):
                before = exec_cache.stats()["programs"]
                chunk = warm_pool[warm_at: warm_at + max_batch]
                warm_at += max_batch
                engine.query_many(
                    [(int(s), int(d)) for s, d in chunk], graph=g
                )
                if before == exec_cache.stats()["programs"] and before:
                    break
            programs_after[g] = exec_cache.stats()["programs"]
        baseline = exec_cache.stats()
        compiles_baseline = sentinel.total_compiles()
        cross_graph_reuse = (
            programs_after["twin"] == programs_after["main"]
        )

        def drain_bounded() -> bool:
            try:
                engine.flush(timeout=60.0)
                return True
            except TimeoutError:
                return False

        drained = True
        versions_seen = {store.current("main").version}
        for epoch in range(epochs):
            adds, dels = sample_updates()
            out = store.update("main", adds=adds, dels=dels)
            live.difference_update(dels)
            live.update(adds)
            for u, v in dels:
                deg[u] -= 1
                deg[v] -= 1
            for u, v in adds:
                deg[u] += 1
                deg[v] += 1
            epoch_edges = np.array(sorted(live), dtype=np.int64)
            csr = build_csr(n, epoch_edges)
            pairs = sample_query_pairs(
                n, queries_per_epoch, seed=seed + 7 * epoch + 1
            )
            n_twin = int(len(pairs) * twin_fraction)
            graphs = (["twin"] * n_twin
                      + ["main"] * (len(pairs) - n_twin))
            rng.shuffle(graphs)
            oracle = {}
            for (s, d), g in zip(pairs, graphs):
                s, d = int(s), int(d)
                if g == "twin":
                    if (s, d) not in twin_oracle:
                        twin_oracle[(s, d)] = solve_serial_csr(
                            n, *twin_csr, s, d
                        )
                    oracle[(s, d, "twin")] = twin_oracle[(s, d)]
                else:
                    oracle[(s, d, "main")] = solve_serial_csr(
                        n, *csr, s, d
                    )

            # odd epochs force a synchronous fold mid-traffic from a
            # side thread — the REPL `swap` path racing live submits
            # (even epochs rely on the threshold-triggered background
            # compaction kicked by the update above)
            forcer = None
            forced_at = max(1, (2 * len(pairs)) // 3)
            t0 = time.perf_counter()
            tickets = []
            for i, ((s, d), g) in enumerate(zip(pairs, graphs)):
                if epoch % 2 == 1 and i == forced_at:
                    forcer = threading.Thread(
                        target=lambda: store.compact("main"),
                        name="bibfs-churn-force-swap", daemon=True,
                    )
                    forcer.start()
                delay = t0 + i / rate_qps - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                tickets.append(engine.submit(int(s), int(d), g))
            ep_drained = drain_bounded()
            drained = drained and ep_drained
            if forcer is not None:
                forcer.join(timeout=60.0)

            ep_lost = ep_failed = ep_bad = 0
            wait_s = 60.0 if ep_drained else 2.0
            for (s, d), g, t in zip(pairs, graphs, tickets):
                s, d = int(s), int(d)
                try:
                    res = t.wait(timeout=wait_s)
                except TimeoutError:
                    lost.append((s, d, g))
                    ep_lost += 1
                    wait_s = 2.0
                    continue
                except Exception as e:
                    failed.append(
                        {"query": [s, d], "graph": g,
                         "kind": getattr(e, "kind", "?"),
                         "error": str(e)[:200]}
                    )
                    ep_failed += 1
                    continue
                if t.t_done is not None:
                    max_lat_s = max(max_lat_s, t.t_done - t.t_submit)
                ref = oracle[(s, d, g)]
                if res.found != ref.found or (
                    ref.found and res.hops != ref.hops
                ):
                    mismatches.append(
                        f"epoch {epoch} {g} {s}->{d}: "
                        f"{res.hops} != {ref.hops}"
                    )
                    ep_bad += 1
            versions_seen.add(store.current("main").version)
            st = store.stats()["graphs"]["main"]
            epochs_out.append({
                "epoch": epoch,
                "adds": len(adds),
                "dels": len(dels),
                "compacting_at_apply": out["compacting"],
                "forced_swap": epoch % 2 == 1,
                "version": st["version"],
                "swaps_total": st["swaps"],
                "delta_pending": st["delta_edges"],
                "lost": ep_lost,
                "failed": ep_failed,
                "mismatched": ep_bad,
                "edges": int(epoch_edges.shape[0]),
            })

        # the final claim, stated on the FINAL graph: fold anything
        # still pending, then verify a fresh batch end-to-end against
        # the post-all-updates oracle
        store.compact("main")
        final_edges = np.array(sorted(live), dtype=np.int64)
        final_csr = build_csr(n, final_edges)
        final_pairs = sample_query_pairs(n, max_batch, seed=seed + 555)
        final_results = engine.query_many(
            [(int(s), int(d)) for s, d in final_pairs], graph="main"
        )
        final_bad = []
        for (s, d), res in zip(final_pairs, final_results):
            s, d = int(s), int(d)
            ref = solve_serial_csr(n, *final_csr, s, d)
            if res.found != ref.found or (
                ref.found and res.hops != ref.hops
            ):
                final_bad.append(f"{s}->{d}: {res.hops} != {ref.hops}")
            elif ref.found and res.path is not None and not _validate(
                final_csr, res, s, d
            ):
                final_bad.append(f"{s}->{d}: path failed validation")

        stats = engine.stats()
        store_stats = store.stats()
        ex = exec_cache.stats()
        stranded = stats["pipeline"]["outstanding"]
        recompiles = ex["programs"] - baseline["programs"]
        # the gate's currency: actual trace+lower events since warmup
        recompiles_sentinel = (
            sentinel.total_compiles() - compiles_baseline
        )
        swaps_total = store_stats["graphs"]["main"]["swaps"]
        out = {
            "n": int(n),
            "epochs": epochs,
            "queries_per_epoch": queries_per_epoch,
            "updates_per_epoch": updates_per_epoch,
            "twin_fraction": twin_fraction,
            "rate_qps": rate_qps,
            "stall_bound_ms": stall_bound_ms,
            "tickets": {
                "submitted": epochs * queries_per_epoch,
                "failed": len(failed),
                "lost": len(lost),
                "stranded_outstanding": stranded,
            },
            "failed_sample": failed[:10],
            "mismatches": mismatches[:10],
            "final_graph": {
                "edges": int(final_edges.shape[0]),
                "version": store_stats["graphs"]["main"]["version"],
                "digest": store_stats["graphs"]["main"]["digest"],
                "verify_queries": int(final_pairs.shape[0]),
                "mismatches": final_bad[:10],
            },
            "store": {
                "swaps": swaps_total,
                "compactions":
                    store_stats["graphs"]["main"]["compactions"],
                "versions_seen": sorted(versions_seen),
                "delta_pending":
                    store_stats["graphs"]["main"]["delta_edges"],
            },
            "exec": {
                "programs_baseline": baseline["programs"],
                "programs_end": ex["programs"],
                "recompiles_during_churn": recompiles,
                "compile_events_during_churn": recompiles_sentinel,
                "hits": ex["hits"],
                "misses": ex["misses"],
                "cross_graph_reuse": cross_graph_reuse,
            },
            "engine": {
                "device_batches": stats["device_batches"],
                "host_queries": stats["host_queries"],
                "overlay_queries": stats["overlay_queries"],
                "cache_served": stats["cache_served"],
                "latency_ms": stats["latency_ms"],
            },
            "max_latency_ms": round(max_lat_s * 1e3, 3),
            "epochs_detail": epochs_out,
            "setup_to_drain_s": round(
                time.perf_counter() - t_setup, 3
            ),
            # the gates
            "zero_lost": not lost and stranded == 0 and drained,
            # unlike the chaos soak, this run injects NO faults: a
            # structured QueryError is a real regression, not an
            # expected casualty — failed tickets gate too (they skip
            # oracle verification, so verified_vs_oracle alone would
            # pass a run that errored a third of its traffic)
            "zero_failed": not failed,
            "verified_vs_oracle": not mismatches and not final_bad,
            "swap_stall_ok": max_lat_s * 1e3 <= stall_bound_ms,
            # gated on the SENTINEL's event count (docstring): a
            # retrace that reuses a noted key passes the counter diff
            # (recompiles) but not the trace+lower count
            "zero_recompiles": (
                recompiles == 0 and recompiles_sentinel == 0
                and cross_graph_reuse
            ),
            "routes_exercised": (
                stats["overlay_queries"] > 0
                and stats["device_batches"] > 0
            ),
            "swaps_ok": swaps_total >= max(1, epochs // 2),
        }
        out["ok"] = bool(
            out["zero_lost"] and out["zero_failed"]
            and out["verified_vs_oracle"]
            and out["swap_stall_ok"] and out["zero_recompiles"]
            and out["routes_exercised"] and out["swaps_ok"]
        )
        return out
    finally:
        if engine is not None:
            engine.close()
        store.close()
        if _owns_sentinel:
            compilegraph.uninstall()


def run_oracle(
    n,
    edges,
    *,
    queries: int = 2000,
    oracle_k: int = 16,
    skew: float = 1.3,
    repeat_fraction: float = 0.25,
    hit_rate_min: float = 0.30,
    speedup_min: float | None = 3.0,
    swap_adds: int = 24,
    swap_dels: int = 8,
    flush_threshold: int = 8,
    max_batch: int = 256,
    index_timeout_s: float = 120.0,
    seed: int = 0,
    **engine_kwargs,
) -> dict:
    """The distance-oracle skew soak (``bench.py --serve-oracle``):
    repeat-heavy Zipf traffic (:func:`sample_skewed_pairs`) served
    through two otherwise-identical store-backed engines — one with the
    landmark oracle tier, one without — then a mid-traffic live update
    + forced hot-swap against the oracle engine. The A/B runs drive the
    synchronous engine closed-loop (submit stream + self-flushing
    batches: each side's best throughput configuration, so the ratio
    measures the tier, not producer-thread scheduling; the pipelined
    engine's oracle route is covered by the serving tests). The four
    claims the tier makes, all gated:

    1. **exactness** — every answer of the oracle run (oracle-served or
       fallen-through) equals a fresh from-scratch serial BFS on the
       graph state it was submitted against; the tier never guesses;
    2. **hit rate** — ``route="oracle"`` serves at least
       ``hit_rate_min`` of the skewed stream (the landmark set is
       degree-seeded, the hot endpoints are degree-ranked: the design
       point, measured);
    3. **throughput** — the oracle engine's full-stream qps is at least
       ``speedup_min`` x the no-oracle engine's on the SAME traffic
       (``None`` skips the gate and just reports the ratio — the
       ``--quick`` CI shape, where solve cost on a tiny graph is
       comparable to per-query overhead and the ratio is noise);
    4. **zero stale answers across a hot-swap** — an update batch
       (hub-shortcut adds + hub-edge deletes, chosen so ground-truth
       answers actually change: ``changed_answers`` must be > 0 or the
       gate would be vacuous) lands mid-run, a forced compaction
       hot-swaps the snapshot from a side thread UNDER the traffic, and
       every post-update answer must match ground truth on the
       POST-update graph — deletes invalidate the index immediately
       (gen bump), the rebuilt index must answer for the new snapshot
       only, and a final phase confirms the rebuilt index actually
       serves (``route="oracle"`` hits > 0 on post-swap traffic).

    Returns the machine-readable ``bench_oracle.json`` payload (``ok``
    aggregates the gates; zero lost/stranded tickets throughout is an
    implicit fifth gate)."""
    from bibfs_tpu.graph.csr import build_csr, canonical_pairs
    from bibfs_tpu.serve.engine import QueryEngine
    from bibfs_tpu.solvers.serial import solve_serial_csr
    from bibfs_tpu.store import GraphStore

    t_setup = time.perf_counter()
    cpairs = canonical_pairs(n, edges)
    csr = build_csr(n, pairs=cpairs)
    deg = (csr[0][1:] - csr[0][:-1]).astype(np.int64)
    traffic = sample_skewed_pairs(
        n, queries, seed=seed, skew=skew,
        repeat_fraction=repeat_fraction, degrees=deg,
    )

    def truth_solver(c):
        """A fresh per-pair ground-truth BFS outside the engines under
        test — no cache, no oracle, no batching. The native C runtime
        when it loads (the soak graph is sized so a BFS costs real
        time; a full NumPy-serial truth pass would dwarf the
        measurement), else the NumPy serial solver; either way a
        seeded subsample is cross-checked against ``solve_serial_csr``
        below, so the truth source itself is audited per run."""
        try:
            from bibfs_tpu.solvers.native import (
                NativeGraph, solve_native_graph,
            )

            # the ctypes ABI is exact about dtypes (int64 row_ptr,
            # int32 col_ind); the python-side CSR carries int64 columns
            ng = NativeGraph(
                n,
                np.ascontiguousarray(c[0], dtype=np.int64),
                np.ascontiguousarray(c[1], dtype=np.int32),
            )
            return lambda s, d: solve_native_graph(ng, s, d)
        except (ImportError, OSError):
            return lambda s, d: solve_serial_csr(n, *c, s, d)

    def truth_for(pairs, c, solver=None):
        solver = truth_solver(c) if solver is None else solver
        out = {}
        for s, d in pairs:
            key = (int(s), int(d))
            if key not in out:
                out[key] = solver(*key)
        return out

    def crosscheck(truth, c, rng, sample=32):
        """Audit the truth table: ``sample`` random entries recomputed
        with the NumPy serial solver must agree exactly."""
        keys = list(truth)
        pick = rng.choice(len(keys), size=min(sample, len(keys)),
                          replace=False)
        bad = []
        for i in pick:
            s, d = keys[int(i)]
            ref = solve_serial_csr(n, *c, s, d)
            got = truth[(s, d)]
            if got.found != ref.found or (
                ref.found and got.hops != ref.hops
            ):
                bad.append(
                    f"truth {s}->{d}: {got.found}/{got.hops} != "
                    f"serial {ref.found}/{ref.hops}"
                )
        return bad

    def verify_against(pairs, results, truth, tag):
        bad = []
        for (s, d), res in zip(pairs, results):
            s, d = int(s), int(d)
            ref = truth[(s, d)]
            if res is None:
                bad.append(f"{tag} {s}->{d}: unresolved")
            elif res.found != ref.found or (
                ref.found and res.hops != ref.hops
            ):
                bad.append(
                    f"{tag} {s}->{d}: {res.found}/{res.hops} != "
                    f"{ref.found}/{ref.hops}"
                )
        return bad

    def drive_max(engine, pairs, force_at=None, force_fn=None):
        """Closed-loop full-speed submit stream (oracle/cache hits
        resolve inline, everything else batches and self-flushes at
        ``max_batch``), optional side-thread store mutation fired at
        index ``force_at`` — the mid-traffic hot-swap. Returns
        (results, elapsed_s, lost)."""
        forcer = None
        t0 = time.perf_counter()
        tickets = []
        for i, (s, d) in enumerate(pairs):
            if force_at is not None and i == force_at:
                forcer = threading.Thread(
                    target=force_fn, name="bibfs-oracle-force-swap",
                    daemon=True,
                )
                forcer.start()
            tickets.append(engine.submit(int(s), int(d)))
        engine.flush()
        elapsed = time.perf_counter() - t0
        if forcer is not None:
            forcer.join(timeout=60.0)
        results, lost = [], 0
        for t in tickets:
            if t.error is not None or t.result is None:
                results.append(None)
                lost += 1
            else:
                results.append(t.result)
        return results, elapsed, lost

    truth1 = truth_for(traffic, csr)
    mm_truth = crosscheck(truth1, csr, np.random.default_rng(seed + 7))
    warm = sample_query_pairs(n, 4 * flush_threshold, seed=seed + 99)
    warm = [(int(s), int(d)) for s, d in warm]
    engine_conf = dict(
        flush_threshold=flush_threshold, max_batch=max_batch,
        **engine_kwargs,
    )

    # ---- baseline: the same store/engine stack, oracle tier OFF ------
    store_b = GraphStore()
    store_b.add("g", n, pairs=cpairs)
    eng_b = QueryEngine(store=store_b, graph="g", **engine_conf)
    try:
        eng_b.query_many(warm)
        res_b, el_b, lost_b = drive_max(eng_b, traffic)
        stats_b = eng_b.stats()
    finally:
        eng_b.close()
        store_b.close()
    mm_base = verify_against(traffic, res_b, truth1, "base")
    qps_base = len(traffic) / el_b if el_b > 0 else None

    # ---- oracle run: same stack + the landmark tier ------------------
    store_o = GraphStore(oracle_k=oracle_k, oracle_seed=seed)
    store_o.add("g", n, pairs=cpairs)
    index_ready = store_o.wait_for_index("g", timeout=index_timeout_s)
    eng_o = QueryEngine(store=store_o, graph="g", **engine_conf)
    try:
        eng_o.query_many(warm)
        served_0 = eng_o.stats()["oracle_served"]
        res_o, el_o, lost_o = drive_max(eng_o, traffic)
        served_a = eng_o.stats()["oracle_served"] - served_0
        mm_oracle = verify_against(traffic, res_o, truth1, "oracle")
        qps_oracle = len(traffic) / el_o if el_o > 0 else None
        hit_rate = served_a / len(traffic) if traffic.size else 0.0
        speedup = (
            round(qps_oracle / qps_base, 3)
            if qps_base and qps_oracle else None
        )

        # ---- mid-traffic update + forced hot-swap --------------------
        und = cpairs[cpairs[:, 0] < cpairs[:, 1]]
        live = set(map(tuple, und.tolist()))
        rng = np.random.default_rng(seed + 1)
        order = np.lexsort((np.arange(n), -deg))
        hubs = [int(v) for v in order[: max(4, oracle_k // 2)]]
        hub_edges = [
            e for e in map(tuple, und.tolist())
            if e[0] in hubs or e[1] in hubs
        ]
        rng.shuffle(hub_edges)
        dels = [tuple(int(x) for x in e)
                for e in hub_edges[: max(0, int(swap_dels))]]
        adds, tries = [], 0
        pend = set(dels)
        while len(adds) < int(swap_adds) and tries < 20000:
            tries += 1
            h = hubs[int(rng.integers(0, len(hubs)))]
            v = int(rng.integers(0, n))
            if v == h:
                continue
            e = (h, v) if h < v else (v, h)
            if e in live and e not in pend:
                continue
            if e in adds or e in pend:
                continue
            adds.append(e)
        live2 = (live - set(dels)) | set(adds)
        csr2 = build_csr(n, np.array(sorted(live2), dtype=np.int64))

        traffic_b = sample_skewed_pairs(
            n, max(queries // 2, 50), seed=seed + 2, skew=skew,
            repeat_fraction=repeat_fraction, degrees=deg,
        )
        truth2 = truth_for(traffic_b, csr2)
        mm_truth.extend(
            crosscheck(truth2, csr2, np.random.default_rng(seed + 8))
        )
        truth_b1 = truth_for(traffic_b, csr)
        changed = sum(
            1 for key, ref in truth2.items()
            if (ref.found, ref.hops)
            != (truth_b1[key].found, truth_b1[key].hops)
        )

        # the deletes invalidate the index HERE (gen bump under the
        # apply lock): pre-swap phase-B queries must fall through to
        # the exact overlay/solver routes, never a stale index
        store_o.update("g", adds=adds, dels=dels)
        served_b0 = eng_o.stats()["oracle_served"]
        res_sw, el_sw, lost_sw = drive_max(
            eng_o, traffic_b,
            force_at=max(1, len(traffic_b) // 3),
            force_fn=lambda: store_o.compact("g"),
        )
        mm_swap = verify_against(traffic_b, res_sw, truth2, "swap")
        served_swap = eng_o.stats()["oracle_served"] - served_b0

        # ---- post-swap: the REBUILT index must serve v2 exactly ------
        index2_ready = store_o.wait_for_index(
            "g", timeout=index_timeout_s
        )
        traffic_c = sample_skewed_pairs(
            n, max(queries // 4, 50), seed=seed + 3, skew=skew,
            repeat_fraction=repeat_fraction, degrees=deg,
        )
        truth_c = truth_for(traffic_c, csr2)
        served_c0 = eng_o.stats()["oracle_served"]
        res_c, el_c, lost_c = drive_max(eng_o, traffic_c)
        served_c = eng_o.stats()["oracle_served"] - served_c0
        mm_post = verify_against(traffic_c, res_c, truth_c, "post")

        stats_o = eng_o.stats()
        store_stats = store_o.stats()
        orc_stats = store_stats["graphs"]["g"]["oracle"]
        stranded = eng_o.pending  # post-flush: anything left is a bug
        lost = lost_o + lost_sw + lost_c
        out = {
            "n": int(n),
            "queries": int(len(traffic)),
            "oracle_k": int(oracle_k),
            "skew": float(skew),
            "repeat_fraction": float(repeat_fraction),
            "traffic": {
                "unique_pairs": len(truth1),
                "swap_queries": int(len(traffic_b)),
                "post_swap_queries": int(len(traffic_c)),
            },
            "baseline": {
                "qps": None if qps_base is None else round(qps_base, 1),
                "elapsed_s": round(el_b, 4),
                "host_queries": stats_b["host_queries"],
                "cache_served": stats_b["cache_served"],
                "mismatches": mm_base[:10],
            },
            "truth_crosscheck_mismatches": mm_truth[:10],
            "oracle": {
                "qps": None if qps_oracle is None
                else round(qps_oracle, 1),
                "elapsed_s": round(el_o, 4),
                "served": int(served_a),
                "hit_rate": round(hit_rate, 4),
                "hits_by_kind": orc_stats.get("hits"),
                "host_queries": stats_o["host_queries"],
                "cache_served": stats_o["cache_served"],
                "index": orc_stats,
                "mismatches": mm_oracle[:10],
            },
            "speedup": speedup,
            "swap": {
                "adds": len(adds),
                "dels": len(dels),
                "changed_answers": int(changed),
                "oracle_served_during": int(served_swap),
                "oracle_served_post": int(served_c),
                "index2_ready": bool(index2_ready),
                "version": store_stats["graphs"]["g"]["version"],
                "swaps": store_stats["graphs"]["g"]["swaps"],
                "mismatches": (mm_swap + mm_post)[:10],
            },
            "tickets": {
                "submitted": int(
                    len(traffic) * 2 + len(traffic_b) + len(traffic_c)
                ),
                "lost": int(lost + lost_b),
                "stranded_outstanding": int(stranded),
            },
            "setup_to_drain_s": round(
                time.perf_counter() - t_setup, 3
            ),
            # the gates
            "index_ready": bool(index_ready),
            "exact": not mm_oracle and not mm_base and not mm_truth,
            "hit_rate_ok": hit_rate >= float(hit_rate_min),
            "speedup_ok": (
                True if speedup_min is None
                else bool(speedup is not None
                          and speedup >= float(speedup_min))
            ),
            "zero_stale": (
                not mm_swap and not mm_post and changed > 0
                and index2_ready and served_c > 0
            ),
            "zero_lost": lost + lost_b == 0 and stranded == 0,
        }
        out["ok"] = bool(
            out["index_ready"] and out["exact"] and out["hit_rate_ok"]
            and out["speedup_ok"] and out["zero_stale"]
            and out["zero_lost"]
        )
        return out
    finally:
        eng_o.close()
        store_o.close()


def run_fleet(
    *,
    replicas: int = 3,
    graphs: int = 30,
    grid: tuple = (150, 150),
    perforation: float = 0.02,
    queries: int = 6000,
    qps_repeats: int = 2,
    chaos_queries: int = 3000,
    chaos_span_s: float = 24.0,
    hot_pool: int = 48,
    repeat_fraction: float = 0.85,
    cache_entries: int = 128,
    max_batch: int = 64,
    qps_factor: float | None = 2.0,
    recovery_bound_s: float = 10.0,
    roll_adds: int = 24,
    roll_dels: int = 8,
    burst_queries: int = 240,
    seed: int = 0,
) -> dict:
    """The fleet serving soak (``bench.py --serve-fleet``): a
    health-aware :class:`~bibfs_tpu.fleet.Router` over N in-process
    engine replicas — each with its OWN versioned graph store — driven
    through the workload the fleet exists for, with kill/restart chaos
    and a rolling swap landing mid-traffic. The claims, all gated:

    1. **horizontal throughput** — repeat-heavy traffic over many
       graphs (per-graph hot pools accessed cyclically, a cold fresh
       tail) is served by a single replica and then by the fleet, same
       replica config and driver protocol (hot pools warmed first, one
       driver thread per hash shard). Consistent-hash affinity means
       each fleet replica's bounded distance cache holds only ITS
       shard's hot set while the single replica thrashes the combined
       set — aggregate cache capacity (and solver parallelism) scales
       with the replica count, so fleet qps must reach ``qps_factor`` x
       single-replica qps (``None`` reports without gating — the
       ``--quick`` CI shape);
    2. **kill/restart chaos, zero lost** — mid-traffic the replica
       owning the hottest graph is killed (queued tickets fail with
       structured internal errors; the router reroutes them and every
       later submission), then restarted; the health poller must
       re-admit it within ``recovery_bound_s`` of the restart, and
       every ticket of the run must resolve (reroutes, never losses);
    3. **rolling swap under load** — an edge-update batch that provably
       changes answers rolls across the fleet replica-at-a-time
       (drain -> roll -> ready-probe -> re-admit) while traffic flows:
       the fleet serves MIXED versions mid-roll and every answer must
       match ground truth for the version its serving replica declared
       (:class:`FleetTicket.declared_version`);
    4. **hot-graph spill** — a closed-loop burst on one graph with the
       spill threshold lowered must spill to less-loaded replicas
       (``bibfs_fleet_spills_total`` > 0) with answers still exact;
    5. **observability** — the fleet metric families render on a LIVE
       ``/metrics`` endpoint scraped over HTTP during the run.

    Ground truth is a fresh per-pair native BFS outside the fleet
    (audited against the NumPy serial solver on seeded subsamples),
    per graph version. Returns the ``bench_fleet.json`` payload.
    """
    import urllib.request

    from bibfs_tpu.fleet import Router, engine_replica
    from bibfs_tpu.graph.csr import build_csr
    from bibfs_tpu.graph.generate import grid_graph
    from bibfs_tpu.obs.http import start_metrics_server
    from bibfs_tpu.obs.metrics import REGISTRY
    from bibfs_tpu.serve.resilience import QueryError
    from bibfs_tpu.solvers.serial import solve_serial_csr
    from bibfs_tpu.store import GraphStore

    class _Refused:
        """A submit the router refused outright (no healthy replica):
        rides the rows like a ticket so the verify pass classifies it."""

        def __init__(self, err):
            self.error = err
            self.result = None
            self.declared_version = None

        def wait(self, timeout=None):
            raise self.error

    t_setup = time.perf_counter()
    w, h = int(grid[0]), int(grid[1])
    n = w * h
    rng = np.random.default_rng(seed)
    names = [f"g{i}" for i in range(int(graphs))]
    edge_sets = {
        g: grid_graph(w, h, perforation=perforation, seed=seed + i)
        for i, g in enumerate(names)
    }
    # canonical undirected edge sets (u < v) — the update sampler's and
    # the truth rebuilds' common currency
    und = {
        g: np.unique(np.sort(e[e[:, 0] != e[:, 1]], axis=1), axis=0)
        for g, e in edge_sets.items()
    }
    csrs = {g: build_csr(n, e) for g, e in edge_sets.items()}

    def truth_solver(c):
        """Fresh per-pair ground truth outside the fleet (native when
        it loads, serial otherwise; audited below either way)."""
        try:
            from bibfs_tpu.solvers.native import (
                NativeGraph,
                solve_native_graph,
            )

            ng = NativeGraph(
                n,
                np.ascontiguousarray(c[0], dtype=np.int64),
                np.ascontiguousarray(c[1], dtype=np.int32),
            )
            return lambda s, d: solve_native_graph(ng, s, d)
        except (ImportError, OSError):
            return lambda s, d: solve_serial_csr(n, *c, s, d)

    solvers = {g: truth_solver(csrs[g]) for g in names}
    truth1: dict = {g: {} for g in names}

    def truth_for(g, s, d, table=None):
        table = truth1[g] if table is None else table
        key = (int(s), int(d))
        if key not in table:
            solver = solvers[g] if table is truth1[g] else table["__solver__"]
            table[key] = solver(*key)
        return table[key]

    # per-graph hot pools, accessed CYCLICALLY by the stream builder:
    # the scanning access pattern under which an LRU bounded below the
    # working set keeps ~nothing (the single replica's regime) and one
    # bounded above its shard keeps ~everything (each fleet replica's)
    pools = {}
    for g in names:
        p = np.unique(
            rng.integers(0, n, size=(3 * int(hot_pool), 2)), axis=0
        )
        p = p[p[:, 0] != p[:, 1]][: int(hot_pool)]
        pools[g] = [(int(s), int(d)) for s, d in p]

    def make_stream(q, fresh_seed):
        r2 = np.random.default_rng(fresh_seed)
        pos = {g: 0 for g in names}
        out = []
        for i in range(q):
            g = names[i % len(names)]
            if r2.random() < repeat_fraction:
                s, d = pools[g][pos[g] % len(pools[g])]
                pos[g] += 1
            else:
                s, d = int(r2.integers(0, n)), int(r2.integers(0, n))
                if s == d:
                    d = (d + 1) % n
            out.append((g, s, d))
        return out

    def make_replica(idx):
        store = GraphStore(compact_threshold=None)
        for g in names:
            store.add(g, n, edge_sets[g])
        return engine_replica(
            f"r{idx}", store, cache_entries=cache_entries,
            max_batch=max_batch,
        )

    def drive_sharded(router, stream):
        """One driver thread per hash shard (a front-end's sticky
        connections), closed-loop; returns ((g, s, d, ticket) rows,
        elapsed submit-start -> all-resolved)."""
        shards: dict = {}
        for item in stream:
            shards.setdefault(router.owner(item[0]), []).append(item)
        rows_per = [[] for _ in shards]

        def work(part, out):
            for g, s, d in part:
                try:
                    out.append((g, s, d, router.submit(s, d, g)))
                except Exception as e:
                    out.append((g, s, d, _Refused(e)))

        threads = [
            threading.Thread(target=work, args=(p, o))
            for p, o in zip(shards.values(), rows_per)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        router.flush(timeout=120.0)
        rows = [r for out in rows_per for r in out]
        for _g, _s, _d, ticket in rows:
            try:
                ticket.wait(timeout=120.0)
            except Exception:
                pass  # classified by the verify pass
        return rows, time.perf_counter() - t0

    lost, failed, mismatches = [], [], []
    truth2: dict = {}

    def verify(rows, tag, rolled_graph=None):
        for g, s, d, ticket in rows:
            if ticket.error is not None:
                failed.append({
                    "phase": tag, "graph": g, "query": [s, d],
                    "kind": getattr(ticket.error, "kind", "?"),
                    "error": str(ticket.error)[:200],
                })
                continue
            if ticket.result is None:
                lost.append((tag, g, s, d))
                continue
            if (g == rolled_graph
                    and (ticket.declared_version or 1) >= 2):
                ref = truth_for(g, s, d, table=truth2)
            else:
                ref = truth_for(g, s, d)
            res = ticket.result
            if res.found != ref.found or (
                ref.found and res.hops != ref.hops
            ):
                mismatches.append(
                    f"{tag} {g} v{ticket.declared_version} "
                    f"{s}->{d}: {res.found}/{res.hops} != "
                    f"{ref.found}/{ref.hops}"
                )

    metrics_server = start_metrics_server(0)
    old_si = sys.getswitchinterval()
    sys.setswitchinterval(5e-4)
    single_router = fleet = None
    try:
        warm_stream = [
            (g, s, d) for g in names for (s, d) in pools[g]
        ]

        def timed_phase(router, tag, seed0):
            """Warm the hot pools, then ``qps_repeats`` timed passes
            with FRESH cold tails each (best-of, the compare_engines
            top-repeats convention: the A/B judges each configuration's
            ceiling, not one noisy scheduler window on a shared box).
            Returns (best qps, best pass's cache hits)."""
            warm_rows, _ = drive_sharded(router, warm_stream)
            verify(warm_rows, f"{tag}-warm")

            def hits():
                return sum(
                    router.replica(r).engine.stats()["cache_served"]
                    for r in router.replica_names
                )

            best_qps = best_hits = None
            for rep in range(max(int(qps_repeats), 1)):
                stream = make_stream(int(queries), seed0 + rep)
                h0 = hits()
                rows, el = drive_sharded(router, stream)
                verify(rows, tag)
                q = len(stream) / el if el > 0 else None
                if q is not None and (best_qps is None or q > best_qps):
                    best_qps, best_hits = q, hits() - h0
            return best_qps, best_hits

        # ---- phase 1: single replica vs fleet, same config/protocol --
        # spill only on real backlog (4x the flush depth): spilling on
        # a queue that merely filled its next micro-batch scatters hot
        # traffic and destroys the affinity under measurement (the
        # Router docstring's measured warning)
        spill_at = 4 * int(max_batch)
        single_router = Router(
            [make_replica(0)], poll_interval_s=0.25,
            spill_after=spill_at,
        )
        qps_single, single_hits = timed_phase(
            single_router, "single", seed + 101
        )
        single_router.close()
        single_router = None

        fleet = Router(
            [make_replica(i) for i in range(int(replicas))],
            poll_interval_s=0.2, spill_after=spill_at,
        )
        qps_fleet, fleet_hits = timed_phase(fleet, "fleet", seed + 201)
        ratio = (
            round(qps_fleet / qps_single, 3)
            if qps_single and qps_fleet else None
        )

        # ---- phase 2: kill/restart + rolling swap under load ---------
        hot_graph = "g0"
        victim = fleet.owner(hot_graph)
        # the update batch, chosen so ground truth provably changes:
        # long-range shortcuts into a large-diameter grid, plus edge
        # deletes (disjoint from the adds)
        live = set(map(tuple, und[hot_graph].tolist()))
        adds = []
        for i in range(n):
            if len(adds) >= int(roll_adds):
                break
            u, v = i, n - 1 - i
            e = (u, v) if u < v else (v, u)
            if u != v and e not in live and e not in adds:
                adds.append(e)
        del_pool = [e for e in sorted(live)][:: max(len(live) // 64, 1)]
        dels = [e for e in del_pool if e not in adds][: int(roll_dels)]
        live2 = (live - set(dels)) | set(adds)
        csr2 = build_csr(
            n, np.array(sorted(live2), dtype=np.int64)
        )
        truth2 = {"__solver__": truth_solver(csr2)}
        changed = sum(
            1 for (s, d) in pools[hot_graph]
            if (lambda a, b: (a.found, a.hops) != (b.found, b.hops))(
                truth_for(hot_graph, s, d),
                truth_for(hot_graph, s, d, table=truth2),
            )
        )

        stream_c = make_stream(int(chaos_queries), seed + 303)
        rate = len(stream_c) / float(chaos_span_s)
        if qps_fleet:
            rate = min(rate, 0.5 * qps_fleet)
        k_kill = max(1, int(0.15 * len(stream_c)))
        k_restart = max(k_kill + 1, int(0.40 * len(stream_c)))
        k_roll = max(k_restart + 1, int(0.60 * len(stream_c)))
        recovery_s = None
        t_restart = None
        roll_out = {}
        roll_thread = recovery_thread = None

        def watch_recovery():
            nonlocal recovery_s
            deadline = time.monotonic() + recovery_bound_s + 5.0
            while time.monotonic() < deadline:
                if fleet.table().get(victim) == "ready":
                    recovery_s = time.monotonic() - t_restart
                    return
                time.sleep(0.02)

        def do_roll():
            roll_out.update(fleet.rolling_swap(
                hot_graph, adds=adds, dels=dels,
                drain_timeout_s=60.0, ready_timeout_s=30.0,
            ))

        rows_c = []
        t0 = time.perf_counter()
        for i, (g, s, d) in enumerate(stream_c):
            if i == k_kill:
                fleet.replica(victim).kill()
            elif i == k_restart:
                fleet.replica(victim).restart()
                t_restart = time.monotonic()
                recovery_thread = threading.Thread(
                    target=watch_recovery, daemon=True
                )
                recovery_thread.start()
            elif i == k_roll:
                roll_thread = threading.Thread(
                    target=do_roll, name="bibfs-fleet-roll",
                    daemon=True,
                )
                roll_thread.start()
            delay = t0 + i / rate - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                rows_c.append((g, s, d, fleet.submit(s, d, g)))
            except QueryError as e:
                failed.append({
                    "phase": "chaos-submit", "graph": g,
                    "query": [s, d],
                    "kind": getattr(e, "kind", "?"),
                    "error": str(e)[:200],
                })
        if roll_thread is not None:
            roll_thread.join(timeout=180.0)
        if recovery_thread is not None:
            recovery_thread.join(timeout=recovery_bound_s + 6.0)
        fleet.flush(timeout=120.0)
        for _g, _s, _d, ticket in rows_c:
            try:
                ticket.wait(timeout=120.0)
            except Exception:
                pass
        verify(rows_c, "chaos", rolled_graph=hot_graph)
        versions_mid = {
            "v1": sum(
                1 for g, _s, _d, t in rows_c
                if g == hot_graph and (t.declared_version or 1) < 2
            ),
            "v2": sum(
                1 for g, _s, _d, t in rows_c
                if g == hot_graph and (t.declared_version or 1) >= 2
            ),
        }
        post_versions = {
            r: fleet.replica(r).version(hot_graph)
            for r in fleet.replica_names
        }

        # ---- phase 3: hot-graph burst exercises the spill path -------
        spill_before = fleet.stats()["spills"]
        old_spill = fleet.spill_after
        fleet.spill_after = 4
        try:
            # FRESH pairs: a cache-served burst resolves inline and the
            # owner's queue never builds — the spill path needs queued
            # work on the hash owner, i.e. misses
            brng = np.random.default_rng(seed + 404)
            burst = []
            while len(burst) < int(burst_queries):
                s = int(brng.integers(0, n))
                d = int(brng.integers(0, n))
                if s != d:
                    burst.append((hot_graph, s, d))
            parts = [burst[i::3] for i in range(3)]
            burst_rows = [[] for _ in parts]

            def bwork(part, out):
                for g, s, d in part:
                    try:
                        out.append((g, s, d, fleet.submit(s, d, g)))
                    except Exception as e:
                        out.append((g, s, d, _Refused(e)))

            bthreads = [
                threading.Thread(target=bwork, args=(p, o))
                for p, o in zip(parts, burst_rows)
            ]
            for t in bthreads:
                t.start()
            for t in bthreads:
                t.join()
            fleet.flush(timeout=120.0)
            flat_burst = [r for out in burst_rows for r in out]
            for _g, _s, _d, ticket in flat_burst:
                try:
                    ticket.wait(timeout=120.0)
                except Exception:
                    pass
            verify(flat_burst, "burst", rolled_graph=hot_graph)
        finally:
            fleet.spill_after = old_spill
        spills = fleet.stats()["spills"] - spill_before

        # ---- truth audit: seeded subsample vs the serial solver ------
        audit_bad = []
        audit_rng = np.random.default_rng(seed + 7)
        for g in [names[int(i)] for i in
                  audit_rng.choice(len(names), size=2, replace=False)]:
            keys = list(truth1[g]) or [(0, n - 1)]
            pick = audit_rng.choice(
                len(keys), size=min(16, len(keys)), replace=False
            )
            for i in pick:
                s, d = keys[int(i)]
                ref = solve_serial_csr(n, *csrs[g], s, d)
                got = truth_for(g, s, d)
                if got.found != ref.found or (
                    ref.found and got.hops != ref.hops
                ):
                    audit_bad.append(
                        f"truth {g} {s}->{d}: {got.found}/{got.hops} "
                        f"!= serial {ref.found}/{ref.hops}"
                    )

        # ---- live /metrics render ------------------------------------
        from bibfs_tpu.fleet import FLEET_METRIC_FAMILIES as families
        try:
            with urllib.request.urlopen(
                metrics_server.url, timeout=10
            ) as resp:
                render = resp.read().decode()
        except Exception:
            render = REGISTRY.render()  # still check; live_ok records
            live_scrape = False
        else:
            live_scrape = True
        metrics_missing = [m for m in families if m not in render]

        fstats = fleet.stats()
        stranded = sum(
            fleet.replica(r).load() for r in fleet.replica_names
            if fleet.replica(r).alive
        )
        submitted = (
            2 * len(warm_stream)
            + 2 * max(int(qps_repeats), 1) * int(queries)
            + len(rows_c) + int(burst_queries)
        )
        out = {
            "n_per_graph": n,
            "graphs": len(names),
            "replicas": int(replicas),
            "grid": f"{w}x{h}",
            "queries_per_phase": int(queries),
            "hot_pool": int(hot_pool),
            "repeat_fraction": float(repeat_fraction),
            "cache_entries": int(cache_entries),
            "qps": {
                "single": None if qps_single is None
                else round(qps_single, 1),
                "fleet": None if qps_fleet is None
                else round(qps_fleet, 1),
                "ratio": ratio,
                "factor_gate": qps_factor,
                "single_timed_cache_served": int(single_hits),
                "fleet_timed_cache_served": int(fleet_hits),
            },
            "chaos": {
                "queries": len(stream_c),
                "rate_qps": round(rate, 1),
                "span_s": float(chaos_span_s),
                "victim": victim,
                "recovery_bound_s": float(recovery_bound_s),
                "recovery_s": (
                    None if recovery_s is None else round(recovery_s, 3)
                ),
            },
            "roll": {
                **roll_out,
                "changed_answers": int(changed),
                "mixed_versions_served": versions_mid,
                "post_versions": post_versions,
            },
            "spill": {
                "burst_queries": int(burst_queries),
                "spills": int(spills),
            },
            "router": {
                "routed": {
                    r: fstats["replicas"][r]["routed"]
                    for r in fstats["replicas"]
                },
                "reroutes": fstats["reroutes"],
                "spills_total": fstats["spills"],
                "rolls": fstats["rolls"],
            },
            "tickets": {
                "submitted": submitted,
                "failed": len(failed),
                "lost": len(lost),
                "stranded_outstanding": int(stranded),
            },
            "failed_sample": failed[:10],
            "mismatches": mismatches[:10],
            "truth_audit_mismatches": audit_bad[:10],
            "metrics": {
                "url": metrics_server.url,
                "live_scrape": live_scrape,
                "missing": metrics_missing,
            },
            "setup_to_drain_s": round(
                time.perf_counter() - t_setup, 3
            ),
            # the gates
            "zero_lost": not lost and stranded == 0,
            "zero_failed": not failed,
            "verified_vs_truth": not mismatches and not audit_bad,
            "qps_ok": (
                True if qps_factor is None
                else bool(ratio is not None
                          and ratio >= float(qps_factor))
            ),
            "recovery_ok": bool(
                recovery_s is not None
                and recovery_s <= recovery_bound_s
            ),
            "roll_ok": bool(
                roll_out.get("ok")
                and changed > 0
                and all(v == 2 for v in post_versions.values())
            ),
            "reroutes_ok": fstats["reroutes"] > 0,
            "spill_ok": spills > 0,
            "metrics_ok": bool(live_scrape and not metrics_missing),
        }
        out["ok"] = bool(
            out["zero_lost"] and out["zero_failed"]
            and out["verified_vs_truth"] and out["qps_ok"]
            and out["recovery_ok"] and out["roll_ok"]
            and out["reroutes_ok"] and out["spill_ok"]
            and out["metrics_ok"]
        )
        return out
    finally:
        sys.setswitchinterval(old_si)
        if single_router is not None:
            single_router.close()
        if fleet is not None:
            fleet.close()
        metrics_server.close()


def run_crash(
    *,
    replicas: int = 3,
    grid: tuple = (40, 40),
    perforation: float = 0.02,
    traffic_graphs: int = 3,
    kill_cycles: int = 3,
    updates_per_cycle: int = 6,
    rate_qps: float = 150.0,
    hot_pool: int = 32,
    repeat_fraction: float = 0.6,
    recovery_bound_s: float = 30.0,
    fsync: str = "always",
    roll_adds: int = 8,
    max_batch: int = 64,
    cache_entries: int = 64,
    seed: int = 0,
    workdir: str | None = None,
) -> dict:
    """The crash-durability soak (``bench.py --serve-crash``): a fleet
    of one DURABLE ``bibfs-serve`` subprocess victim (``--durable
    --fsync always``: every acked update is WAL-fsync'd before the ack
    reply) plus in-process engine replicas over their own durable
    stores, under open-loop routed traffic, while the victim is
    SIGKILL'd and respawned ``kill_cycles`` times mid-update-stream.
    The claims, all gated:

    1. **zero acknowledged-update loss** — each cycle applies an
       acked edge-update stream to the victim's ``gu`` graph and
       SIGKILLs the child IMMEDIATELY after the last ack; after
       respawn (manifest + WAL replay recovery) every acked update
       must be visible: sampled pairs are re-queried through the
       respawned child and checked against fresh native BFS on the
       seed+acked edge set, and the cycle ends with a forced fold
       whose snapshot digest must equal the content digest of exactly
       that edge set — a total-state equality, not a sample;
    2. **bounded recovery-to-ready** — every respawn must be back in
       the router's ``ready`` state within ``recovery_bound_s``
       (subprocess spawn + recovery + health re-admission, catch-up
       check included);
    3. **catch-up re-admission** — a rolling swap commits a fleet-wide
       version mid-soak; the victim killed and respawned after it must
       re-enter ``ready`` only with its declared version at the
       committed one (its own WAL provides it — the stale-v1 respawn
       this layer exists to kill);
    4. **torn-tail replay** — garbage appended to the victim's live
       WAL segment (the torn write a crash mid-append leaves) must be
       truncated by recovery, in-process (a parent-side recovery of a
       copy of the victim's dir, digest-verified) AND by the respawned
       child, which still serves every acked update;
    5. **0 lost / stranded tickets on non-killed replicas** — the
       routed open-loop traffic flowing through the whole soak loses
       nothing: victim kills cost reroutes, never tickets, and every
       survivor answer is verified against fresh native BFS (audited
       vs the serial solver on a seeded subsample);
    6. **observability** — the durability metric families
       (``store/wal.DURABLE_METRIC_FAMILIES``) render on the registry.

    Returns the ``bench_crash.json`` payload."""
    import os
    import shutil
    import tempfile

    from bibfs_tpu.fleet import ProcessReplica, Router, engine_replica
    from bibfs_tpu.graph.csr import build_csr, canonical_pairs
    from bibfs_tpu.graph.generate import grid_graph
    from bibfs_tpu.graph.io import write_graph_bin
    from bibfs_tpu.obs.metrics import REGISTRY
    from bibfs_tpu.solvers.serial import solve_serial_csr
    from bibfs_tpu.store import GraphStore, content_digest
    from bibfs_tpu.store.wal import DURABLE_METRIC_FAMILIES

    t_setup = time.perf_counter()
    w, h = int(grid[0]), int(grid[1])
    n = w * h
    rng = np.random.default_rng(seed)
    routed = [f"g{i}" for i in range(int(traffic_graphs))]
    names = routed + ["gu", "gr"]  # gu: victim update stream; gr: roll
    edge_sets = {
        g: grid_graph(w, h, perforation=perforation, seed=seed + i)
        for i, g in enumerate(names)
    }
    csrs = {g: build_csr(n, e) for g, e in edge_sets.items()}
    und = {
        g: sorted(map(tuple, np.unique(
            np.sort(e[e[:, 0] != e[:, 1]], axis=1), axis=0
        ).tolist()))
        for g, e in edge_sets.items()
    }

    def truth_solver(c):
        try:
            from bibfs_tpu.solvers.native import (
                NativeGraph,
                solve_native_graph,
            )

            ng = NativeGraph(
                n,
                np.ascontiguousarray(c[0], dtype=np.int64),
                np.ascontiguousarray(c[1], dtype=np.int32),
            )
            return lambda s, d: solve_native_graph(ng, s, d)
        except (ImportError, OSError):
            return lambda s, d: solve_serial_csr(n, *c, s, d)

    solvers = {g: truth_solver(csrs[g]) for g in routed}
    truth: dict = {g: {} for g in routed}

    def truth_for(g, s, d):
        key = (int(s), int(d))
        if key not in truth[g]:
            truth[g][key] = solvers[g](*key)
        return truth[g][key]

    # the victim's acked update stream: long-range shortcut adds into a
    # large-diameter grid — every one provably changes its endpoints'
    # distance (grid hops >> 1), so "served after respawn" is decidable
    # from one query
    live_u = set(und["gu"])
    shortcut_pool = []
    for i in range(n):
        u, v = i, n - 1 - i
        e = (u, v) if u < v else (v, u)
        if u != v and e not in live_u and e not in shortcut_pool:
            shortcut_pool.append(e)
        if len(shortcut_pool) >= int(kill_cycles) * int(
            updates_per_cycle
        ) + 8:
            break

    base = tempfile.mkdtemp(prefix="bibfs-crash-") \
        if workdir is None else os.fspath(workdir)
    dirs = {}
    for r in range(int(replicas)):
        d = os.path.join(base, f"r{r}")
        os.makedirs(d, exist_ok=True)
        for g in names:
            write_graph_bin(os.path.join(d, f"{g}.bin"), n, edge_sets[g])
        dirs[f"r{r}"] = d

    victim_name = "r0"
    lost, failed, mismatches, checks = [], [], [], []
    recoveries = []

    def check(name, ok, detail=""):
        checks.append({"check": name, "ok": bool(ok),
                       "detail": str(detail)[:300]})
        return bool(ok)

    stop = threading.Event()
    tickets: list = []
    tickets_lock = threading.Lock()

    fleet = None
    victim = None
    old_si = sys.getswitchinterval()
    sys.setswitchinterval(5e-4)
    try:
        victim = ProcessReplica(
            victim_name, store_dir=dirs[victim_name],
            durable=True, fsync=fsync,
        )
        others = [
            engine_replica(
                f"r{i}",
                GraphStore.from_dir(
                    dirs[f"r{i}"], durable=True, fsync="batch",
                    compact_threshold=None,
                ),
                cache_entries=cache_entries, max_batch=max_batch,
            )
            for i in range(1, int(replicas))
        ]
        fleet = Router([victim] + others, poll_interval_s=0.2)

        pools = {}
        for g in routed:
            p = np.unique(
                rng.integers(0, n, size=(3 * int(hot_pool), 2)), axis=0
            )
            p = p[p[:, 0] != p[:, 1]][: int(hot_pool)]
            pools[g] = [(int(s), int(d)) for s, d in p]

        def traffic_main():
            """Open-loop routed traffic across the whole soak — the
            plane the crash cycles must not perturb: a victim kill
            costs reroutes, never tickets."""
            trng = np.random.default_rng(seed + 77)
            i = 0
            t0 = time.perf_counter()
            while not stop.is_set():
                g = routed[i % len(routed)]
                if trng.random() < repeat_fraction:
                    s, d = pools[g][int(trng.integers(len(pools[g])))]
                else:
                    s = int(trng.integers(0, n))
                    d = int(trng.integers(0, n))
                    if s == d:
                        d = (d + 1) % n
                try:
                    t = fleet.submit(s, d, g)
                except Exception as e:
                    failed.append({
                        "phase": "traffic-submit", "graph": g,
                        "query": [s, d],
                        "kind": getattr(e, "kind", "?"),
                        "error": str(e)[:200],
                    })
                else:
                    with tickets_lock:
                        tickets.append((g, s, d, t))
                i += 1
                delay = t0 + i / float(rate_qps) - time.perf_counter()
                if delay > 0:
                    stop.wait(delay)

        traffic = threading.Thread(
            target=traffic_main, name="bibfs-crash-traffic", daemon=True
        )
        traffic.start()

        def respawn_victim(bound):
            """Restart the victim and wait for it to be serving again:
            recovery-to-ready is clocked from BEFORE the respawn (the
            subprocess spawn + manifest/WAL recovery + health
            re-admission are all part of what a crash costs). The
            router table may still read a stale pre-kill "ready" until
            the poller's generation check lands, so readiness = table
            ready AND the victim answering a probe end-to-end."""
            t0 = time.monotonic()
            victim.restart()
            deadline = t0 + bound
            while time.monotonic() < deadline:
                try:
                    if (fleet.table().get(victim_name) == "ready"
                            and victim.probe("gu", timeout=5.0)):
                        return time.monotonic() - t0
                except Exception:
                    pass
                time.sleep(0.05)
            return None

        # ---- phase 1: SIGKILL/respawn cycles mid-update-stream -------
        acked: list = []  # every (u, v) add the victim ever acked
        shortcut_i = 0
        for cycle in range(int(kill_cycles)):
            cycle_adds = []
            for _ in range(int(updates_per_cycle)):
                e = shortcut_pool[shortcut_i]
                shortcut_i += 1
                # update() returns only after the child's ack reply —
                # under fsync=always, after the WAL record is fsync'd
                victim.update("gu", adds=[e])
                acked.append(e)
                cycle_adds.append(e)
            # the regression case: SIGKILL with ZERO gap after the ack
            victim.kill()
            time.sleep(0.3)  # let reroutes happen under traffic
            rec_s = respawn_victim(recovery_bound_s)
            recoveries.append(rec_s)
            check(
                f"cycle{cycle}-recovery",
                rec_s is not None,
                f"{rec_s}s (bound {recovery_bound_s}s)",
            )
            # every acked update must be visible after recovery: each
            # shortcut makes its endpoints 1 hop apart (they were far)
            for u, v in cycle_adds:
                try:
                    res = victim.wait_ticket(
                        victim.submit(u, v, "gu"), timeout=30.0
                    )
                    hops = res.hops
                except Exception as e:
                    hops = f"error: {e}"
                if hops != 1:
                    mismatches.append(
                        f"cycle{cycle}: acked add ({u},{v}) not served "
                        f"after respawn (hops={hops})"
                    )
            check(
                f"cycle{cycle}-acked-visible",
                not any(f"cycle{cycle}" in m for m in mismatches),
                f"{len(cycle_adds)} adds",
            )
            # total-state gate: fold the overlay and compare the
            # snapshot digest against the expected edge set exactly
            victim.roll("gu")
            got = victim.stats()["store"]["graphs"]["gu"]["digest"]
            expect = content_digest(n, canonical_pairs(
                n, np.array(sorted(set(und["gu"]) | set(acked)),
                            dtype=np.int64)
            ))
            check(f"cycle{cycle}-digest", got == expect,
                  f"{got[:12]} vs {expect[:12]}")

        # ---- phase 2: rolling swap commit + catch-up re-admission ----
        gr_adds = []
        live_r = set(und["gr"])
        for i in range(n):
            u, v = i, n - 1 - i
            e = (u, v) if u < v else (v, u)
            if u != v and e not in live_r:
                gr_adds.append(e)
            if len(gr_adds) >= int(roll_adds):
                break
        catch0 = fleet.stats()["catchups"]
        roll_out = fleet.rolling_swap("gr", adds=gr_adds, dels=[])
        committed = fleet.stats()["committed"].get("gr")
        check("roll-committed", roll_out["ok"] and committed == 2,
              f"ok={roll_out['ok']} committed={committed}")
        victim.kill()
        time.sleep(0.3)
        rec_s = respawn_victim(recovery_bound_s)
        recoveries.append(rec_s)
        check("post-roll-recovery", rec_s is not None, f"{rec_s}s")
        # the poller's generation check (the catch-up verdict) may land
        # one tick after the probe succeeds — wait for it explicitly
        t0w = time.monotonic()
        while (fleet.stats()["catchups"] <= catch0
               and time.monotonic() - t0w < recovery_bound_s):
            time.sleep(0.05)
        v_after = victim.version("gr")
        catchup_ok = check(
            "catchup-version",
            v_after == committed
            and fleet.stats()["catchups"] > catch0,
            f"declared v{v_after} vs committed v{committed}, "
            f"catchups {catch0} -> {fleet.stats()['catchups']}",
        )
        try:
            res = victim.wait_ticket(
                victim.submit(*gr_adds[0], "gr"), timeout=30.0
            )
            check("catchup-answer", res.hops == 1, f"hops={res.hops}")
        except Exception as e:
            check("catchup-answer", False, str(e))

        # ---- phase 3: torn-tail replay -------------------------------
        victim.update("gu", adds=[shortcut_pool[shortcut_i],
                                  shortcut_pool[shortcut_i + 1]])
        acked += shortcut_pool[shortcut_i: shortcut_i + 2]
        torn_pair = shortcut_pool[shortcut_i]
        shortcut_i += 2
        victim.kill()
        segs = sorted(
            (int(f.rsplit(".", 1)[1]), f)
            for f in os.listdir(dirs[victim_name])
            if f.startswith("gu.wal.") and f.rsplit(".", 1)[1].isdigit()
        )
        live_seg = os.path.join(dirs[victim_name], segs[-1][1])
        # the torn write a crash mid-append leaves: a record header
        # promising more payload than exists
        with open(live_seg, "ab") as f:
            f.write(b"\xff\x00\x00\x00" + b"\xde\xad\xbe\xef" * 3)
        # parent-side recovery of a COPY: exact, in-process, metric-
        # minting — the digest gate over the acked state
        copy_dir = os.path.join(base, "torn-copy")
        shutil.copytree(dirs[victim_name], copy_dir)
        st = GraphStore.from_dir(copy_dir, durable=True,
                                 compact_threshold=None)
        rec = st.stats()["graphs"]["gu"]["durable"]["recovered"]
        ov = st.overlay("gu")
        parent_ok = (
            rec["torn_tail_truncated"]
            and rec["replayed_records"] == 2
            and ov is not None
            and ov.solve(*torn_pair).hops == 1
        )
        st.close()
        check("torn-parent-recovery", parent_ok, rec)
        # child-side: the respawn truncates the tail and still serves
        # every acked update
        rec_s = respawn_victim(recovery_bound_s)
        recoveries.append(rec_s)
        check("torn-recovery", rec_s is not None, f"{rec_s}s")
        child_rec = (victim.stats()["store"]["graphs"]["gu"]
                     .get("durable", {}).get("recovered") or {})
        try:
            res = victim.wait_ticket(
                victim.submit(*torn_pair, "gu"), timeout=30.0
            )
            torn_child_ok = (
                res.hops == 1 and child_rec.get("torn_tail_truncated")
            )
        except Exception as e:
            torn_child_ok = False
            child_rec["error"] = str(e)[:200]
        check("torn-child-recovery", torn_child_ok, child_rec)
        torn_ok = bool(parent_ok and torn_child_ok)

        # ---- drain + verify the routed traffic plane -----------------
        stop.set()
        traffic.join(timeout=30.0)
        fleet.flush(timeout=120.0)
        with tickets_lock:
            rows = list(tickets)
        for _g, _s, _d, t in rows:
            try:
                t.wait(timeout=120.0)
            except Exception:
                pass
        for g, s, d, t in rows:
            if t.error is not None:
                failed.append({
                    "phase": "traffic", "graph": g, "query": [s, d],
                    "kind": getattr(t.error, "kind", "?"),
                    "error": str(t.error)[:200],
                })
            elif t.result is None:
                lost.append((g, s, d))
            else:
                ref = truth_for(g, s, d)
                if t.result.found != ref.found or (
                    ref.found and t.result.hops != ref.hops
                ):
                    mismatches.append(
                        f"traffic {g} {s}->{d}: "
                        f"{t.result.found}/{t.result.hops} != "
                        f"{ref.found}/{ref.hops}"
                    )
        # audit the truth source itself on a seeded subsample
        audit_bad = []
        arng = np.random.default_rng(seed + 7)
        for g in routed[:2]:
            keys = list(truth[g]) or [(0, n - 1)]
            for i in arng.choice(len(keys),
                                 size=min(12, len(keys)),
                                 replace=False):
                s, d = keys[int(i)]
                ref = solve_serial_csr(n, *csrs[g], s, d)
                got = truth_for(g, s, d)
                if got.found != ref.found or (
                    ref.found and got.hops != ref.hops
                ):
                    audit_bad.append(f"truth {g} {s}->{d}")

        stranded = sum(
            fleet.replica(r).load() for r in fleet.replica_names
            if fleet.replica(r).alive
        )
        render = REGISTRY.render()
        metrics_missing = [
            m for m in DURABLE_METRIC_FAMILIES if m not in render
        ]
        fstats = fleet.stats()
        bound_recs = [r for r in recoveries if r is not None]
        out = {
            "n_per_graph": n,
            "grid": f"{w}x{h}",
            "replicas": int(replicas),
            "fsync": fsync,
            "kill_cycles": int(kill_cycles),
            "updates_per_cycle": int(updates_per_cycle),
            "acked_updates": len(acked),
            "rate_qps": float(rate_qps),
            "recovery_bound_s": float(recovery_bound_s),
            "recoveries_s": [
                None if r is None else round(r, 3) for r in recoveries
            ],
            "recovery_max_s": (
                round(max(bound_recs), 3) if bound_recs else None
            ),
            "roll": {"ok": roll_out["ok"], "committed": committed},
            "checks": checks,
            "router": {
                "reroutes": fstats["reroutes"],
                "catchups": fstats["catchups"],
                "rolls": fstats["rolls"],
            },
            "tickets": {
                "submitted": len(rows),
                "failed": len(failed),
                "lost": len(lost),
                "stranded_outstanding": int(stranded),
            },
            "failed_sample": failed[:10],
            "mismatches": mismatches[:10],
            "truth_audit_mismatches": audit_bad[:10],
            "metrics_missing": metrics_missing,
            "setup_to_drain_s": round(
                time.perf_counter() - t_setup, 3
            ),
            # the gates
            "zero_acked_loss": all(
                c["ok"] for c in checks
                if "acked-visible" in c["check"]
                or "digest" in c["check"]
            ) and not any("acked add" in m for m in mismatches),
            "recovery_ok": bool(
                len(bound_recs) == len(recoveries)
                and all(r <= recovery_bound_s for r in bound_recs)
            ),
            "torn_tail_ok": torn_ok,
            "catchup_ok": bool(catchup_ok),
            "zero_lost": not lost and stranded == 0,
            "zero_failed": not failed,
            "verified_vs_truth": not mismatches and not audit_bad,
            "wal_metrics_ok": not metrics_missing,
            # every recorded check verdict, so a red row in checks[]
            # (roll-committed, catchup-answer, ...) can never coexist
            # with a green artifact
            "checks_ok": all(c["ok"] for c in checks),
        }
        out["ok"] = bool(
            out["zero_acked_loss"] and out["recovery_ok"]
            and out["torn_tail_ok"] and out["catchup_ok"]
            and out["zero_lost"] and out["zero_failed"]
            and out["verified_vs_truth"] and out["wal_metrics_ok"]
            and out["checks_ok"]
        )
        return out
    finally:
        stop.set()
        sys.setswitchinterval(old_si)
        if fleet is not None:
            fleet.close()
        elif victim is not None:
            victim.close()
        if workdir is None:
            shutil.rmtree(base, ignore_errors=True)


def _proc_mem(pid: int) -> dict | None:
    """One ``/proc/<pid>/smaps_rollup`` sample in bytes: ``rss`` (all
    resident pages, shared mapped ones counted in full per process),
    ``pss`` (proportional — shared pages divided among mappers, so a
    fleet-wide PSS sum counts one page-cache copy ONCE), ``private``
    (pages only this process holds). None when the process is gone or
    the platform has no smaps_rollup."""
    want = {"Rss:": "rss", "Pss:": "pss",
            "Private_Clean:": "private", "Private_Dirty:": "private"}
    out = {"rss": 0, "pss": 0, "private": 0}
    try:
        with open(f"/proc/{pid}/smaps_rollup") as f:
            for ln in f:
                key = want.get(ln.split(None, 1)[0])
                if key is not None:
                    out[key] += int(ln.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    return out


def run_memtier(
    *,
    scale: int = 24,
    edge_factor: int = 8,
    replicas: int = 3,
    queries: int = 48,
    rss_factor: float = 1.4,
    residency_probe_budget: int = 1,
    seed: int = 0,
    quick: bool = False,
    spawn_timeout_s: float = 900.0,
    workdir: str | None = None,
) -> dict:
    """The memory-tier soak (``bench.py --serve-memtier``): one durable
    store directory holding a streamed RMAT graph (scale 24 ≈ 16.7M
    nodes in the full run) served by a fleet of ``bibfs-serve``
    subprocess replicas that all ``np.memmap`` the SAME checkpointed
    arrays sidecar. The claims, gated in the full run (``quick`` runs
    every leg but only reports the machine-shape-sensitive RSS and
    remap-speed ratios):

    1. **one page-cache copy, M replicas** — aggregate fleet PSS
       (proportional RSS: shared mapped pages counted once across the
       fleet) stays within ``rss_factor`` of the private copy a single
       ``--no-mmap`` replica costs;
    2. **exact answers** — every routed query from every replica (and
       after the respawn) is verified hop-for-hop against fresh native
       BFS built independently from the ``.bin``;
    3. **recovery-by-remap beats rebuild** — a SIGKILL'd replica
       respawns to ready by mapping the sidecar, faster than the
       ``--no-mmap`` baseline's rebuild-from-``.bin`` spawn, at the
       exact store digest (verified over the ``memory`` control
       surface);
    4. **zero compile-sentinel events post-warmup** — the executable
       cache reports no new compiles on any replica across the traffic
       window;
    5. **cold tier round-trips** — the varint+delta compressed CSR
       decodes bit-exactly (digest-verified promote after demote),
       decode bandwidth is benched, and the residency accountant
       demotes under a starvation budget and promotes on access.

    Returns the ``bench_memtier.json`` payload."""
    import os
    import shutil
    import tempfile

    from bibfs_tpu.fleet import ProcessReplica
    from bibfs_tpu.graph.compress import decode_csr, encode_snapshot_csr
    from bibfs_tpu.graph.csr import build_csr
    from bibfs_tpu.graph.generate import rmat_stream_bin
    from bibfs_tpu.graph.io import read_graph_bin
    from bibfs_tpu.store import GraphStore, content_digest

    t_all = time.perf_counter()
    base = tempfile.mkdtemp(prefix="bibfs-memtier-") \
        if workdir is None else os.fspath(workdir)
    store_dir = os.path.join(base, "store")
    os.makedirs(store_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    checks: list = []

    def check(name, ok, detail=""):
        checks.append({"check": name, "ok": bool(ok),
                       "detail": str(detail)[:300]})
        return bool(ok)

    fleet: list = []
    baseline = None
    try:
        # ---- generate (streamed — never materializes the edge list) --
        t0 = time.perf_counter()
        bin_path = os.path.join(base, "rmat.bin")
        gen = rmat_stream_bin(
            bin_path, scale, edge_factor, seed=seed,
        )
        gen_s = time.perf_counter() - t0
        n, m = gen["n"], gen["m"]

        # ---- seed the durable store (writes .bin ckpt + sidecar) -----
        t0 = time.perf_counter()
        _, edges = read_graph_bin(bin_path)
        seed_store = GraphStore(
            wal_dir=store_dir, fsync="off", compact_threshold=None,
        )
        seed_store.add("rmat", n, edges)
        digest = seed_store.current("rmat").digest
        arrays_dir = seed_store.stats()["graphs"]["rmat"]["durable"]["arrays"]
        seed_store.close()
        build_s = time.perf_counter() - t0
        check("sidecar_written", arrays_dir is not None, arrays_dir)

        # ---- independent truth: fresh native BFS from the .bin -------
        row_ptr, col_ind = build_csr(n, edges)
        del edges
        try:
            from bibfs_tpu.solvers.native import (
                NativeGraph,
                solve_native_graph,
            )

            ng = NativeGraph(
                n, np.ascontiguousarray(row_ptr, dtype=np.int64),
                np.ascontiguousarray(col_ind, dtype=np.int32),
            )

            def truth(s, d):
                r = solve_native_graph(ng, s, d)
                return r.hops if r.found else None
        except (ImportError, OSError):
            from bibfs_tpu.solvers.serial import solve_serial_csr

            def truth(s, d):
                r = solve_serial_csr(n, row_ptr, col_ind, s, d)
                return r.hops if r.found else None

        pairs = []
        while len(pairs) < int(queries):
            s, d = (int(x) for x in rng.integers(0, n, size=2))
            if s != d:
                pairs.append((s, d))

        def drive(replica_list, plist):
            """Round-robin the pairs across the replicas; verify every
            answer hop-for-hop vs the fresh native truth."""
            bad = []
            for i, (s, d) in enumerate(plist):
                r = replica_list[i % len(replica_list)]
                res = r.wait_ticket(r.submit(s, d), timeout=120.0)
                want = truth(s, d)
                got = None if res is None else (
                    res.hops if res.found else None
                )
                if got != want:
                    bad.append({"pair": (s, d), "got": got,
                                "want": want, "replica": r.name})
            return bad

        # ---- baseline: ONE --no-mmap replica (private copy) ----------
        t0 = time.perf_counter()
        baseline = ProcessReplica(
            "base", store_dir=store_dir, durable=True, fsync="off",
            extra_args=["--no-mmap"], spawn_timeout_s=spawn_timeout_s,
        )
        rebuild_ready_s = time.perf_counter() - t0
        base_bad = drive([baseline], pairs[: max(8, len(pairs) // 4)])
        check("baseline_exact", not base_bad, base_bad[:3])
        base_mem_probe = baseline.memory()
        check("baseline_tier_hot",
              base_mem_probe["graphs"]["rmat"]["tier"] == "hot",
              base_mem_probe["graphs"]["rmat"]["tier"])
        base_mem = _proc_mem(baseline.pid) or {}
        baseline.close()
        baseline = None

        # ---- the fleet: M replicas mapping ONE sidecar ---------------
        ready_times = []
        for i in range(int(replicas)):
            t0 = time.perf_counter()
            fleet.append(ProcessReplica(
                f"m{i}", store_dir=store_dir, durable=True,
                fsync="off", spawn_timeout_s=spawn_timeout_s,
            ))
            ready_times.append(round(time.perf_counter() - t0, 3))

        probes = [r.memory() for r in fleet]
        check(
            "fleet_tier_mapped",
            all(p["graphs"]["rmat"]["tier"] == "mapped" for p in probes),
            [p["graphs"]["rmat"]["tier"] for p in probes],
        )
        check(
            "fleet_mapped_bytes",
            all(p["graphs"]["rmat"]["mapped_bytes"] > 0 for p in probes),
        )
        check(
            "fleet_digest",
            all(p["graphs"]["rmat"]["digest"] == digest for p in probes),
        )

        # warmup (each replica's host solver builds over the mapped
        # csr32), then the measured window with the compile sentinel
        warm_bad = drive(fleet, pairs[: len(fleet)])
        compiles_before = [
            r.stats()["exec_cache"]["misses"] for r in fleet
        ]
        fleet_bad = drive(fleet, pairs)
        check("fleet_exact", not (warm_bad or fleet_bad),
              (warm_bad + fleet_bad)[:3])
        compiles_after = [
            r.stats()["exec_cache"]["misses"] for r in fleet
        ]
        compile_events = sum(
            a - b for a, b in zip(compiles_after, compiles_before)
        )
        check("zero_compile_events", compile_events == 0, compile_events)

        mem_samples = []
        for _ in range(3):
            mem_samples.append({
                r.name: _proc_mem(r.pid) for r in fleet
            })
            time.sleep(0.2)
        sums = [
            {k: sum((s[r] or {}).get(k, 0) for r in s)
             for k in ("rss", "pss", "private")}
            for s in mem_samples if all(s.values())
        ]
        fleet_pss = max((s["pss"] for s in sums), default=0)
        rss_ratio = (
            round(fleet_pss / base_mem["rss"], 3)
            if base_mem.get("rss") else None
        )
        rss_ok = rss_ratio is not None and rss_ratio <= float(rss_factor)
        if not quick:
            check("fleet_rss_bounded", rss_ok,
                  f"sum(pss)={fleet_pss} vs {rss_factor}x "
                  f"baseline rss={base_mem.get('rss')}")

        # ---- SIGKILL + recovery-by-remap -----------------------------
        victim = fleet[0]
        victim.kill()
        t0 = time.perf_counter()
        victim.restart()
        remap_ready_s = time.perf_counter() - t0
        post = victim.memory()
        check("respawn_tier_mapped",
              post["graphs"]["rmat"]["tier"] == "mapped",
              post["graphs"]["rmat"]["tier"])
        check("respawn_digest",
              post["graphs"]["rmat"]["digest"] == digest)
        respawn_bad = drive([victim], pairs[: max(8, len(pairs) // 4)])
        check("respawn_exact", not respawn_bad, respawn_bad[:3])
        if not quick:
            check(
                "remap_beats_rebuild", remap_ready_s < rebuild_ready_s,
                f"remap {remap_ready_s:.2f}s vs rebuild "
                f"{rebuild_ready_s:.2f}s",
            )
        for r in fleet:
            r.close()
        fleet = []

        # ---- cold tier: codec bench + residency accountant -----------
        cold_store = GraphStore.from_dir(
            store_dir, durable=True, compact_threshold=None,
            mmap_arrays=False,
            residency_budget=int(residency_probe_budget),
        )
        ms0 = cold_store.memory_stats()
        check("accountant_demoted",
              ms0["graphs"]["rmat"]["tier"] == "cold",
              ms0["graphs"]["rmat"]["tier"])
        snap = cold_store.acquire("rmat")
        t0 = time.perf_counter()
        _ = snap.pairs  # decode-promote on access
        promote_s = time.perf_counter() - t0
        check("accountant_promoted", snap.tier == "hot", snap.tier)
        check("promote_digest_exact",
              content_digest(snap.n, snap.pairs) == digest)
        t0 = time.perf_counter()
        comp = encode_snapshot_csr(snap)
        encode_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        d_rp, d_ci = decode_csr(comp)
        decode_s = time.perf_counter() - t0
        s_rp, s_ci = snap.csr()
        check("codec_roundtrip",
              np.array_equal(d_rp, s_rp) and np.array_equal(d_ci, s_ci))
        cold = {
            "ratio": comp.ratio,
            "compressed_bytes": comp.compressed_bytes,
            "raw_bytes": comp.raw_bytes,
            "encode_s": round(encode_s, 3),
            "decode_s": round(decode_s, 4),
            "decode_mb_s": round(
                comp.raw_bytes / max(decode_s, 1e-9) / 1e6, 1
            ),
            "promote_s": round(promote_s, 4),
        }
        snap.release()
        cold_store.close()

        ok = all(c["ok"] for c in checks)
        return {
            "ok": ok,
            "n": n,
            "m": m,
            "scale": scale,
            "edge_factor": edge_factor,
            "generate": {**gen, "gen_s": round(gen_s, 1)},
            "store_build_s": round(build_s, 1),
            "replicas": int(replicas),
            "queries": len(pairs),
            "rebuild_ready_s": round(rebuild_ready_s, 2),
            "remap_ready_s": round(remap_ready_s, 2),
            "fleet_ready_s": ready_times,
            "baseline_mem": base_mem,
            "fleet_mem_samples": mem_samples,
            "fleet_pss_max": fleet_pss,
            "rss_ratio": rss_ratio,
            "rss_factor": float(rss_factor),
            "rss_ok": rss_ok,
            "compile_events": compile_events,
            "memory_probe": probes[0] if probes else None,
            "cold_tier": cold,
            "checks": checks,
            "total_s": round(time.perf_counter() - t_all, 1),
        }
    finally:
        for r in fleet:
            try:
                r.close()
            except Exception:
                pass
        if baseline is not None:
            try:
                baseline.close()
            except Exception:
                pass
        if workdir is None:
            shutil.rmtree(base, ignore_errors=True)


def run_queries(n: int, edges, *, queries: int = 200,
                mix: dict | None = None, ms_traffic: int = 24,
                msbfs_min_speedup: float = 3.0, seed: int = 0,
                wal_dir: str | None = None, quick: bool = False) -> dict:
    """The query-taxonomy soak (``bench.py --serve-queries``).

    Five phases against ONE durable, history-retaining store
    (``retain_history=True`` — the as-of read path's ground truth):

    0. **device tier** (FIRST, on a pristine process state — the
       jitted sweep's wall clock is acutely noise-sensitive on the
       shared box): paired host-vs-device A/B rounds per kind on
       identical traffic, gated (full runs) on the solver-stamped
       sweep clocks at ``msbfs_min_speedup``x; per-source-count A/B
       rows whose measured crossovers become the calibration
       ``queries`` block; device msbfs exactness across a dedicated
       mid-traffic hot-swap; device k-shortest IDENTICAL to host
       Yen's; weighted exact vs the Dijkstra oracle on both tiers.
    1. **history build + mid-traffic as-of**: the graph rolls under
       live ``as_of`` + point-to-point traffic (one roll lands
       MID-STREAM), and every historical answer is verified hop-exact
       against a Python-tracked reference edge set for its version —
       the "time-travel reads stay exact across a hot-swap" gate.
    2. **mixed taxonomy traffic**: a ``--mix``-shaped stream
       (default ``pt=0.4,ms=0.2,weighted=0.2,kshortest=0.1,
       asof=0.1``) through one engine; every weighted answer is
       checked exact against the NumPy Dijkstra oracle, every msbfs
       per-source hop against independent serial solves, every
       k-shortest path CSR-edge-validated + non-decreasing, every pt
       answer against the serial oracle.
    3. **msbfs speedup**: ``ms_traffic`` 64-source MultiSource
       queries (shared source set, distinct destinations) served in
       one flush — packed sweeps shared across the flush — timed
       against the SAME (source, dst) units as per-query
       point-to-point solves on a fresh engine; gated at
       ``msbfs_min_speedup`` x qps, with the msbfs hop answers
       cross-checked against the pt answers.
    4. **per-kind resilience**: each kind's chaos seam
       (``msbfs``/``weighted``/``kshortest``/``asof_replay`` +
       ``host_batch`` for pt, plus the device rungs'
       ``msbfs_device``/``weighted_device``/``kshortest_device``)
       injected on a fresh engine; the gate is every query still
       answering THROUGH the degrade, with the fallback/bisection
       witnessed in the resilience counters.
    """
    import os
    import tempfile

    from bibfs_tpu.graph.csr import build_csr
    from bibfs_tpu.query import (
        AsOf,
        KShortest,
        MultiSource,
        PointToPoint,
        Weighted,
    )
    from bibfs_tpu.query.weighted import dijkstra_numpy, synthetic_weights
    from bibfs_tpu.serve import QueryEngine
    from bibfs_tpu.serve.faults import FaultPlan
    from bibfs_tpu.serve.resilience import QueryError
    from bibfs_tpu.solvers.serial import solve_serial_csr
    from bibfs_tpu.store import GraphStore
    from bibfs_tpu.store.delta import canonical_edge

    rng = np.random.default_rng(seed)
    if wal_dir is None:
        wal_dir = tempfile.mkdtemp(prefix="bibfs-queries-")
    os.makedirs(wal_dir, exist_ok=True)
    store = GraphStore(
        compact_threshold=None, wal_dir=wal_dir,
        retain_history=True, fsync="always",
    )
    store.add("g", n, edges)

    def edge_set():
        return set(
            map(tuple, store.current("g").undirected_edges().tolist())
        )

    def rand_edges(count, existing):
        out = set()
        while len(out) < count:
            u = int(rng.integers(n))
            v = int(rng.integers(n))
            if u == v:
                continue
            e = canonical_edge(n, u, v)
            if e not in existing and e not in out:
                out.add(e)
        return sorted(out)

    refs = {1: edge_set()}
    csrs = {1: build_csr(n, np.array(sorted(refs[1]), dtype=np.int64))}

    def roll(adds, dels):
        store.roll("g", adds=adds, dels=dels)
        v = store.current("g").version
        refs[v] = edge_set()
        csrs[v] = build_csr(n, np.array(sorted(refs[v]), dtype=np.int64))
        return v

    # ---- phase 0: device-tier rungs ----------------------------------
    # per-kind host-vs-device A/B on identical traffic (fresh engine
    # per timed pass, device side warmed so compile/upload never lands
    # in the measurement), the measured crossovers for the calibration
    # ``queries`` block, device-msbfs exactness ACROSS a mid-traffic
    # hot-swap, and device k-shortest identity with host Yen's.
    # Runs FIRST: the jitted sweep's wall time is acutely sensitive to
    # accumulated process state on the shared 1-core box (measured
    # ~2.5x inflation after the soak phases churn the allocator while
    # the NumPy sweep is unaffected), so the A/B measures both tiers
    # in the same pristine state — the pairing, not the absolute
    # numbers, is the measurement.
    def _force_device_rungs(e):
        """Pin the device rungs ON for the A/B regardless of what a
        previous soak banked in calibration.json — the measurement
        must exercise the rung it is measuring."""
        e.routes["msbfs_device"].min_sources = 1
        e.routes["weighted_device"].min_batch = 1
        e.routes["kshortest_device"].min_k = 2

    def _timed_pass(qs, *, device, warm=None, repeats=3):
        """One engine pass over ``qs``, best-of-``repeats`` (fresh
        engine each time; the device side warmed so compile/upload
        never lands in a measurement). Returns ``(wall_s, solver_s,
        results, kinds)`` — ``solver_s`` is the SOLVER-STAMPED batch
        clock (``result.time_s``: the sweep/relaxation itself, the
        same clock the adaptive policy learns from), which is what the
        sweep-vs-sweep gate compares; wall time rides along for the
        A/B rows."""
        best = best_solver = None
        keep = None
        for _r in range(repeats):
            e = QueryEngine(
                store=store, graph="g", device_batches=device,
            )
            if device:
                _force_device_rungs(e)
            if warm is not None:
                # twice: the first run triggers XLA's ASYNC compile —
                # on the 1-core box its worker threads finishing
                # during a timed pass read as a 2x-slower kernel, so
                # the second warm run also absorbs that window
                e.query_many(list(warm), return_errors=True)
                e.query_many(list(warm), return_errors=True)
            t0 = time.perf_counter()
            res = e.query_many(list(qs), return_errors=True)
            dt = time.perf_counter() - t0
            kinds = e.stats()["query_kinds"]
            e.close()
            solver = max(
                (getattr(r, "time_s", 0.0) for r in res
                 if not isinstance(r, QueryError)),
                default=dt,
            )
            if best is None or dt < best:
                best, keep = dt, (res, kinds)
            if best_solver is None or solver < best_solver:
                best_solver = solver
        return best, best_solver, keep[0], keep[1]

    failures: list[str] = []
    dev_failures: list[str] = []
    dev_csr = csrs[1]
    dev_sources = tuple(
        int(x) for x in rng.choice(n, size=min(64, n - 1),
                                   replace=False)
    )
    dev_dsts = [int(x) for x in rng.integers(0, n, size=ms_traffic)]
    ms_queries_dev = [MultiSource(dev_sources, d) for d in dev_dsts]
    warm_ms = [MultiSource(dev_sources, (dev_dsts[0] + 1) % n)]
    # the gate A/B runs as PAIRED rounds — one host pass immediately
    # followed by one device pass — and gates on the best round's
    # ratio: the shared 1-core box drifts through slow windows that
    # hit the two tiers' resource profiles unequally (measured: the
    # jitted sweep swings ~2x between runs while the NumPy sweep
    # holds), and adjacent passes share the window
    host_ms_s = host_ms_sweep = dev_ms_s = dev_ms_sweep = None
    best_pair = 0.0
    dev_ms_res = dev_ms_kinds = host_ms_kinds = None
    for _round in range(3):
        h_s, h_sw, h_res, h_kinds = _timed_pass(
            ms_queries_dev, device=False, warm=warm_ms, repeats=1,
        )
        d_s, d_sw, d_res, d_kinds = _timed_pass(
            ms_queries_dev, device=True, warm=warm_ms, repeats=1,
        )
        if host_ms_kinds is None:
            host_ms_kinds, dev_ms_res, dev_ms_kinds = (
                h_kinds, d_res, d_kinds
            )
        if h_sw > 0 and d_sw > 0 and h_sw / d_sw > best_pair:
            best_pair = h_sw / d_sw
            host_ms_s, host_ms_sweep = h_s, h_sw
            dev_ms_s, dev_ms_sweep = d_s, d_sw
    if not dev_ms_kinds.get("msbfs", {}).get("msbfs_device"):
        dev_failures.append("device msbfs rung not exercised")
    if host_ms_kinds.get("msbfs", {}).get("msbfs_device"):
        dev_failures.append("host A/B side leaked onto the device rung")
    for q, res in zip(ms_queries_dev, dev_ms_res):
        if isinstance(res, QueryError):
            dev_failures.append(f"device msbfs {q.dst}: {res}")
            continue
        for s, hops in zip(q.sources, res.per_source):
            truth = solve_serial_csr(n, *dev_csr, int(s), q.dst)
            want = truth.hops if truth.found else None
            if hops != want:
                dev_failures.append(
                    f"device msbfs ({s}->{q.dst}): {hops} != {want}"
                )
    # measured msbfs crossover: the smallest source count where the
    # jitted sweep beats the NumPy one on this platform
    ab_rows: dict = {}
    min_sources = None
    k_ladder = (64,) if quick else (4, 16, 64)
    for kk in k_ladder:
        ss = dev_sources[: min(kk, len(dev_sources))]
        kq = [MultiSource(ss, d)
              for d in dev_dsts[: max(4, ms_traffic // 4)]]
        wq = [MultiSource(ss, (dev_dsts[0] + 3) % n)]
        h_s, h_sw, _hr, _hk = _timed_pass(kq, device=False, warm=wq)
        d_s, d_sw, _dr, _dk = _timed_pass(kq, device=True, warm=wq)
        ab_rows[str(kk)] = {
            "host_ms": round(h_s * 1e3, 3),
            "device_ms": round(d_s * 1e3, 3),
            "host_sweep_ms": round(h_sw * 1e3, 3),
            "device_sweep_ms": round(d_sw * 1e3, 3),
            "device_wins": bool(d_sw < h_sw),
        }
        if d_sw < h_sw and min_sources is None:
            min_sources = int(kk)
        if (kk == len(dev_sources) and h_sw > 0 and d_sw > 0
                and h_sw / d_sw > best_pair):
            # the full-width row measures the SAME sweep shape as the
            # gate's paired A/B, later in the process — one more pair
            # observation for the best-round gate
            best_pair = h_sw / d_sw
            host_ms_s, host_ms_sweep = h_s, h_sw
            dev_ms_s, dev_ms_sweep = d_s, d_sw

    # the gate clock is the SOLVER-STAMPED sweep time (the packed
    # sweep vs the jitted sweep on the same 64-source traffic — the
    # clock the adaptive policy learns from; wall time carries the
    # shared per-query read/ticket overhead both tiers pay
    # identically and is reported alongside)
    dev_units = len(dev_sources) * len(dev_dsts)
    dev_ms_qps = (
        dev_units / dev_ms_sweep if dev_ms_sweep > 0 else float("inf")
    )
    host_ms_qps = (
        dev_units / host_ms_sweep if host_ms_sweep > 0
        else float("inf")
    )
    dev_speedup = (
        dev_ms_qps / host_ms_qps if host_ms_qps > 0 else float("inf")
    )
    dev_wall_qps = dev_units / dev_ms_s if dev_ms_s > 0 else float("inf")
    host_wall_qps = (
        dev_units / host_ms_s if host_ms_s > 0 else float("inf")
    )

    # weighted A/B: identical traffic, exact vs the Dijkstra oracle
    w_pairs = [
        (int(rng.integers(n)), int(rng.integers(n)))
        for _ in range(8 if quick else 16)
    ]
    w_queries = [Weighted(s, d, weight_seed=seed) for s, d in w_pairs]
    warm_w = [Weighted((w_pairs[0][0] + 1) % n, w_pairs[0][1],
                       weight_seed=seed)]
    host_w_s, _host_w_sw, host_w_res, _hk = _timed_pass(
        w_queries, device=False, warm=warm_w,
    )
    dev_w_s, _dev_w_sw, dev_w_res, dev_w_kinds = _timed_pass(
        w_queries, device=True, warm=warm_w,
    )
    if not dev_w_kinds.get("weighted", {}).get("weighted_device"):
        dev_failures.append("device weighted rung not exercised")
    dev_w = synthetic_weights(*dev_csr, seed)
    for q, res, href in zip(w_queries, dev_w_res, host_w_res):
        if isinstance(res, QueryError):
            dev_failures.append(f"device weighted {q.src},{q.dst}: {res}")
            continue
        dist, _par = dijkstra_numpy(
            n, *dev_csr, dev_w, q.src, q.dst
        )
        ref = dist[q.dst]
        if res.found != bool(np.isfinite(ref)) or (
            res.found and abs(res.dist - float(ref)) > 1e-9
        ):
            dev_failures.append(
                f"device weighted ({q.src},{q.dst}): {res.dist} != {ref}"
            )
        if not isinstance(href, QueryError) and (
            (res.found, res.dist) != (href.found, href.dist)
        ):
            dev_failures.append(
                f"weighted host/device disagree ({q.src},{q.dst})"
            )

    # k-shortest A/B: batched device output IDENTICAL to host Yen's
    ks_pairs = [
        (int(rng.integers(n)), int(rng.integers(n)))
        for _ in range(4 if quick else 8)
    ]
    ks_queries = [KShortest(s, d, k=4) for s, d in ks_pairs
                  if s != d]
    warm_ks = [KShortest((ks_pairs[0][0] + 1) % n, ks_pairs[0][1], k=2)]
    host_ks_s, _host_ks_sw, host_ks_res, _hk = _timed_pass(
        ks_queries, device=False, warm=warm_ks,
    )
    dev_ks_s, _dev_ks_sw, dev_ks_res, dev_ks_kinds = _timed_pass(
        ks_queries, device=True, warm=warm_ks,
    )
    if not dev_ks_kinds.get("kshortest", {}).get("kshortest_device"):
        dev_failures.append("device kshortest rung not exercised")
    ks_identical = True
    for q, a, b in zip(ks_queries, host_ks_res, dev_ks_res):
        if isinstance(a, QueryError) or isinstance(b, QueryError):
            ks_identical = False
            dev_failures.append(f"kshortest error ({q.src},{q.dst})")
            continue
        if a.paths != b.paths or a.hops != b.hops:
            ks_identical = False
            dev_failures.append(
                f"kshortest paths differ ({q.src},{q.dst})"
            )

    # mid-traffic hot-swap through the device rungs: answers exact
    # against the edge set of the snapshot each flush bound
    swap_eng = QueryEngine(store=store, graph="g", device_batches=True)
    _force_device_rungs(swap_eng)
    swap_ok = True

    def _swap_check(csr):
        nonlocal swap_ok
        for d in dev_dsts[:4]:
            res = swap_eng.query_one(MultiSource(dev_sources, int(d)))
            for s, hops in zip(dev_sources, res.per_source):
                truth = solve_serial_csr(n, *csr, int(s), int(d))
                want = truth.hops if truth.found else None
                if hops != want:
                    swap_ok = False
                    dev_failures.append(
                        f"device msbfs post-swap ({s}->{d}): "
                        f"{hops} != {want}"
                    )

    _swap_check(dev_csr)
    v_dev = roll(rand_edges(6, refs[1]), [])
    _swap_check(csrs[v_dev])
    st_swap = swap_eng.stats()["query_kinds"]
    if st_swap.get("msbfs", {}).get("msbfs_device", 0) < 2:
        swap_ok = False
        dev_failures.append("hot-swap phase did not ride the device rung")
    swap_eng.close()

    crossovers = {
        "msbfs_min_sources": (
            min_sources if min_sources is not None else 1 << 30
        ),
        "weighted_min_batch": 1 if dev_w_s < host_w_s else 1 << 30,
        "kshortest_min_k": 2 if dev_ks_s < host_ks_s else 1 << 30,
    }
    device_exact = len(dev_failures) == 0
    device_ok = bool(
        device_exact and swap_ok and ks_identical
        and (quick or dev_speedup >= float(msbfs_min_speedup))
    )
    failures.extend(dev_failures)


    # ---- phase 1: history + mid-traffic as-of ------------------------
    # seed the roll from the LIVE edge set (phase 0 already rolled
    # the store once: excluding only v1's edges could re-add one of
    # phase 0's and fail the roll)
    cur = edge_set()
    v2 = roll(rand_edges(8, cur), sorted(rng.permutation(
        np.array(sorted(cur), dtype=np.int64))[:4].tolist()
    ))
    eng = QueryEngine(store=store, graph="g")
    asof_q = max(queries // 4, 16)
    checked = {1: 0, 2: 0}
    pre_asof_failures = len(failures)
    rolled_mid = False
    for i in range(asof_q):
        if i == asof_q // 2 and not rolled_mid:
            # the MID-TRAFFIC hot-swap: v3 commits while as-of
            # queries for v1/v2 are in flight either side of it
            roll(rand_edges(6, refs[v2]), [])
            rolled_mid = True
        v = 1 if i % 2 == 0 else 2
        s = int(rng.integers(n))
        d = int(rng.integers(n))
        res = eng.query_one(AsOf(PointToPoint(s, d), v))
        truth = solve_serial_csr(n, *csrs[v], s, d)
        if (res.found, res.hops) != (truth.found, truth.hops):
            failures.append(
                f"asof v{v} ({s},{d}): {res.found, res.hops} != "
                f"{truth.found, truth.hops}"
            )
        else:
            checked[v] += 1
    asof_ok = (len(failures) == pre_asof_failures and rolled_mid
               and min(checked.values()) > 0)
    cur_v = store.current("g").version
    cur_csr = csrs[cur_v]

    # ---- phase 2: mixed taxonomy traffic -----------------------------
    if mix is None:
        mix = {"pt": 0.4, "msbfs": 0.2, "weighted": 0.2,
               "kshortest": 0.1, "asof": 0.1}
    stream = sample_query_mix(
        n, queries, mix, seed=seed + 1, ms_sources=16,
        weight_seed=seed, versions=(1, v2),
    )
    pre_mixed_failures = len(failures)
    t0 = time.perf_counter()
    results = eng.query_many(stream, return_errors=True)
    mixed_s = time.perf_counter() - t0
    served = {k: 0 for k in ("pt", "msbfs", "weighted",
                             "kshortest", "asof")}
    w_cache: dict = {}
    for q, res in zip(stream, results):
        if isinstance(res, QueryError):
            failures.append(f"{q.kind} {q}: {res}")
            continue
        served[q.kind] += 1
        if isinstance(q, PointToPoint):
            truth = solve_serial_csr(n, *cur_csr, q.src, q.dst)
            if (res.found, res.hops) != (truth.found, truth.hops):
                failures.append(f"pt ({q.src},{q.dst}) wrong hops")
        elif isinstance(q, Weighted):
            key = int(q.weight_seed)
            if key not in w_cache:
                w_cache[key] = synthetic_weights(*cur_csr, key)
            dist, _par = dijkstra_numpy(
                n, *cur_csr, w_cache[key], q.src, q.dst
            )
            ref = dist[q.dst]
            if res.found != bool(np.isfinite(ref)) or (
                res.found and abs(res.dist - float(ref)) > 1e-9
            ):
                failures.append(
                    f"weighted ({q.src},{q.dst}): {res.dist} != {ref}"
                )
        elif isinstance(q, MultiSource):
            for s, hops in zip(q.sources, res.per_source):
                truth = solve_serial_csr(n, *cur_csr, int(s), q.dst)
                want = truth.hops if truth.found else None
                if hops != want:
                    failures.append(
                        f"msbfs ({s}->{q.dst}): {hops} != {want}"
                    )
            if res.found and not _validate(
                cur_csr, res, res.path[0], q.dst
            ):
                failures.append(f"msbfs path invalid -> {q.dst}")
        elif isinstance(q, KShortest):
            if res.hops != sorted(res.hops):
                failures.append(f"kshortest ({q.src},{q.dst}) unsorted")
            for p, h in zip(res.paths, res.hops):
                from bibfs_tpu.solvers.api import validate_path

                if not validate_path(cur_csr, p, q.src, q.dst, hops=h):
                    failures.append(
                        f"kshortest ({q.src},{q.dst}) invalid path"
                    )
        elif isinstance(q, AsOf):
            truth = solve_serial_csr(
                n, *csrs[int(q.version)], q.inner.src, q.inner.dst
            )
            if (res.found, res.hops) != (truth.found, truth.hops):
                failures.append(
                    f"asof-mixed v{q.version} wrong answer"
                )
    # only kinds the MIX actually carries must be served: a caller's
    # --mix pt=1 override is a valid single-kind soak, not a failure
    mixed_ok = len(failures) == pre_mixed_failures and all(
        served[k] > 0 for k in served if mix.get(k)
    )
    mixed_stats = eng.stats()
    eng.close()

    # ---- phase 3: msbfs speedup over per-query pt solves -------------
    m_src = min(64, n - 1)
    sources = tuple(
        int(x) for x in rng.choice(n, size=m_src, replace=False)
    )
    dsts = [int(x) for x in rng.choice(n, size=ms_traffic, replace=True)]
    ms_queries = [MultiSource(sources, d) for d in dsts]
    ms_eng = QueryEngine(store=store, graph="g")
    t0 = time.perf_counter()
    ms_results = ms_eng.query_many(ms_queries, return_errors=True)
    ms_s = time.perf_counter() - t0
    ms_eng.close()
    pt_pairs = [(s, d) for d in dsts for s in sources]
    # the gate's baseline: PER-QUERY point-to-point serving — one
    # submit+flush per (source, dst) unit, the shape a client issuing
    # independent queries gets (the acceptance criterion's wording);
    # the engine's own batched route over the same units is measured
    # alongside for the full picture (pt_batched_qps)
    pt_eng = QueryEngine(store=store, graph="g")
    t0 = time.perf_counter()
    pt_results = [pt_eng.query(s, d) for s, d in pt_pairs]
    pt_s = time.perf_counter() - t0
    pt_eng.close()
    ptb_eng = QueryEngine(store=store, graph="g")
    t0 = time.perf_counter()
    ptb_eng.query_many(pt_pairs, return_errors=True)
    ptb_s = time.perf_counter() - t0
    ptb_eng.close()
    units = len(pt_pairs)
    ms_qps = units / ms_s if ms_s > 0 else float("inf")
    pt_qps = units / pt_s if pt_s > 0 else float("inf")
    ptb_qps = units / ptb_s if ptb_s > 0 else float("inf")
    speedup = ms_qps / pt_qps if pt_qps > 0 else float("inf")
    cross_ok = True
    it = iter(pt_results)
    for q, res in zip(ms_queries, ms_results):
        if isinstance(res, QueryError):
            cross_ok = False
            # keep the pt iterator aligned: this query still owns
            # len(sources) reference slots — skipping them silently
            # would pair every LATER comparison with the wrong pt
            # answer and bury the real failure under fabricated ones
            for _ in q.sources:
                next(it)
            continue
        for s, hops in zip(q.sources, res.per_source):
            ref = next(it)
            want = (
                ref.hops if not isinstance(ref, QueryError) and ref.found
                else None
            )
            if hops != want:
                cross_ok = False
                failures.append(
                    f"msbfs-vs-pt ({s}->{q.dst}): {hops} != {want}"
                )
    msbfs_ok = cross_ok and speedup >= float(msbfs_min_speedup)

    # ---- phase 4: per-kind fault-injected degrade --------------------
    kind_sites = {
        "pt": "host_batch",
        "msbfs": "msbfs",
        "weighted": "weighted",
        "kshortest": "kshortest",
        "asof": "asof_replay",
        # the device rungs' chaos seams: a faulted device rung must
        # degrade to its host kind rung with zero lost tickets
        "msbfs_device": "msbfs_device",
        "weighted_device": "weighted_device",
        "kshortest_device": "kshortest_device",
    }
    resilience: dict = {}
    for kind, site in kind_sites.items():
        on_device = kind.endswith("_device")
        plan = FaultPlan.parse(f"{site}:times=4", seed=seed)
        keng = QueryEngine(
            store=store, graph="g", faults=plan,
            device_batches=True if on_device else None,
        )
        if on_device:
            _force_device_rungs(keng)
        kqs: list = []
        for _ in range(4):
            s = int(rng.integers(n))
            d = int(rng.integers(n))
            if kind == "pt":
                kqs.append(PointToPoint(s, d))
            elif kind in ("msbfs", "msbfs_device"):
                # enough distinct sources to clear the device rung's
                # calibrated crossover when that rung is the target
                kqs.append(MultiSource(
                    tuple((s + j) % n for j in range(12)), d
                ))
            elif kind in ("weighted", "weighted_device"):
                kqs.append(Weighted(s, d, weight_seed=seed))
            elif kind in ("kshortest", "kshortest_device"):
                kqs.append(KShortest(s, d, k=2))
            else:
                kqs.append(AsOf(PointToPoint(s, d), 1))
        kres = keng.query_many(kqs, return_errors=True)
        kstats = keng.stats()
        keng.close()
        res_block = kstats["resilience"]
        answered = sum(
            1 for r in kres if not isinstance(r, QueryError)
        )
        degrade = (
            sum(res_block["fallbacks"].values())
            + res_block["bisections"]
        )
        fired = kstats["resilience"]["faults"]["fired_total"]
        resilience[kind] = {
            "site": site,
            "answered": answered,
            "of": len(kqs),
            "faults_fired": fired,
            "fallbacks": {
                k: v for k, v in res_block["fallbacks"].items() if v
            },
            "retries": res_block["retries"],
            "ok": answered == len(kqs) and fired > 0 and degrade > 0,
        }
    resilience_ok = all(r["ok"] for r in resilience.values())
    store.close()

    ok = bool(asof_ok and mixed_ok and msbfs_ok and device_ok
              and resilience_ok and not failures)
    return {
        "ok": ok,
        "n": n,
        "queries": queries,
        "mix": mix,
        "failures": failures[:20],
        "device": {
            "ok": device_ok,
            "exact": device_exact,
            "msbfs": {
                "speedup_vs_host_sweep": round(dev_speedup, 2),
                "min_speedup": float(msbfs_min_speedup),
                "gated": not quick,
                "device_qps": round(dev_ms_qps, 1),
                "host_sweep_qps": round(host_ms_qps, 1),
                "device_wall_qps": round(dev_wall_qps, 1),
                "host_wall_qps": round(host_wall_qps, 1),
                "units": dev_units,
                "ab_by_sources": ab_rows,
            },
            "weighted": {
                "host_ms": round(host_w_s * 1e3, 3),
                "device_ms": round(dev_w_s * 1e3, 3),
                "queries": len(w_queries),
            },
            "kshortest": {
                "identical_to_host": ks_identical,
                "host_ms": round(host_ks_s * 1e3, 3),
                "device_ms": round(dev_ks_s * 1e3, 3),
                "queries": len(ks_queries),
            },
            "hot_swap": {"ok": swap_ok, "version": cur_v},
            "crossovers": crossovers,
        },
        "asof": {
            "ok": asof_ok,
            "versions_checked": checked,
            "mid_traffic_swap": rolled_mid,
            "final_version": cur_v,
        },
        "mixed": {
            "ok": mixed_ok,
            "served_by_kind": served,
            "wall_s": round(mixed_s, 3),
            "query_kinds": mixed_stats["query_kinds"],
            "kind_cache": mixed_stats["kind_cache"],
        },
        "msbfs": {
            "ok": msbfs_ok,
            "speedup": round(speedup, 2),
            "min_speedup": float(msbfs_min_speedup),
            "msbfs_qps": round(ms_qps, 1),
            "pt_qps": round(pt_qps, 1),
            "pt_batched_qps": round(ptb_qps, 1),
            "units": units,
            "sources": m_src,
            "traffic": ms_traffic,
            "cross_checked": cross_ok,
        },
        "resilience": {"ok": resilience_ok, **resilience},
    }


def _validate(csr, res, s, d) -> bool:
    from bibfs_tpu.solvers.api import validate_path

    return validate_path(csr, res.path, s, d, hops=res.hops)


def measure_capacity(make_engine, pairs) -> float:
    """Closed-loop capacity of a fresh sync engine driven the way the
    open-loop driver saturates it — flush_threshold-sized batched
    flushes (queries/s). This is the anchor the offered-rate ladder is
    scaled from; a per-query estimate would undersell the batch-
    amortized ceiling by 2-3x and leave the 'saturating' rate
    unsaturating."""
    engine = make_engine()
    try:
        step = max(engine.flush_threshold, 1)
        engine.query_many(pairs[:step])  # warm the solver + first batch
        rest = pairs[step:]
        if len(rest) == 0:
            rest = pairs  # tiny pool: re-time the (warmed) chunk
        t0 = time.perf_counter()
        for i in range(0, len(rest), step):
            engine.query_many(rest[i: i + step])
        dt = time.perf_counter() - t0
        return len(rest) / dt if dt > 0 else float("inf")
    finally:
        engine.close()


def compare_engines(
    n, edges, pairs, rates, *, max_wait_ms: float = 5.0,
    max_queue: int | None = None, max_inflight: int = 2,
    top_repeats: int = 1, verify: bool = True, **engine_kwargs,
) -> dict:
    """Sync vs pipelined under the same open-loop schedules — the
    ``bench_load.json`` payload. ``rates`` is the offered-rate ladder
    (queries/s); each point gets a fresh engine of each flavor. The
    LAST (saturating) rate runs ``top_repeats`` times per engine and
    keeps each engine's best sustained row — the headline judgment
    should reflect each engine's ceiling, not one noisy scheduler
    window (both sides get the same treatment)."""
    from bibfs_tpu.graph.csr import build_csr, canonical_pairs
    from bibfs_tpu.serve.engine import QueryEngine
    from bibfs_tpu.serve.pipeline import PipelinedQueryEngine

    cpairs = canonical_pairs(n, edges)
    oracle = csr = None
    if verify:
        from bibfs_tpu.solvers.serial import solve_serial_csr

        csr = build_csr(n, pairs=cpairs)
        oracle = {
            (int(s), int(d)): solve_serial_csr(n, *csr, int(s), int(d))
            for s, d in {(int(s), int(d)) for s, d in pairs}
        }

    def make_sync():
        return QueryEngine(n, edges, pairs=cpairs, **engine_kwargs)

    def make_pipe():
        return PipelinedQueryEngine(
            n, edges, pairs=cpairs, max_wait_ms=max_wait_ms,
            max_queue=max_queue, max_inflight=max_inflight,
            **engine_kwargs,
        )

    points = []
    # harness-level: the default 5 ms GIL switch interval turns every
    # producer<->pipeline thread handoff into a multi-ms convoy on small
    # hosts — measured here as ~5 ms per handoff at sub-ms batch times.
    # Serving processes tune this; so does the harness (set just around
    # the driven runs, restored after).
    old_si = sys.getswitchinterval()
    sys.setswitchinterval(5e-4)
    try:
        for i, rate in enumerate(rates):
            reps = max(top_repeats, 1) if i == len(rates) - 1 else 1
            sync_row = pipe_row = None
            deadline_all_ok = True
            worst_qwait = 0.0
            for _ in range(reps):
                s = run_load_point(
                    make_sync, pairs, rate, pipelined=False,
                    max_wait_ms=max_wait_ms, oracle=oracle, csr=csr,
                )
                p = run_load_point(
                    make_pipe, pairs, rate, pipelined=True,
                    max_wait_ms=max_wait_ms, oracle=oracle, csr=csr,
                )
                d = p.get("deadline", {})
                deadline_all_ok = deadline_all_ok and d.get("ok", True)
                worst_qwait = max(
                    worst_qwait, d.get("queue_wait_max_ms", 0.0)
                )
                if (sync_row is None
                        or (s["sustained_qps"] or 0)
                        > (sync_row["sustained_qps"] or 0)):
                    sync_row = s
                if (pipe_row is None
                        or (p["sustained_qps"] or 0)
                        > (pipe_row["sustained_qps"] or 0)):
                    pipe_row = p
            if "deadline" in pipe_row:
                # an SLO claim may not select away its counterexamples:
                # the kept row is the best-throughput one, but deadline
                # compliance aggregates over EVERY repeat
                pipe_row["deadline"]["ok"] = (
                    pipe_row["deadline"]["ok"] and deadline_all_ok
                )
                pipe_row["deadline"]["queue_wait_max_ms_all_reps"] = round(
                    worst_qwait, 3
                )
            points.append(_load_point_row(rate, sync_row, pipe_row))
    finally:
        sys.setswitchinterval(old_si)
    top = points[-1] if points else None
    return {
        "n": int(n),
        "queries_per_point": len(pairs),
        "max_wait_ms": max_wait_ms,
        "max_queue": max_queue,
        "rates": points,
        # the headline claims, judged at the highest (saturating) rate
        "pipelined_beats_sync": bool(
            top and top["sustained_speedup"] and top["sustained_speedup"] > 1.0
        ),
        "deadline_ok": all(
            p["pipelined"].get("deadline", {}).get("ok", True)
            for p in points
        ),
        "verified_vs_oracle": all(
            p["sync"]["ok"] and p["pipelined"]["ok"] for p in points
        ),
    }


# ---------------------------------------------------------------------
# network front door (bench.py --serve-net)
# ---------------------------------------------------------------------

#: wire grace on top of the engine deadline budget: two framed hops,
#: the server's 50 ms selector tick and the completer's 10 ms poll are
#: all between a net query's scheduled arrival and its reply landing
NET_SLACK_MS = 75.0


def _connect_many(addr, k: int, *, tenant: str | None = None) -> list:
    """``k`` independent framed connections to one front door — each
    gets its own reader thread, so resolution latency never serializes
    behind a single socket's reply stream."""
    from bibfs_tpu.serve.net import NetClient

    clients = []
    try:
        for _ in range(int(k)):
            clients.append(NetClient(addr[0], addr[1], tenant=tenant))
    except Exception:
        for c in clients:
            c.close()
        raise
    return clients


def _close_many(clients) -> None:
    for c in clients:
        try:
            c.close()
        except Exception:
            pass


def _drive_net(clients, pairs, rate_qps, *, graph=None,
               deadline_ms: float | None = None,
               wait_timeout_s: float = 120.0):
    """The socket twin of :func:`_drive_pipelined`: one open-loop
    global schedule, queries striped round-robin across the client
    connections, latency clocked from each query's SCHEDULED arrival
    to the reader thread's resolve stamp (``NetTicket.t_done``).
    Refused submissions (dead connection) become error-shaped entries
    so callers classify rather than crash."""
    C = len(clients)
    t0 = time.perf_counter()
    tickets = []
    for i, (s, d) in enumerate(pairs):
        delay = t0 + i / rate_qps - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        try:
            tickets.append(clients[i % C].submit(
                int(s), int(d), graph, deadline_ms=deadline_ms,
            ))
        except ConnectionError as e:
            tickets.append(_RefusedNet(int(s), int(d), e))
    for t in tickets:
        t.event.wait(timeout=wait_timeout_s)
    elapsed = time.perf_counter() - t0
    lats = [
        t.t_done - (t0 + i / rate_qps)
        for i, t in enumerate(tickets)
        if t.result is not None and t.t_done is not None
    ]
    return tickets, lats, elapsed


class _RefusedNet:
    """A submit the transport refused outright; rides the ticket rows
    so the verify pass classifies it (the run_fleet convention)."""

    def __init__(self, src, dst, err):
        self.src, self.dst = src, dst
        self.result = None
        self.error = err
        self.t_done = None
        self.event = threading.Event()
        self.event.set()


def _verify_net(pairs, tickets, oracle) -> list[str]:
    """Hop-exactness of every RESOLVED net ticket against the serial
    oracle (the wire carries found/hops, never paths)."""
    errors = []
    for (s, d), t in zip(pairs, tickets):
        s, d = int(s), int(d)
        if t.result is None:
            continue  # refusals/timeouts are classified by the caller
        ref = oracle[(s, d)]
        if t.result.found != ref.found or (
            ref.found and t.result.hops != ref.hops
        ):
            errors.append(
                f"{s}->{d}: {t.result.found}/{t.result.hops} != "
                f"oracle {ref.found}/{ref.hops}"
            )
    return errors


def _net_point(rep, pairs, rate, *, connections, max_wait_ms, oracle):
    """One offered-rate point against a live front door: open-loop
    multi-connection drive, hop-verified, with the engine's OWN
    deadline counters (fetched over a control frame) judged against
    the same budget the in-process driver uses plus wire slack."""
    clients = _connect_many(rep.addr, connections)
    try:
        tickets, lats, elapsed = _drive_net(clients, pairs, rate)
    finally:
        _close_many(clients)
    errors = _verify_net(pairs, tickets, oracle)
    unresolved = sum(
        1 for t in tickets if t.result is None and t.error is None
    )
    transport_failed = sum(
        1 for t in tickets
        if t.error is not None and not hasattr(t.error, "kind")
    )
    completed = sum(t.result is not None for t in tickets)
    stats = rep.stats()
    pipe = stats.get("pipeline", {})
    budget_ms = (
        max_wait_ms + pipe.get("batch_service_max_ms", 0.0)
        + SCHED_SLACK_MS + NET_SLACK_MS
    )
    return {
        "offered_qps": round(float(rate), 1),
        "connections": int(connections),
        "completed": completed,
        "unresolved": unresolved,
        "transport_failed": transport_failed,
        "elapsed_s": round(elapsed, 4),
        "sustained_qps": round(completed / elapsed, 1)
        if elapsed > 0 else None,
        "latency_ms": _percentiles_ms(lats),
        "latency_hist": _latency_hist(lats),
        "deadline": {
            "max_wait_ms": max_wait_ms,
            "queue_wait_max_ms": round(
                pipe.get("queue_wait_max_ms", 0.0), 3
            ),
            "batch_service_max_ms": round(
                pipe.get("batch_service_max_ms", 0.0), 3
            ),
            "budget_ms": round(budget_ms, 3),
            "ok": pipe.get("queue_wait_max_ms", 0.0) <= budget_ms,
        },
        "ok": not errors and unresolved == 0 and transport_failed == 0,
        "errors": errors[:10],
    }


def run_net(
    n: int,
    edges,
    *,
    queries: int = 400,
    rates=(100.0, 400.0, 1200.0),
    connections: int = 64,
    max_wait_ms: float = 5.0,
    net_floor: float = 0.8,
    quota_qps: float = 50.0,
    quota_burst: float = 10.0,
    fleet_replicas: int = 2,
    chaos_queries: int = 300,
    chaos_span_s: float = 8.0,
    recovery_bound_s: float = 20.0,
    seed: int = 0,
    workdir: str | None = None,
) -> dict:
    """The network front door soak (``bench.py --serve-net``): the
    in-process pipelined engine and a spawned ``bibfs-serve --port``
    child judged on IDENTICAL open-loop traffic, plus the wire-only
    claims no in-process harness can make. Gates:

    1. **net throughput** — at the saturating rate the front door
       sustains at least ``net_floor`` (default 0.8) of the in-process
       pipelined engine on the same pairs/rates (the protocol tax is
       bounded, not hand-waved);
    2. **deadline SLO end-to-end** — every net point's engine-side
       queue-wait stays within the in-process budget plus wire slack,
       a generous per-request ``deadline_ms`` produces zero timeout
       replies, and an impossible one produces ONLY structured
       ``kind='timeout'`` replies (counted by the server's
       ``bibfs_net_deadline_misses_total``);
    3. **quota admission** — a greedy tenant blowing through its
       token bucket gets structured ``capacity`` refusals naming the
       quota, a polite tenant sharing the same door gets none, and
       every accepted answer stays exact;
    4. **fleet chaos, zero lost acked tickets** — a
       :class:`~bibfs_tpu.fleet.Router` over :class:`NetReplica`
       children takes a mid-stream SIGKILL + respawn: every acked
       ticket resolves or fails STRUCTURED (then reroutes exactly on
       resubmit), the victim re-admits within ``recovery_bound_s``,
       and nothing hangs;
    5. **observability** — the ``bibfs_net_*`` families all render on
       a LIVE ``/metrics`` scrape of the serving child.

    The multi-process pod dryrun is its own phase in ``bench.py``
    (it spawns jax.distributed processes and merges into the same
    artifact). Returns the ``bench_net.json`` payload body."""
    import shutil
    import socket as _socket
    import tempfile
    import urllib.request

    from bibfs_tpu.fleet import NetReplica, Router
    from bibfs_tpu.graph.csr import build_csr, canonical_pairs
    from bibfs_tpu.graph.io import write_graph_bin
    from bibfs_tpu.obs.names import NET_METRIC_FAMILIES
    from bibfs_tpu.serve.pipeline import PipelinedQueryEngine
    from bibfs_tpu.serve.resilience import QueryError
    from bibfs_tpu.solvers.serial import solve_serial_csr

    t_all = time.perf_counter()
    cpairs = canonical_pairs(n, edges)
    csr = build_csr(n, pairs=cpairs)
    pairs = sample_query_pairs(n, int(queries), seed=seed + 1)
    oracle = {
        (int(s), int(d)): solve_serial_csr(n, *csr, int(s), int(d))
        for s, d in {(int(s), int(d)) for s, d in pairs}
    }

    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="bibfs-net-soak-")
    gpath = os.path.join(workdir, "g.bin")
    write_graph_bin(gpath, n, cpairs)

    def free_port() -> int:
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    out: dict = {
        "n": int(n),
        "queries_per_point": len(pairs),
        "connections": int(connections),
        "max_wait_ms": max_wait_ms,
        "net_floor": net_floor,
    }
    old_si = sys.getswitchinterval()
    sys.setswitchinterval(5e-4)
    try:
        # ---- phase 1: in-process pipelined ladder (the baseline) ----
        def make_pipe():
            return PipelinedQueryEngine(
                n, edges, pairs=cpairs, max_wait_ms=max_wait_ms,
            )

        baseline = [
            run_load_point(
                make_pipe, pairs, rate, pipelined=True,
                max_wait_ms=max_wait_ms, oracle=oracle, csr=csr,
            )
            for rate in rates
        ]
        out["inprocess"] = baseline

        # ---- phase 2: the net ladder, fresh child per point ---------
        # (cold caches each point, the run_load_point convention; the
        # LAST child also carries the /metrics endpoint for phase 5
        # and stays up for the deadline phase)
        metrics_port = free_port()
        net_points = []
        rep = None
        deadline_phase: dict = {}
        scrape: dict = {}
        try:
            for i, rate in enumerate(rates):
                last = i == len(rates) - 1
                rep = NetReplica(
                    f"net{i}", gpath, max_wait_ms=max_wait_ms,
                    extra_args=(
                        ["--metrics-port", str(metrics_port)]
                        if last else []
                    ),
                )
                net_points.append(_net_point(
                    rep, pairs, rate, connections=connections,
                    max_wait_ms=max_wait_ms, oracle=oracle,
                ))
                if not last:
                    rep.close()
                    rep = None
            out["net"] = net_points

            # ---- phase 3: per-request deadlines, end to end ---------
            # FRESH pairs per sub-phase: a cache-served query resolves
            # inline and never meets the deadline machinery, so reusing
            # the ladder's (warmed) pairs would test nothing
            dl_n = max(64, len(pairs) // 4)
            dl_pairs = sample_query_pairs(n, dl_n, seed=seed + 11)
            tight_pairs = sample_query_pairs(n, dl_n, seed=seed + 13)
            for s, d in {
                (int(s), int(d))
                for p in (dl_pairs, tight_pairs) for s, d in p
            }:
                if (s, d) not in oracle:
                    oracle[(s, d)] = solve_serial_csr(n, *csr, s, d)
            generous_ms = (
                max_wait_ms
                + net_points[-1]["deadline"]["batch_service_max_ms"]
                + 1000.0
            )
            clients = _connect_many(rep.addr, min(8, connections))
            try:
                tk_g, _, _ = _drive_net(
                    clients, dl_pairs, 200.0, deadline_ms=generous_ms,
                )
                # near-simultaneous arrivals + an already-expired
                # deadline: every queued (non-inline) query must come
                # back as a structured timeout, never a hang
                tk_t, _, _ = _drive_net(
                    clients, tight_pairs, 5000.0, deadline_ms=0.01,
                )
            finally:
                _close_many(clients)
            generous_timeouts = sum(
                1 for t in tk_g
                if getattr(t.error, "kind", None) == "timeout"
            )
            tight_timeouts = sum(
                1 for t in tk_t
                if getattr(t.error, "kind", None) == "timeout"
            )
            tight_unstructured = sum(
                1 for t in tk_t
                if t.result is None
                and getattr(t.error, "kind", None) not in (
                    "timeout", "capacity", "invalid", "internal",
                )
            )
            deadline_phase = {
                "generous_deadline_ms": round(generous_ms, 1),
                "generous_completed": sum(
                    t.result is not None for t in tk_g
                ),
                "generous_timeouts": generous_timeouts,
                "generous_errors": _verify_net(dl_pairs, tk_g, oracle)[:5],
                "tight_deadline_ms": 0.01,
                "tight_timeouts": tight_timeouts,
                "tight_unstructured": tight_unstructured,
                "ok": (
                    generous_timeouts == 0
                    and not _verify_net(dl_pairs, tk_g, oracle)
                    and sum(t.result is not None for t in tk_g)
                    == len(dl_pairs)
                    and tight_timeouts > 0
                    and tight_unstructured == 0
                ),
            }
            out["deadline_phase"] = deadline_phase

            # ---- phase 5 (early: same child): live /metrics scrape --
            with urllib.request.urlopen(
                f"http://127.0.0.1:{metrics_port}/metrics", timeout=10
            ) as resp:
                render = resp.read().decode()
            missing = [m for m in NET_METRIC_FAMILIES
                       if m not in render]
            miss_line = next(
                (ln for ln in render.splitlines()
                 if ln.startswith("bibfs_net_deadline_misses_total")),
                "",
            )
            try:
                misses_scraped = float(miss_line.split()[-1])
            except (IndexError, ValueError):
                misses_scraped = None
            scrape = {
                "live": True,
                "metrics_missing": missing,
                "deadline_misses_scraped": misses_scraped,
                # the tight-deadline phase above MUST show up in the
                # scraped counter — the families are live, not minted
                "ok": not missing and bool(misses_scraped),
            }
            out["metrics"] = scrape
        finally:
            if rep is not None:
                rep.close()

        # ---- phase 4: quota admission, two tenants ------------------
        qrep = NetReplica(
            "quota", gpath, max_wait_ms=max_wait_ms,
            extra_args=[
                "--net-quota-qps", str(quota_qps),
                "--net-quota-burst", str(quota_burst),
            ],
        )
        try:
            greedy = _connect_many(qrep.addr, 4, tenant="greedy")
            polite = _connect_many(qrep.addr, 1, tenant="polite")
            try:
                q_pairs = pairs[: min(200, len(pairs))]
                # 8x the refill rate: the bucket must run dry
                tk_greedy, _, _ = _drive_net(
                    greedy, q_pairs, 8.0 * quota_qps,
                )
                tk_polite, _, _ = _drive_net(
                    polite, pairs[:20], 0.5 * quota_qps,
                )
            finally:
                _close_many(greedy)
                _close_many(polite)
        finally:
            qrep.close()

        def quota_rejects(tickets):
            return [
                t for t in tickets
                if getattr(t.error, "kind", None) == "capacity"
                and "quota" in str(t.error)
            ]

        g_rej = quota_rejects(tk_greedy)
        g_unstructured = sum(
            1 for t in tk_greedy
            if t.result is None and not hasattr(t.error, "kind")
        )
        quota_phase = {
            "quota_qps": quota_qps,
            "quota_burst": quota_burst,
            "greedy_offered": len(q_pairs),
            "greedy_accepted": sum(
                t.result is not None for t in tk_greedy
            ),
            "greedy_quota_rejected": len(g_rej),
            "greedy_unstructured": g_unstructured,
            "polite_rejected": len(quota_rejects(tk_polite)),
            "polite_completed": sum(
                t.result is not None for t in tk_polite
            ),
            "accepted_errors": (
                _verify_net(q_pairs, tk_greedy, oracle)[:5]
                + _verify_net(pairs[:20], tk_polite, oracle)[:5]
            ),
            "ok": (
                len(g_rej) > 0
                and g_unstructured == 0
                and len(quota_rejects(tk_polite)) == 0
                and sum(t.result is not None for t in tk_polite)
                == len(pairs[:20])
                and not _verify_net(q_pairs, tk_greedy, oracle)
                and not _verify_net(pairs[:20], tk_polite, oracle)
            ),
        }
        out["quota_phase"] = quota_phase

        # ---- phase 6: NetReplica fleet chaos ------------------------
        stores = []
        for i in range(int(fleet_replicas)):
            sd = os.path.join(workdir, f"store{i}")
            os.makedirs(sd, exist_ok=True)
            shutil.copy(gpath, os.path.join(sd, "a.bin"))
            stores.append(sd)
        fleet = Router(
            [
                NetReplica(
                    f"f{i}", store_dir=stores[i],
                    max_wait_ms=max_wait_ms,
                )
                for i in range(int(fleet_replicas))
            ],
            poll_interval_s=0.2,
        )
        chaos_rows = []
        resubmitted = []
        recovery_s = None
        try:
            stream = sample_query_pairs(
                n, int(chaos_queries), seed=seed + 5
            )
            for s, d in {(int(s), int(d)) for s, d in stream}:
                if (s, d) not in oracle:
                    oracle[(s, d)] = solve_serial_csr(n, *csr, s, d)
            rate = len(stream) / float(chaos_span_s)
            k_kill = max(1, int(0.2 * len(stream)))
            k_restart = max(k_kill + 1, int(0.5 * len(stream)))
            victim = fleet.replica_names[0]
            t_restart = None
            t0 = time.perf_counter()
            for i, (s, d) in enumerate(stream):
                if i == k_kill:
                    fleet.replica(victim).kill()
                elif i == k_restart:
                    fleet.replica(victim).restart()
                    t_restart = time.monotonic()
                delay = t0 + i / rate - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    chaos_rows.append(
                        (int(s), int(d), fleet.submit(int(s), int(d)))
                    )
                except QueryError as e:
                    chaos_rows.append(
                        (int(s), int(d), _RefusedNet(int(s), int(d), e))
                    )
            fleet.flush(timeout=120.0)
            for _s, _d, t in chaos_rows:
                try:
                    t.wait(timeout=120.0)
                except Exception:
                    pass
            # re-admission: the poller must mark the victim ready again
            if t_restart is not None:
                bound = t_restart + recovery_bound_s
                while time.monotonic() < bound:
                    if fleet.table().get(victim) == "ready":
                        recovery_s = time.monotonic() - t_restart
                        break
                    time.sleep(0.05)
            # every acked ticket resolves or fails STRUCTURED; the
            # failures reroute exactly on resubmit — zero lost
            lost = [
                (s, d) for s, d, t in chaos_rows
                if t.result is None and t.error is None
            ]
            unstructured = [
                (s, d) for s, d, t in chaos_rows
                if t.result is None and t.error is not None
                and not hasattr(t.error, "kind")
            ]
            failed = [
                (s, d) for s, d, t in chaos_rows
                if t.result is None and hasattr(t.error, "kind")
            ]
            for s, d in failed:
                t = fleet.submit(s, d)
                try:
                    t.wait(timeout=60.0)
                except Exception:
                    pass
                resubmitted.append((s, d, t))
            mism = _verify_net(
                [(s, d) for s, d, _ in chaos_rows],
                [t for _, _, t in chaos_rows], oracle,
            ) + _verify_net(
                [(s, d) for s, d, _ in resubmitted],
                [t for _, _, t in resubmitted], oracle,
            )
            resub_unserved = sum(
                1 for _, _, t in resubmitted if t.result is None
            )
            fleet_phase = {
                "replicas": int(fleet_replicas),
                "queries": len(stream),
                "offered_qps": round(rate, 1),
                "killed_at": k_kill,
                "restarted_at": k_restart,
                "failed_structured": len(failed),
                "failed_unstructured": len(unstructured),
                "lost": len(lost),
                "resubmitted": len(resubmitted),
                "resubmit_unserved": resub_unserved,
                "recovery_s": (
                    None if recovery_s is None else round(recovery_s, 2)
                ),
                "mismatches": mism[:10],
                "ok": (
                    not lost and not unstructured and not mism
                    and resub_unserved == 0
                    and recovery_s is not None
                ),
            }
            out["fleet_phase"] = fleet_phase
        finally:
            fleet.close()

        # ---- phase 7: distributed tracing, end to end ---------------
        # sampling forced on; the driver and the spawned serving child
        # spool into one dir, and the merged trace must show at least
        # one query's spans crossing the process boundary (client span
        # here, ingress/stage spans in the child) with valid parentage
        from bibfs_tpu.obs import dtrace as _dtrace

        spool = os.path.join(workdir, "trace_spool")
        os.environ[_dtrace.ENV_SPOOL] = spool
        os.environ[_dtrace.ENV_SAMPLE] = "1.0"
        _dt = _dtrace.install_from_env("loadgen")
        try:
            trep = NetReplica(
                "traced", gpath, max_wait_ms=max_wait_ms,
            )
            try:
                t_pairs = pairs[: min(50, len(pairs))]
                t_tickets = [
                    trep.submit(int(s), int(d)) for s, d in t_pairs
                ]
                for t in t_tickets:
                    try:
                        t.wait(timeout=60.0)
                    except Exception:
                        pass
            finally:
                trep.close()
        finally:
            _dtrace.set_dtracer(None)
            if _dt is not None:
                _dt.close()
            os.environ.pop(_dtrace.ENV_SPOOL, None)
            os.environ.pop(_dtrace.ENV_SAMPLE, None)
        t_report = _dtrace.merge_spools(spool)
        t_cross = _dtrace.cross_process_traces(t_report, min_procs=2)
        trace_phase = {
            "spool_files": t_report["files"],
            "spans": t_report["spans"],
            "truncated_lines": t_report["truncated_lines"],
            "traces": len(t_report["traces"]),
            "cross_process_traces": len(t_cross),
            "orphan_parents": t_report["orphan_parents"],
            "ok": bool(t_cross) and t_report["orphan_parents"] == 0,
        }
        out["trace_phase"] = trace_phase

        # ---- the headline gates -------------------------------------
        top_base = baseline[-1]["sustained_qps"] or 0.0
        top_net = net_points[-1]["sustained_qps"] or 0.0
        ratio = round(top_net / top_base, 3) if top_base else None
        out["net_vs_inprocess"] = {
            "inprocess_qps": top_base,
            "net_qps": top_net,
            "ratio": ratio,
            "floor": net_floor,
        }
        out["elapsed_s"] = round(time.perf_counter() - t_all, 1)
        out["gates"] = {
            "net_throughput_ok": bool(ratio and ratio >= net_floor),
            "verified_vs_oracle": all(
                p["ok"] for p in net_points
            ) and all(p["ok"] for p in baseline),
            "deadline_ladder_ok": all(
                p["deadline"]["ok"] for p in net_points
            ),
            "deadline_e2e_ok": bool(deadline_phase.get("ok")),
            "quota_ok": bool(quota_phase["ok"]),
            "fleet_zero_lost_ok": bool(out["fleet_phase"]["ok"]),
            "metrics_ok": bool(scrape.get("ok")),
            "metrics_missing": scrape.get("metrics_missing"),
            "trace_ok": bool(trace_phase.get("ok")),
        }
        out["ok"] = all(
            v for k, v in out["gates"].items()
            if k.endswith("_ok")
        )
        return out
    finally:
        sys.setswitchinterval(old_si)
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


def run_pod_dryrun(
    *,
    grid=(32, 32),
    local_devices: int = 2,
    queries: int = 48,
    roll_adds: int = 6,
    max_wait_ms: float = 5.0,
    mesh_shard_min_n: int = 64,
    spawn_timeout_s: float = 300.0,
    seed: int = 0,
    workdir: str | None = None,
) -> dict:
    """The multi-process mesh replica dryrun (``bench.py
    --pod-dryrun``, merged into ``bench_net.json`` by the full
    ``--serve-net`` run): a REAL two-process ``jax.distributed`` job on
    the CPU backend — ``bibfs-serve --process-id 0`` builds the store,
    the engine and the network front door; ``--process-id 1`` joins as
    a pod worker — served over the framed TCP protocol and gated exact
    against the NumPy serial oracle, across a mid-traffic hot-swap:

    1. every query answered over the wire matches the serial oracle
       AND was served by the mesh route (``stats.mesh_queries`` — the
       bitpacked dual-frontier exchange crossed process boundaries,
       not a single-host fallback that happens to be right);
    2. a ``roll`` control frame (edge adds that provably change
       answers) hot-swaps the snapshot on BOTH processes mid-traffic —
       post-roll answers match the post-roll oracle, still mesh-served;
    3. SIGTERM on the primary drains the front door and shuts the pod
       down; both processes exit 0 (the worker's shutdown descriptor /
       EOF path, not a crash).

    Skips (``{"skipped": reason}``) where multi-process jax is
    unavailable. Returns the ``pod`` block of ``bench_net.json``."""
    import shutil
    import socket as _socket
    import subprocess
    import tempfile

    from bibfs_tpu.graph.csr import build_csr
    from bibfs_tpu.graph.generate import grid_graph
    from bibfs_tpu.graph.io import write_graph_bin
    from bibfs_tpu.serve.net import NetClient, read_port_file
    from bibfs_tpu.solvers.serial import solve_serial_csr

    try:
        import jax.distributed  # noqa: F401
    except ImportError as e:
        return {"skipped": f"jax.distributed unavailable: {e}"}

    t_all = time.perf_counter()
    w, h = int(grid[0]), int(grid[1])
    n = w * h
    edges = grid_graph(w, h, perforation=0.02, seed=seed)
    und = np.unique(
        np.sort(edges[edges[:, 0] != edges[:, 1]], axis=1), axis=0
    )
    csr1 = build_csr(n, edges)

    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="bibfs-pod-dryrun-")
    store = os.path.join(workdir, "store")
    os.makedirs(store, exist_ok=True)
    write_graph_bin(os.path.join(store, "a.bin"), n, und)
    port_file = os.path.join(workdir, "net.port")
    try:  # a reused workdir must not hand us a stale port
        os.unlink(port_file)
    except OSError:
        pass

    def free_port() -> int:
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    coord = f"127.0.0.1:{free_port()}"
    pod_port = free_port()
    env = dict(os.environ)
    env["PYTHONUNBUFFERED"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={int(local_devices)} "
        + env.get("XLA_FLAGS", "")
    ).strip()
    # distributed tracing across all three processes (this driver, the
    # serving primary, the pod worker), sampling forced on: the merged
    # trace must show one query's spans in >= 3 OS processes
    from bibfs_tpu.obs import dtrace as _dtrace

    spool = os.path.join(workdir, "trace_spool")
    env[_dtrace.ENV_SPOOL] = spool
    env[_dtrace.ENV_SAMPLE] = "1.0"
    _dt = _dtrace.DTracer(spool, "loadgen", sample=1.0)
    _dtrace.set_dtracer(_dt)
    common = [
        "--coordinator", coord, "--num-processes", "2",
        "--pod-port", str(pod_port),
    ]
    argv0 = [
        sys.executable, "-u", "-m", "bibfs_tpu.serve.cli",
        "--store", store, "--pipeline", "--no-path",
        "--max-wait-ms", str(max_wait_ms),
        "--port", "0", "--port-file", port_file,
        "--mesh-shard-min-n", str(int(mesh_shard_min_n)),
        *common, "--process-id", "0",
    ]
    argv1 = [
        sys.executable, "-u", "-m", "bibfs_tpu.serve.cli",
        *common, "--process-id", "1",
    ]
    logs = [os.path.join(workdir, f"proc{i}.log") for i in (0, 1)]
    handles = [open(p, "w") for p in logs]
    procs = [
        subprocess.Popen(
            argv, stdin=subprocess.DEVNULL, stdout=handle,
            stderr=subprocess.STDOUT, env=env,
        )
        for argv, handle in zip((argv0, argv1), handles)
    ]

    def tails() -> dict:
        out = {}
        for i, p in enumerate(logs):
            try:
                with open(p) as f:
                    out[f"proc{i}"] = f.read()[-2000:]
            except OSError:
                pass
        return out

    def reap(sig_primary: bool) -> list:
        if sig_primary and procs[0].poll() is None:
            procs[0].terminate()
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(timeout=60.0))
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass
                rcs.append(None)
        return rcs

    client = None
    try:
        deadline = time.monotonic() + float(spawn_timeout_s)
        addr = None
        while addr is None:
            for i, p in enumerate(procs):
                if p.poll() is not None:
                    reap(sig_primary=False)
                    return {
                        "skipped": (
                            f"pod process {i} exited rc="
                            f"{p.returncode} before serving"
                        ),
                        "logs": tails(),
                    }
            if time.monotonic() >= deadline:
                reap(sig_primary=True)
                return {
                    "skipped": (
                        f"pod did not serve within {spawn_timeout_s}s"
                    ),
                    "logs": tails(),
                }
            addr = read_port_file(port_file)
            if addr is None:
                time.sleep(0.2)

        client = NetClient(addr[0], addr[1], connect_timeout=60.0)
        pairs = sample_query_pairs(n, int(queries), seed=seed + 1)

        def drive(ps, csr) -> tuple:
            tickets = [
                client.submit(int(s), int(d)) for s, d in ps
            ]
            bad = []
            for (s, d), t in zip(ps, tickets):
                try:
                    res = t.wait(timeout=120.0)
                except Exception as e:
                    bad.append(f"{s}->{d}: {type(e).__name__}: {e}")
                    continue
                ref = solve_serial_csr(n, *csr, int(s), int(d))
                if res.found != ref.found or (
                    ref.found and res.hops != ref.hops
                ):
                    bad.append(
                        f"{s}->{d}: {res.found}/{res.hops} != "
                        f"serial {ref.found}/{ref.hops}"
                    )
            return tickets, bad

        def mesh_count() -> int:
            return int(client.request("stats").get("mesh_queries", 0))

        _tk1, bad1 = drive(pairs, csr1)
        mesh1 = mesh_count()
        v1 = client.request("version").get("version")

        # the hot-swap: long-range shortcuts that provably change hops
        live = set(map(tuple, und.tolist()))
        adds = []
        for i in range(n):
            if len(adds) >= int(roll_adds):
                break
            u, v = i, n - 1 - i
            e = (u, v) if u < v else (v, u)
            if u != v and e not in live and e not in adds:
                adds.append(e)
        rolled = client.request(
            "roll", timeout=180.0,
            adds=[[int(u), int(v)] for u, v in adds],
        )
        live2 = sorted(live | set(adds))
        csr2 = build_csr(n, np.array(live2, dtype=np.int64))
        changed = sum(
            1 for s, d in pairs
            if (solve_serial_csr(n, *csr1, int(s), int(d)).hops
                != solve_serial_csr(n, *csr2, int(s), int(d)).hops)
        )
        _tk2, bad2 = drive(pairs, csr2)
        mesh2 = mesh_count()

        client.close()
        client = None
        rcs = reap(sig_primary=True)
        # both children have exited (spools closed); merge and gate:
        # at least one sampled query's spans in driver + primary +
        # worker, with every parent resolving
        _dtrace.set_dtracer(None)
        _dt.close()
        t_report = _dtrace.merge_spools(spool)
        t_cross = _dtrace.cross_process_traces(t_report, min_procs=3)
        trace_block = {
            "spool_files": t_report["files"],
            "spans": t_report["spans"],
            "truncated_lines": t_report["truncated_lines"],
            "traces": len(t_report["traces"]),
            "cross_process_traces_3": len(t_cross),
            "orphan_parents": t_report["orphan_parents"],
            "procs": sorted({
                p for t in t_report["traces"] for p in t["procs"]
            }),
        }
        out = {
            "n": n,
            "processes": 2,
            "local_devices_per_process": int(local_devices),
            "queries_per_pass": len(pairs),
            "mesh_queries_pre_roll": mesh1,
            "mesh_queries_post_roll": mesh2,
            "version_pre_roll": v1,
            "version_post_roll": rolled.get("version"),
            "answers_changed_by_roll": changed,
            "mismatches": (bad1 + bad2)[:10],
            "exit_codes": rcs,
            "elapsed_s": round(time.perf_counter() - t_all, 1),
            "exact_ok": not bad1 and not bad2,
            "mesh_used_ok": mesh1 > 0 and mesh2 > mesh1,
            "swap_ok": (
                rolled.get("version") == (v1 or 1) + 1 and changed > 0
                and not bad2
            ),
            "clean_exit_ok": rcs == [0, 0],
            "trace": trace_block,
            "trace_ok": (
                bool(t_cross) and t_report["orphan_parents"] == 0
            ),
        }
        out["ok"] = (
            out["exact_ok"] and out["mesh_used_ok"]
            and out["swap_ok"] and out["clean_exit_ok"]
            and out["trace_ok"]
        )
        if not out["ok"]:
            out["logs"] = tails()
        # the merged Chrome-trace events ride OUTSIDE the bench payload
        # body: bench.py pops them and writes visual/pod_trace.json
        out["trace_events"] = t_report["events"]
        return out
    except Exception as e:
        reap(sig_primary=True)
        return {
            "ok": False,
            "error": f"{type(e).__name__}: {e}"[:300],
            "logs": tails(),
        }
    finally:
        _dtrace.set_dtracer(None)
        _dt.close()
        if client is not None:
            client.close()
        for handle in handles:
            try:
                handle.close()
            except OSError:
                pass
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)

class _ScriptedPodWorker:
    """A pod worker's control half scripted for the elastic soak: real
    sockets, real length-prefixed frames, real heartbeats — no solver
    behind it, so a fault can be injected at an exact protocol point.
    ``silent.set()`` turns it into the zombie incarnation (keeps
    reading so the primary's sends never block, but stops acking AND
    heartbeating — the GC-pause/partition shape); :meth:`ack` doubles
    as the zombie's late-ack injector, since every send stamps the
    worker's OWN epoch and the primary's reader fence judges it."""

    def __init__(self, host: str, port: int, *, process: int = 1,
                 epoch: int = 0, hb_s: float = 0.15):
        import socket as _socket

        from bibfs_tpu.serve.net import encode_frame

        self._encode = encode_frame
        self.process = int(process)
        self.epoch = int(epoch)
        self.graphs = 0          # graph descriptors fully received
        self.joined: list = []   # solve seqs join-acked
        self.served: list = []   # solve seqs committed + done-acked
        self.silent = threading.Event()
        self._stop = threading.Event()
        self._wlock = threading.Lock()
        self.sock = _socket.create_connection((host, int(port)),
                                              timeout=10.0)
        self.sock.setsockopt(_socket.IPPROTO_TCP,
                             _socket.TCP_NODELAY, 1)
        self._send({"op": "hello", "process": self.process,
                    "epoch": self.epoch})
        self._hb_s = float(hb_s)
        threading.Thread(
            target=self._hb_main, daemon=True,
            name=f"elastic-pod-hb-e{self.epoch}",
        ).start()
        threading.Thread(
            target=self._main, daemon=True,
            name=f"elastic-pod-w-e{self.epoch}",
        ).start()

    def _send(self, obj: dict) -> None:
        try:
            with self._wlock:
                self.sock.sendall(self._encode(dict(obj)))
        except (OSError, ValueError):
            pass

    def _hb_main(self) -> None:
        # first beat IMMEDIATELY: the primary only judges workers that
        # have ever heartbeat, so a worker that dies before its first
        # interval elapses would otherwise be invisible to the sweep
        while True:
            if not self.silent.is_set():
                self._send({"op": "hb", "process": self.process,
                            "epoch": self.epoch})
            if self._stop.wait(self._hb_s):
                return

    def ack(self, seq: int, phase: str, ok: bool = True,
            **extra) -> None:
        self._send(dict(extra, seq=int(seq), phase=phase,
                        ok=bool(ok), epoch=self.epoch))

    def _main(self) -> None:
        from bibfs_tpu.parallel.podmesh import _recv_frames

        buf = bytearray()
        g_seq, g_left, g_digest = -1, 0, ""
        try:
            while not self._stop.is_set():
                for msg in _recv_frames(self.sock, buf):
                    if self.silent.is_set():
                        continue  # the zombie reads but never answers
                    op = msg.get("op")
                    seq = int(msg.get("seq", -1))
                    if op == "graph":
                        g_seq = seq
                        g_left = int(msg.get("chunks", 0))
                        g_digest = str(msg.get("digest"))
                        if g_left == 0:
                            self.graphs += 1
                            self.ack(g_seq, "done", digest=g_digest)
                    elif op == "graph_chunk":
                        g_left -= 1
                        if g_left == 0:
                            self.graphs += 1
                            self.ack(g_seq, "done", digest=g_digest)
                    elif op == "solve":
                        self.joined.append(seq)
                        self.ack(seq, "join")
                    elif op == "go":
                        fseq = int(msg.get("for", -1))
                        self.served.append(fseq)
                        self.ack(fseq, "done")
                    elif op == "shutdown":
                        self.ack(seq, "done")
                        return
                    # "abort": parked batch skipped, nothing to ack
        except (ConnectionError, OSError, ValueError):
            pass

    def close(self) -> None:
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass


class _IdleRouter:
    """The pod-heal leg's router stub: the supervisor's pod watching
    is router-independent, so it supervises an empty fleet."""

    replica_names = ()
    obs_label = "podheal"

    def table(self) -> dict:
        return {}

    def catchup_stuck(self) -> dict:
        return {}

    def replica(self, name):
        raise KeyError(name)


def run_elastic(
    n: int,
    edges,
    *,
    base_qps: float = 50.0,
    ramp_mult: float = 10.0,
    warm_span_s: float = 3.0,
    ramp_span_s: float = 6.0,
    trail_span_s: float = 30.0,
    max_wait_ms: float = 25.0,
    max_batch: int = 4,
    start_replicas: int = 1,
    max_replicas: int = 3,
    queue_hi: int = 32,
    queue_lo: int = 2,
    cooldown_s: float = 2.5,
    p99_bound_ms: float = 30000.0,
    hb_timeout_s: float = 0.6,
    seed: int = 0,
    workdir: str | None = None,
) -> dict:
    """The self-healing elastic fleet soak (``bench.py
    --serve-elastic``): three failure legs, one artifact
    (``bench_elastic.json``).

    1. **Elastic fleet.** A :class:`~bibfs_tpu.fleet.Supervisor` over a
       :class:`~bibfs_tpu.fleet.Router` of deliberately THROTTLED
       ``bibfs-serve`` children: each child's front door enforces a
       ``--net-quota-qps`` token bucket (batch shaping alone cannot
       create overload — ``max_wait_ms`` is a MAX, and full batches
       flush back-to-back), so the ramp overloads deterministically on
       any machine and a second replica genuinely doubles fleet
       capacity. The supervisor's shed signal is the observed rate of
       structured capacity refusals. Open-loop traffic ramps
       ``ramp_mult``x over base while one ORIGINAL replica takes a
       SIGKILL mid-ramp; a closed-loop probe stream clocks end-to-end
       latency through every scale event. Gates: zero lost acked
       tickets, every survivor exact vs the serial oracle, probe p99
       bounded, scale-OUT and scale-IN both witnessed, the dead replica
       respawned and re-admitted, and zero flapping (no out/in pair
       closer than the cooldown window).
    2. **Pod-worker failure domains.** An in-process
       :class:`~bibfs_tpu.parallel.podmesh.PodPrimary` over a scripted
       worker speaking the real frame protocol: a served batch at epoch
       0, then the worker goes zombie mid-batch — the join barrier
       aborts pre-collective (degrade to the local ladder, never a
       hang), the supervisor's heartbeat sweep respawns the worker at
       epoch 1 via ``accept_rejoin``, the next launch re-broadcasts the
       graph, a batch serves at the new epoch, and the zombie's late
       ack is FENCED (counted, never re-marking the healthy worker).
    3. **Overload brownout.** An in-process
       :class:`~bibfs_tpu.serve.net.NetServer` with
       :class:`~bibfs_tpu.serve.net.BrownoutPolicy`: an infeasible
       deadline is shed with a structured ``capacity`` reply carrying
       ``retry_after_ms``, queue pressure sheds the expensive ladder
       kinds while POINT lookups keep serving, and the rungs release
       with hysteresis once pressure clears.

    Cross-cutting: every ``ELASTIC_METRIC_FAMILIES`` family renders,
    and the trail window shows zero compile-sentinel events
    (``exec_cache`` miss deltas on same-generation replicas).
    Returns the ``bench_elastic.json`` payload body."""
    import shutil
    import tempfile

    from bibfs_tpu.fleet import (
        NetReplica,
        Router,
        ScalePolicy,
        Supervisor,
    )
    from bibfs_tpu.graph.csr import build_csr, canonical_pairs
    from bibfs_tpu.graph.io import write_graph_bin
    from bibfs_tpu.obs.metrics import REGISTRY
    from bibfs_tpu.obs.names import ELASTIC_METRIC_FAMILIES
    from bibfs_tpu.parallel.podmesh import PodError, PodPrimary
    from bibfs_tpu.serve.net import BrownoutPolicy, NetClient, NetServer
    from bibfs_tpu.serve.pipeline import PipelinedQueryEngine
    from bibfs_tpu.serve.resilience import QueryError
    from bibfs_tpu.solvers.serial import solve_serial_csr

    t_all = time.perf_counter()
    cpairs = canonical_pairs(n, edges)
    csr = build_csr(n, pairs=cpairs)

    # DISTINCT pairs throughout: a repeated pair is served from the
    # result cache inline and never loads the queue, which would melt
    # the overload the autoscaler must see
    warm_q = max(16, int(base_qps * warm_span_s))
    ramp_q = max(64, int(base_qps * ramp_mult * ramp_span_s))
    warm_pairs = sample_query_pairs(n, warm_q, seed=seed + 1)
    ramp_pairs = sample_query_pairs(n, ramp_q, seed=seed + 2)
    probe_pool = sample_query_pairs(n, 1024, seed=seed + 3)
    oracle = {}
    for pool in (warm_pairs, ramp_pairs, probe_pool):
        for s, d in {(int(s), int(d)) for s, d in pool}:
            if (s, d) not in oracle:
                oracle[(s, d)] = solve_serial_csr(n, *csr, s, d)

    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="bibfs-elastic-")
    gpath = os.path.join(workdir, "g.bin")
    write_graph_bin(gpath, n, cpairs)

    def make_store(tag: str) -> str:
        sd = os.path.join(workdir, tag)
        os.makedirs(sd, exist_ok=True)
        shutil.copy(gpath, os.path.join(sd, "a.bin"))
        return sd

    # per-replica capacity is enforced by the front door's token
    # bucket, NOT by batching knobs: max_wait_ms is a MAX (full
    # batches flush back-to-back), so batch shaping alone cannot
    # create overload on a fast machine — the quota can, on any
    quota_qps = 4.0 * base_qps
    quota_burst = 2.0 * base_qps

    def throttled(name: str, tag: str) -> NetReplica:
        return NetReplica(
            name, store_dir=make_store(tag), max_wait_ms=max_wait_ms,
            extra_args=[
                "--max-batch", str(int(max_batch)),
                "--net-quota-qps", str(quota_qps),
                "--net-quota-burst", str(quota_burst),
            ],
        )

    out: dict = {
        "n": int(n),
        "base_qps": float(base_qps),
        "ramp_qps": float(base_qps * ramp_mult),
        "throttle": {"max_batch": int(max_batch),
                     "max_wait_ms": float(max_wait_ms),
                     "quota_qps": float(quota_qps),
                     "quota_burst": float(quota_burst)},
        "policy": {"queue_hi": int(queue_hi), "queue_lo": int(queue_lo),
                   "shed_hi": float(base_qps),
                   "cooldown_s": float(cooldown_s),
                   "max_replicas": int(max_replicas)},
    }
    old_si = sys.getswitchinterval()
    sys.setswitchinterval(5e-4)
    try:
        # ================ leg 1: the elastic fleet ===================
        fleet = Router(
            [throttled(f"e{i}", f"store-e{i}")
             for i in range(int(start_replicas))],
            poll_interval_s=0.2,
        )
        policy = ScalePolicy(
            min_replicas=int(start_replicas),
            max_replicas=int(max_replicas),
            queue_hi=int(queue_hi), queue_lo=int(queue_lo),
            shed_hi=float(base_qps),
            settle_ticks=2, cooldown_s=float(cooldown_s),
            respawn_backoff_s=1.0, stuck_after_s=30.0,
            warm_timeout_s=120.0,
        )
        # the shed signal: structured capacity refusals per second as
        # observed at the load generator (the same events the replicas
        # count in bibfs_admission_shed_total) — over-quota pressure is
        # what scale-out must relieve, and a second replica genuinely
        # doubles the fleet's token budget
        refusals: deque = deque()
        refusals_lock = threading.Lock()
        refused_total = [0]

        def note_refusal() -> None:
            with refusals_lock:
                refusals.append(time.monotonic())
                refused_total[0] += 1

        def elastic_signals() -> dict:
            now = time.monotonic()
            with refusals_lock:
                while refusals and refusals[0] < now - 1.0:
                    refusals.popleft()
                shed = float(len(refusals))
            depth = 0
            for nm in fleet.replica_names:
                try:
                    ld = int(fleet.replica(nm).load())
                except Exception:
                    continue
                if ld < (1 << 29):  # dead replicas read saturated
                    depth = max(depth, ld)
            return {"queue_depth": depth, "p99_ms": None,
                    "shed_rate": shed}

        sup = Supervisor(
            fleet, lambda idx: throttled(f"es{idx}", f"store-es{idx}"),
            policy=policy, poll_interval_s=0.2,
            signals=elastic_signals,
        )
        rows: list = []
        probe_rows: list = []
        probe_stop = threading.Event()

        def drive(pairs_seg, rate: float, kill_at=None,
                  victim=None) -> None:
            t0 = time.perf_counter()
            for i, (s, d) in enumerate(pairs_seg):
                if kill_at is not None and i == kill_at:
                    fleet.replica(victim).kill()
                delay = t0 + i / rate - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    rows.append(
                        (int(s), int(d), fleet.submit(int(s), int(d)))
                    )
                except QueryError as e:
                    note_refusal()
                    rows.append((int(s), int(d),
                                 _RefusedNet(int(s), int(d), e)))

        def probe_main() -> None:
            i = 0
            while not probe_stop.is_set():
                s, d = probe_pool[i % len(probe_pool)]
                i += 1
                t0p = time.perf_counter()
                try:
                    t = fleet.submit(int(s), int(d))
                except QueryError as e:
                    note_refusal()
                    probe_rows.append((int(s), int(d),
                                       _RefusedNet(int(s), int(d), e),
                                       None))
                    probe_stop.wait(0.12)
                    continue
                try:
                    t.wait(timeout=90.0)
                except Exception:
                    pass
                probe_rows.append((int(s), int(d), t,
                                   time.perf_counter() - t0p))
                probe_stop.wait(0.12)

        victim = fleet.replica_names[0]
        elastic: dict = {}
        try:
            prober = threading.Thread(
                target=probe_main, name="bibfs-elastic-probe",
                daemon=True,
            )
            prober.start()
            drive(warm_pairs, base_qps)
            drive(ramp_pairs, base_qps * ramp_mult,
                  kill_at=int(0.35 * len(ramp_pairs)), victim=victim)
            # drain the ramp backlog before the quiet trail
            fleet.flush(timeout=180.0)
            for _s, _d, t in rows:
                try:
                    t.wait(timeout=120.0)
                except Exception:
                    pass
            # compile-sentinel window opens here: the fleet is warmed
            # and every shape it will see again is cached
            def cache_sample() -> dict:
                sample = {}
                for name in fleet.replica_names:
                    try:
                        rep = fleet.replica(name)
                        misses = rep.stats().get(
                            "exec_cache", {}).get("misses")
                        if misses is not None:
                            sample[name] = (rep.generation, int(misses))
                    except Exception:
                        continue
                return sample

            before = cache_sample()
            # the quiet trail: probes only — fleet-max queue depth
            # sits at ~0 <= queue_lo, which is what provokes scale-in
            trail_end = time.monotonic() + float(trail_span_s)
            while time.monotonic() < trail_end:
                if (any(e["dir"] == "in" for e in sup.events())
                        and len(fleet.replica_names)
                        <= int(start_replicas)):
                    break
                time.sleep(0.2)
            after = cache_sample()
            compile_events = sum(
                after[k][1] - v[1] for k, v in before.items()
                if k in after and after[k][0] == v[0]
            )
            probe_stop.set()
            prober.join(timeout=120.0)

            # classification: the run_net convention — lost (acked,
            # vanished) / unstructured / failed-structured (resubmit)
            all_rows = rows + [(s, d, t) for s, d, t, _ in probe_rows]
            lost = [(s, d) for s, d, t in all_rows
                    if t.result is None and t.error is None]
            unstructured = [
                (s, d) for s, d, t in all_rows
                if t.result is None and t.error is not None
                and not hasattr(t.error, "kind")
            ]
            failed = [(s, d) for s, d, t in all_rows
                      if t.result is None and hasattr(t.error, "kind")]
            # resubmission honors the capacity reply's retry_after_ms
            # hint: a blind loop would outrun the very token bucket
            # that refused these queries in the first place
            resubmitted = []
            for s, d in failed:
                end = time.monotonic() + 30.0
                while True:
                    try:
                        t = fleet.submit(s, d)
                        break
                    except QueryError as e:
                        if time.monotonic() >= end:
                            t = _RefusedNet(s, d, e)
                            break
                        hint = getattr(e, "retry_after_ms", None)
                        time.sleep(min(0.25, (hint or 50.0) / 1e3))
                try:
                    t.wait(timeout=60.0)
                except Exception:
                    pass
                resubmitted.append((s, d, t))
            mism = _verify_net(
                [(s, d) for s, d, _ in all_rows],
                [t for _, _, t in all_rows], oracle,
            ) + _verify_net(
                [(s, d) for s, d, _ in resubmitted],
                [t for _, _, t in resubmitted], oracle,
            )
            resub_unserved = sum(
                1 for _, _, t in resubmitted if t.result is None
            )
            lats = sorted(
                lat for _, _, t, lat in probe_rows
                if lat is not None and t.result is not None
            )
            p99_ms = (
                round(lats[int(0.99 * (len(lats) - 1))] * 1e3, 1)
                if lats else None
            )
            events = sup.events()
            scale_dirs = [e for e in events if e["dir"] in ("out", "in")]
            flaps = [
                (a, b) for a, b in zip(scale_dirs, scale_dirs[1:])
                if a["dir"] != b["dir"]
                and b["t"] - a["t"] < 0.9 * float(cooldown_s)
            ]
            elastic = {
                "queries": len(rows),
                "probes": len(probe_rows),
                "killed": victim,
                "events": events,
                "replicas_final": list(fleet.replica_names),
                "lost": len(lost),
                "refused_total": refused_total[0],
                "failed_unstructured": len(unstructured),
                "failed_structured": len(failed),
                "resubmit_unserved": resub_unserved,
                "mismatches": mism[:10],
                "probe_p99_ms": p99_ms,
                "flaps": len(flaps),
                "compile_events_trail": compile_events,
                "spawn_failures": sup.stats()["spawn_failures"],
                "scaled_out": any(e["dir"] == "out" for e in events),
                "scaled_in": any(e["dir"] == "in" for e in events),
                "respawned_dead": any(
                    e["dir"] == "respawn" and e["reason"] == "dead"
                    for e in events
                ),
                "victim_state": fleet.table().get(victim),
            }
        finally:
            probe_stop.set()
            sup.close()
            fleet.close()

        # ============ leg 2: pod-worker failure domains ==============
        class _SnapLite:
            n = 8
            pairs = np.array(
                [[i, i + 1] for i in range(7)], dtype=np.int64
            )
            digest = "elastic-pod-snap"
            version = 1

        snap = _SnapLite()
        pad = np.array([[0, 7], [2, 5]], dtype=np.int64)
        primary = PodPrimary(
            1, host="127.0.0.1", heartbeat_timeout_s=float(hb_timeout_s)
        )
        workers: dict = {}
        psup = None
        pod: dict = {}
        try:
            workers[0] = _ScriptedPodWorker(
                "127.0.0.1", primary.port, epoch=0
            )
            primary.accept_workers()
            primary.ensure_graph(snap, timeout=15.0)
            graphs_epoch0 = workers[0].graphs

            def pod_batch() -> bool:
                primary.check_heartbeats()     # the route's sweep
                primary.ensure_graph(snap, timeout=15.0)
                seq = primary.post_solve(snap.digest, "sync", pad, 2)
                primary.await_phase(seq, "join", timeout=10.0)
                primary.commit_solve(seq)
                primary.await_phase(seq, "done", timeout=10.0)
                return True

            served_epoch0 = pod_batch()
            # let a few heartbeats land first: the sweep only judges
            # workers it has HEARD from, so a worker that goes zombie
            # before its first beat would never be marked dead
            time.sleep(3.0 * 0.15)
            # the zombie: mid-stream the worker stops acking AND
            # heartbeating; the next batch must abort via the
            # two-phase join barrier, never hang in a collective
            workers[0].silent.set()
            seq_b = primary.post_solve(snap.digest, "sync", pad, 2)
            degraded = False
            try:
                primary.await_phase(seq_b, "join", timeout=1.5)
                primary.commit_solve(seq_b)
            except PodError:
                primary.abort_solve(seq_b)
                degraded = True  # -> the engine's local ladder
            # supervisor-driven heal: heartbeat sweep marks the worker
            # dead, the respawn callback rejoins at a HIGHER epoch
            psup = Supervisor(
                _IdleRouter(), lambda idx: None,
                policy=ScalePolicy(respawn_backoff_s=1.0),
                poll_interval_s=0.1,
            )

            def pod_respawn(p, pidx):
                workers[1] = _ScriptedPodWorker(
                    "127.0.0.1", p.port,
                    epoch=p.worker_epoch(pidx) + 1,
                )
                p.accept_rejoin(timeout_s=10.0)

            psup.watch_pod(primary, pod_respawn)
            heal_end = time.monotonic() + 20.0
            while time.monotonic() < heal_end:
                if (not primary.dead_workers()
                        and primary.worker_epoch(1) >= 1):
                    break
                time.sleep(0.05)
            healed = (not primary.dead_workers()
                      and primary.worker_epoch(1) >= 1)
            # recovery: the next launch re-broadcasts the graph (the
            # respawned incarnation holds none) and serves at epoch 1
            served_epoch1 = False
            regraphed = False
            if healed:
                served_epoch1 = pod_batch()
                regraphed = workers[1].graphs >= 1
            # the zombie wakes and fires its late ack for the aborted
            # batch: the reader fence drops and counts it
            workers[0].silent.clear()
            workers[0].ack(seq_b, "join")
            fence_end = time.monotonic() + 5.0
            while time.monotonic() < fence_end:
                if primary.fenced_frames >= 1:
                    break
                time.sleep(0.05)
            fenced = int(primary.fenced_frames)
            # the zombie's EOF must retire its reader SILENTLY — the
            # recovered incarnation is never re-marked dead
            workers[0].close()
            time.sleep(0.4)
            zombie_eof_silent = not primary.dead_workers()
            pod = {
                "graphs_epoch0": graphs_epoch0,
                "served_epoch0": served_epoch0,
                "degraded_to_local": degraded,
                "healed": healed,
                "regraphed": regraphed,
                "served_epoch1": served_epoch1,
                "worker_epoch": primary.worker_epoch(1),
                "fenced_frames": fenced,
                "zombie_eof_silent": zombie_eof_silent,
                "heal_events": [
                    e for e in (psup.events() if psup else [])
                    if e["reason"] == "pod_worker"
                ],
            }
        finally:
            if psup is not None:
                psup.close()
            try:
                primary.shutdown(timeout=5.0)
            except Exception:
                primary.close()
            for w in workers.values():
                w.close()

        # ================= leg 3: overload brownout ==================
        beng = PipelinedQueryEngine(n, edges, pairs=cpairs,
                                    max_wait_ms=150.0)
        bsrv = NetServer(
            beng, port=0, max_inflight=16,
            brownout=BrownoutPolicy(min_samples=16),
        )
        brown: dict = {}
        try:
            bcli = NetClient("127.0.0.1", bsrv.port)
            try:
                bw_pairs = sample_query_pairs(n, 24, seed=seed + 7)
                for s, d in bw_pairs:  # warm past min_samples
                    bcli.submit(int(s), int(d)).wait(timeout=30.0)
                fresh = sample_query_pairs(n, 24, seed=seed + 8)
                fi = iter([(int(s), int(d)) for s, d in fresh])

                def shed_kind(err) -> str | None:
                    if getattr(err, "kind", None) != "capacity":
                        return None
                    return (str(err), getattr(err, "retry_after_ms",
                                              None))

                # rung 1: a deadline no p99 can meet -> structured
                # capacity reply with a retry_after_ms backoff hint
                s, d = next(fi)
                infeasible = None
                try:
                    bcli.submit(s, d, deadline_ms=0.001).wait(
                        timeout=30.0)
                except QueryError as e:
                    infeasible = shed_kind(e)
                # rung 2: queue pressure -> the ladder sheds expensive
                # kinds while a point lookup keeps serving
                burst = [bcli.submit(*next(fi)) for _ in range(14)]
                ladder_shed = None
                try:
                    bcli.submit(*next(fi), kind="kshortest").wait(
                        timeout=30.0)
                except QueryError as e:
                    ladder_shed = shed_kind(e)
                point = bcli.submit(*next(fi))
                point.wait(timeout=30.0)
                point_served = point.result is not None
                for t in burst:
                    t.wait(timeout=30.0)
                # hysteresis release: pressure gone, the rung re-admits
                release = bcli.submit(*next(fi), kind="kshortest")
                release.wait(timeout=30.0)
                released = release.result is not None
                brown = {
                    "warmed": len(bw_pairs),
                    "infeasible_shed": infeasible,
                    "ladder_shed": ladder_shed,
                    "point_served": point_served,
                    "released": released,
                }
            finally:
                bcli.close()
        finally:
            bsrv.close()
            beng.close()

        # ================= the cross-cutting gates ===================
        render = REGISTRY.render()
        missing = [m for m in ELASTIC_METRIC_FAMILIES
                   if m not in render]
        out["elastic_phase"] = elastic
        out["pod_phase"] = pod
        out["brownout_phase"] = brown
        out["elapsed_s"] = round(time.perf_counter() - t_all, 1)
        brown_ok = bool(
            brown.get("infeasible_shed")
            and "infeasible" in brown["infeasible_shed"][0]
            and brown["infeasible_shed"][1] is not None
            and brown.get("ladder_shed")
            and "kshortest" in brown["ladder_shed"][0]
            and brown.get("point_served")
            and brown.get("released")
        )
        out["gates"] = {
            "zero_lost_ok": elastic.get("lost") == 0
            and elastic.get("failed_unstructured") == 0
            and elastic.get("resubmit_unserved") == 0,
            "exact_ok": elastic.get("mismatches") == [],
            "p99_bounded_ok": (
                elastic.get("probe_p99_ms") is not None
                and elastic["probe_p99_ms"] <= float(p99_bound_ms)
            ),
            "scale_out_ok": bool(elastic.get("scaled_out")),
            "scale_in_ok": bool(elastic.get("scaled_in")),
            "respawn_ok": bool(elastic.get("respawned_dead"))
            and elastic.get("victim_state") == "ready",
            "no_flap_ok": elastic.get("flaps") == 0,
            "compile_sentinel_ok":
                elastic.get("compile_events_trail") == 0,
            "pod_degrade_ok": bool(pod.get("served_epoch0"))
            and bool(pod.get("degraded_to_local")),
            "pod_recover_ok": bool(pod.get("healed"))
            and bool(pod.get("regraphed"))
            and bool(pod.get("served_epoch1"))
            and pod.get("worker_epoch", 0) >= 1,
            "pod_fence_ok": pod.get("fenced_frames", 0) >= 1
            and bool(pod.get("zombie_eof_silent")),
            "brownout_ok": brown_ok,
            "metrics_ok": not missing,
            "metrics_missing": missing,
        }
        out["ok"] = all(
            v for k, v in out["gates"].items() if k.endswith("_ok")
        )
        return out
    finally:
        sys.setswitchinterval(old_si)
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


def _analytics_refs(n, edges, seed):
    """Independent references for one graph: full Dijkstra distances
    are computed lazily per source by the caller; this precomputes the
    shared CSR + weights and the three whole-graph answers."""
    from bibfs_tpu.analytics.semiring import (
        ref_components_unionfind,
        ref_pagerank_dense,
        ref_triangles_intersect,
    )
    from bibfs_tpu.graph.csr import build_csr
    from bibfs_tpu.query.weighted import synthetic_weights

    csr = build_csr(n, edges)
    return {
        "csr": csr,
        "weights": synthetic_weights(*csr, seed),
        "pagerank": ref_pagerank_dense(n, *csr),
        "components": ref_components_unionfind(n, edges),
        "triangles": ref_triangles_intersect(n, *csr),
    }


def _check_analytics(tag, n, refs, queries, results, failures, *,
                     pr_tol=2e-4):
    """Verify one (query, result) stream against the independent
    references: SSSP exact vs binary-heap Dijkstra, PageRank within
    ``pr_tol`` of the dense power iteration (the blocked rung's f32
    planes round at ~1e-6), components/triangles exact."""
    from bibfs_tpu.query.weighted import dijkstra_numpy
    from bibfs_tpu.serve.resilience import QueryError

    before = len(failures)
    for q, res in zip(queries, results):
        if isinstance(res, QueryError):
            failures.append(f"{tag} {q.kind}: {res}")
            continue
        if q.kind == "sssp":
            ref, _par = dijkstra_numpy(
                n, *refs["csr"], refs["weights"], int(q.source)
            )
            if res.dist.shape != (n,) or not np.allclose(
                res.dist, ref, atol=1e-9, equal_nan=False
            ):
                bad = int(np.sum(~np.isclose(res.dist, ref, atol=1e-9)))
                failures.append(
                    f"{tag} sssp src={q.source}: {bad} wrong distances"
                )
            if res.reached != int(np.isfinite(ref).sum()):
                failures.append(f"{tag} sssp src={q.source}: reached")
        elif q.kind == "pagerank":
            ref = refs["pagerank"]
            err = float(np.max(np.abs(res.ranks - ref))) if n else 0.0
            if res.ranks.shape != (n,) or err > pr_tol:
                failures.append(f"{tag} pagerank: max err {err:.2e}")
        elif q.kind == "components":
            labels, count = refs["components"]
            if res.count != count or not np.array_equal(
                res.labels, labels
            ):
                failures.append(
                    f"{tag} components: {res.count} != {count} "
                    "or labels differ"
                )
        elif q.kind == "triangles":
            if res.count != refs["triangles"]:
                failures.append(
                    f"{tag} triangles: {res.count} != "
                    f"{refs['triangles']}"
                )
    return len(failures) == before


def _force_analytics_rung(engine, min_edges: int) -> None:
    """Pin the blocked analytics rungs' crossover for an A/B side
    (0 forces blocked wherever the tile gates allow; a huge value
    forces the host rungs)."""
    from bibfs_tpu.analytics.queries import ANALYTICS_KINDS

    for kind in ANALYTICS_KINDS:
        engine.routes[f"{kind}_blocked"].min_edges = int(min_edges)


def run_analytics(*, quick: bool = False, seed: int = 0,
                  wal_dir: str | None = None) -> dict:
    """The whole-graph analytics soak (``bench.py --serve-analytics``).

    Five phases:

    1. **exactness**: every kind on a random G(n, p), a perforated
       grid, and an RMAT graph, through BOTH engines — the synchronous
       engine pinned to the BLOCKED rungs (crossover forced to 0), the
       pipelined engine pinned to the HOST rungs — every answer
       verified against its independent reference (binary-heap
       Dijkstra, dense power iteration, union-find, adjacency
       intersection), and each side witnessed on the rung it claims in
       ``bibfs_query_total``.
    2. **host/blocked A/B**: per-kind best-of-3 solver-stamped clocks
       on a density ladder of random graphs (fresh engine per repeat,
       process-global jit cache warmed first, tables pre-built by an
       untimed primer query so only the kind's own fixpoint is timed).
       The smallest edge count where the blocked rung wins becomes the
       calibration ``analytics`` block; full runs gate blocked winning
       every kind at the dense end.
    3. **serving + store lifecycle** on one durable GraphStore:
       results persist as sidecar arrays (puts witnessed), a second
       (pipelined) engine re-serves them without recompute
       (``route="store"``), a MID-TRAFFIC roll with deletes
       invalidates and recomputes exactly, an adds-only
       update+compact serves SSSP/components by INCREMENTAL
       maintenance (``incremental`` events, no new full puts), and an
       adaptive engine learns per-``digest#kind`` ladder entries.
    4. **respawn**: a fresh ``GraphStore.from_dir`` process-restart
       serves the persisted vectors from mmap (``load`` events) with
       zero recompute.
    5. **chaos**: ``analytics:every=3`` and ``analytics_finish:times=4``
       each injected on a fresh engine; every kind still answers with
       the degrade witnessed in the resilience counters.
    """
    import shutil
    import tempfile

    from bibfs_tpu.analytics.queries import (
        ANALYTICS_KINDS,
        Components,
        PageRank,
        Sssp,
        Triangles,
    )
    from bibfs_tpu.graph.generate import (
        gnp_random_graph,
        grid_graph,
        rmat_graph,
    )
    from bibfs_tpu.serve import QueryEngine
    from bibfs_tpu.serve.faults import FaultPlan
    from bibfs_tpu.serve.pipeline import PipelinedQueryEngine
    from bibfs_tpu.store import GraphStore

    rng = np.random.default_rng(seed)
    failures: list[str] = []

    def kind_queries(s1, s2):
        return [Sssp(int(s1)), Sssp(int(s2)), PageRank(),
                Components(), Triangles()]

    # ---- phase 1: exactness on random + grid + RMAT, both engines ----
    if quick:
        n_rand = 260
        grid_wh = (14, 13)
        rmat_scale, rmat_ef = 7, 6
    else:
        n_rand = 420
        grid_wh = (19, 17)
        rmat_scale, rmat_ef = 9, 8
    n_rm, e_rm = rmat_graph(rmat_scale, rmat_ef, seed=seed + 3)
    graphs = {
        "random": (n_rand, gnp_random_graph(
            n_rand, 6.0 / n_rand, seed=seed + 1
        )),
        "grid": (grid_wh[0] * grid_wh[1], grid_graph(
            *grid_wh, perforation=0.08, seed=seed + 2
        )),
        "rmat": (n_rm, e_rm),
    }
    exact = {}
    for gname, (gn, gedges) in graphs.items():
        refs = _analytics_refs(gn, gedges, 0)
        s1, s2 = int(rng.integers(gn)), int(rng.integers(gn))
        qs = kind_queries(s1, s2)
        # sync engine, blocked rungs forced on
        eb = QueryEngine(gn, gedges)
        _force_analytics_rung(eb, 0)
        rb = eb.query_many(list(qs), return_errors=True)
        kb = eb.stats()["query_kinds"]
        eb.close()
        _check_analytics(f"{gname}/blocked", gn, refs, qs, rb, failures)
        # pipelined engine, host rungs forced
        eh = PipelinedQueryEngine(gn, gedges, max_wait_ms=None)
        _force_analytics_rung(eh, 1 << 30)
        rh = eh.query_many(list(qs), return_errors=True)
        kh = eh.stats()["query_kinds"]
        eh.close()
        _check_analytics(f"{gname}/host", gn, refs, qs, rh, failures)
        blocked_served = {
            k: int(kb.get(k, {}).get(f"{k}_blocked", 0))
            for k in ANALYTICS_KINDS
        }
        host_served = {
            k: int(kh.get(k, {}).get(k, 0)) for k in ANALYTICS_KINDS
        }
        if not all(blocked_served.values()):
            failures.append(
                f"{gname}: blocked rungs not exercised {blocked_served}"
            )
        if not all(host_served.values()):
            failures.append(
                f"{gname}: host rungs not exercised {host_served}"
            )
        if any(
            kh.get(k, {}).get(f"{k}_blocked") for k in ANALYTICS_KINDS
        ):
            failures.append(f"{gname}: host side leaked onto blocked")
        exact[gname] = {
            "n": gn, "edges": int(len(gedges)),
            "blocked_served": blocked_served,
            "host_served": host_served,
        }
    exact_ok = not failures

    # ---- phase 2: host/blocked A/B + crossover ladder ----------------
    # fresh engine per timed repeat (the per-engine kind cache would
    # otherwise re-serve the first answer); the process-global jit
    # cache is warmed by an untimed full pass per size, and an untimed
    # PRIMER query on each repeat engine pre-builds the tile tables so
    # the solver-stamped clock times only the kind's own fixpoint.
    # density ladder: the blocked substrate's work scales with the
    # occupied TILE x TILE blocks, the host scatter iteration with E —
    # so the ladder ramps density (edges per round of scatter), not
    # just vertex count, toward the dense-ish regime the tile tables
    # were built for
    ab_sizes = ((300, 8.0), (800, 24.0)) if quick else (
        (300, 8.0), (900, 12.0), (1200, 200.0)
    )
    ab_rows: dict = {}
    crossovers: dict = {}
    blocked_wins_dense: dict = {}
    kind_q = {
        "sssp": Sssp(1), "pagerank": PageRank(),
        "components": Components(), "triangles": Triangles(),
    }
    # the primer is untimed and runs first on every repeat engine: an
    # Sssp with a DIFFERENT source builds the tile tables AND the
    # seed-0 weight table (the one per-(engine, seed) build), so the
    # timed query's solver-stamped clock is the fixpoint alone
    primer = {k: Sssp(2) for k in ANALYTICS_KINDS}
    for an, deg in ab_sizes:
        a_edges = gnp_random_graph(an, deg / an, seed=seed + 5)
        num_edges = int(len(a_edges))

        def _timed(kind, min_edges, repeats=3):
            best = None
            for _r in range(repeats):
                e = QueryEngine(an, a_edges)
                _force_analytics_rung(e, min_edges)
                e.query_one(primer[kind])  # untimed: builds tables
                res = e.query_one(kind_q[kind])
                kinds = e.stats()["query_kinds"]
                e.close()
                want = (f"{kind}_blocked" if min_edges == 0 else kind)
                if not kinds.get(kind, {}).get(want):
                    failures.append(
                        f"ab n={an} {kind}: rung {want} not used"
                    )
                    return float("inf")
                if best is None or res.time_s < best:
                    best = float(res.time_s)
            return best

        # warm pass: compile every blocked program for this shape
        ew = QueryEngine(an, a_edges)
        _force_analytics_rung(ew, 0)
        ew.query_many(
            [Sssp(0), PageRank(), Components(), Triangles()],
            return_errors=True,
        )
        ew.close()
        row = {}
        for kind in ANALYTICS_KINDS:
            h = _timed(kind, 1 << 30)
            b = _timed(kind, 0)
            wins = bool(b < h)
            row[kind] = {
                "host_ms": round(h * 1e3, 3),
                "blocked_ms": round(b * 1e3, 3),
                "blocked_wins": wins,
            }
            if wins and kind not in crossovers:
                crossovers[f"{kind}_min_edges"] = num_edges
            blocked_wins_dense[kind] = wins  # last size stands
        ab_rows[str(an)] = {"edges": num_edges, **row}
    for kind in ANALYTICS_KINDS:
        crossovers.setdefault(f"{kind}_min_edges", 1 << 30)
    ab_ok = quick or all(blocked_wins_dense.values())
    if not ab_ok:
        failures.append(
            f"blocked rung lost the dense A/B: {blocked_wins_dense}"
        )

    # ---- phase 3: serving + store lifecycle --------------------------
    own_wal = wal_dir is None
    if own_wal:
        wal_dir = tempfile.mkdtemp(prefix="bibfs-analytics-")
    os.makedirs(wal_dir, exist_ok=True)
    store = GraphStore(
        compact_threshold=None, wal_dir=wal_dir, fsync="off",
    )
    sn = 320 if quick else 500
    s_edges = gnp_random_graph(sn, 7.0 / sn, seed=seed + 7)
    store.add("g", sn, s_edges)

    def store_events():
        return store.analytics.stats()["events"]

    def edge_set():
        return set(
            map(tuple, store.current("g").undirected_edges().tolist())
        )

    def rand_new_edges(count, existing):
        from bibfs_tpu.store.delta import canonical_edge

        out = set()
        while len(out) < count:
            u, v = int(rng.integers(sn)), int(rng.integers(sn))
            if u == v:
                continue
            e = canonical_edge(sn, u, v)
            if e not in existing and e not in out:
                out.add(e)
        return sorted(out)

    refs1 = _analytics_refs(sn, np.array(sorted(edge_set())), 0)
    src1 = int(rng.integers(sn))
    qs1 = [Sssp(src1), PageRank(), Components(), Triangles()]
    eng1 = QueryEngine(store=store, graph="g")
    r1 = eng1.query_many(list(qs1), return_errors=True)
    _check_analytics("serve/v1", sn, refs1, qs1, r1, failures)
    ev = store_events()
    puts_v1 = ev["put"]
    store_ok = bool(puts_v1 >= len(qs1))
    if not store_ok:
        failures.append(f"store puts after v1 serve: {puts_v1}")

    # a SECOND engine (pipelined — the consult seam both engines
    # share) re-serves from the store with zero recompute
    eng2 = PipelinedQueryEngine(store=store, graph="g",
                                max_wait_ms=None)
    r2 = eng2.query_many(list(qs1), return_errors=True)
    _check_analytics("serve/store-hit", sn, refs1, qs1, r2, failures)
    k2 = eng2.stats()["query_kinds"]
    served_store = sum(
        int(k2.get(k, {}).get("store", 0)) for k in ANALYTICS_KINDS
    )
    if served_store < len(qs1):
        failures.append(
            f"second engine not store-served: {served_store}"
        )
    reserve_ok = served_store >= len(qs1)

    # MID-TRAFFIC hot-swap with deletes: invalidate-and-recompute
    cur = edge_set()
    dels = sorted(
        map(tuple, rng.permutation(
            np.array(sorted(cur), dtype=np.int64)
        )[:4].tolist())
    )
    adds = rand_new_edges(8, cur)
    inval_before = store_events()["invalidated"]
    store.roll("g", adds=adds, dels=dels)
    refs2 = _analytics_refs(sn, np.array(sorted(edge_set())), 0)
    r1b = eng1.query_many(list(qs1), return_errors=True)
    _check_analytics("serve/post-swap", sn, refs2, qs1, r1b, failures)
    inval_after = store_events()["invalidated"]
    swap_ok = bool(inval_after > inval_before)
    if not swap_ok:
        failures.append("delete-roll did not invalidate stored results")

    # adds-only delta batch: SSSP/components maintained INCREMENTALLY
    adds2 = rand_new_edges(6, edge_set())
    store.update("g", adds=adds2, dels=[])
    store.compact("g")
    ev_before = store_events()
    refs3 = _analytics_refs(sn, np.array(sorted(edge_set())), 0)
    qs_inc = [Sssp(src1), Components()]
    r_inc = eng1.query_many(list(qs_inc), return_errors=True)
    _check_analytics("serve/incremental", sn, refs3, qs_inc, r_inc,
                     failures)
    ev_after = store_events()
    inc_delta = ev_after["incremental"] - ev_before["incremental"]
    put_delta = ev_after["put"] - ev_before["put"]
    incremental_ok = bool(inc_delta >= 2 and put_delta == 0)
    if not incremental_ok:
        failures.append(
            f"adds-only leg: incremental={inc_delta} new_puts="
            f"{put_delta} (wanted >=2 maintained, 0 full recomputes)"
        )

    # adaptive ladder: per-(digest, kind) entries learned for the new
    # kinds (the policy namespaces them as ``digest#<kind>``)
    eng_a = QueryEngine(store=store, graph="g", adaptive=True)
    eng_a.query_one(Sssp((src1 + 1) % sn))
    eng_a.query_one(Triangles())
    pol = (eng_a.stats().get("adaptive") or {}).get("digests", {})
    adaptive_kinds = sorted({
        k.rsplit("#", 1)[1] for k in pol if "#" in k
    })
    adaptive_ok = bool(
        {"sssp", "triangles"} <= set(adaptive_kinds)
    )
    if not adaptive_ok:
        failures.append(
            f"adaptive policy learned no analytics entries: {pol}"
        )
    eng_a.close()
    eng1.close()
    eng2.close()
    store.close()

    # ---- phase 4: respawn — mmap-served from the sidecars ------------
    store_r = GraphStore.from_dir(wal_dir, durable=True)
    eng_r = QueryEngine(store=store_r, graph="g")
    ev_r0 = store_r.analytics.stats()["events"]
    r_resp = eng_r.query_many(list(qs_inc), return_errors=True)
    _check_analytics("respawn", sn, refs3, qs_inc, r_resp, failures)
    ev_r1 = store_r.analytics.stats()["events"]
    kr = eng_r.stats()["query_kinds"]
    respawn_store_served = sum(
        int(kr.get(k, {}).get("store", 0)) for k in ANALYTICS_KINDS
    )
    respawn_ok = bool(
        ev_r1["load"] > ev_r0["load"]
        and respawn_store_served >= len(qs_inc)
    )
    if not respawn_ok:
        failures.append(
            f"respawn not mmap-served: loads={ev_r1['load']} "
            f"store_served={respawn_store_served}"
        )
    eng_r.close()
    store_r.close()

    # ---- phase 5: chaos on both analytics seams ----------------------
    cn = 240
    c_edges = gnp_random_graph(cn, 7.0 / cn, seed=seed + 9)
    c_refs = _analytics_refs(cn, c_edges, 0)
    chaos: dict = {}
    for spec in ("analytics:every=3", "analytics_finish:times=4"):
        plan = FaultPlan.parse(spec, seed=seed)
        ce = QueryEngine(cn, c_edges, faults=plan)
        cq = kind_queries(3, 5)[1:]  # one sssp + the three others
        cr = ce.query_many(list(cq), return_errors=True)
        cst = ce.stats()
        ce.close()
        pre = len(failures)
        _check_analytics(f"chaos[{spec}]", cn, c_refs, cq, cr, failures)
        res_block = cst["resilience"]
        fired = res_block["faults"]["fired_total"]
        degrade = (
            sum(res_block["fallbacks"].values())
            + int(res_block["retries"])
        )
        answered_exact = len(failures) == pre
        chaos[spec] = {
            "answered_exact": answered_exact,
            "faults_fired": fired,
            "degrades": degrade,
            "ok": bool(answered_exact and fired > 0 and degrade > 0),
        }
    chaos_ok = all(c["ok"] for c in chaos.values())

    if own_wal:
        shutil.rmtree(wal_dir, ignore_errors=True)

    gates = {
        "exact_ok": exact_ok,
        "ab_ok": ab_ok,
        "store_ok": store_ok,
        "reserve_ok": reserve_ok,
        "swap_ok": swap_ok,
        "incremental_ok": incremental_ok,
        "adaptive_ok": adaptive_ok,
        "respawn_ok": respawn_ok,
        "chaos_ok": chaos_ok,
    }
    return {
        "ok": bool(all(gates.values()) and not failures),
        "failures": failures[:20],
        "gates": gates,
        "exactness": exact,
        "ab": {"rows": ab_rows, "crossovers": crossovers,
               "gated": not quick},
        "store": {
            "puts_v1": int(puts_v1),
            "store_served": int(served_store),
            "incremental": int(inc_delta),
            "adaptive_kinds": adaptive_kinds,
        },
        "chaos": chaos,
    }
