"""Pipelined async serving — overlapped dispatch/finish + deadline flushing.

:class:`~bibfs_tpu.serve.engine.QueryEngine` is strictly synchronous:
every ``flush()`` blocks through device dispatch, the forced value read,
the host-side finish/decode, and forest banking before the next batch
can even be enqueued — host and device take turns idling, exactly the
serialization a sustained-traffic serving path cannot afford. ScalaBFS
(arxiv 2105.11754) gets its throughput from keeping every pipeline
stage busy simultaneously; :class:`PipelinedQueryEngine` applies the
same principle at the host/device seam, which the solver's already-split
``dispatch``/``finish`` callables expose for free:

- **background flusher** — ``submit()`` never blocks on solving: it
  appends to a lock-guarded queue and returns a :class:`QueryTicket`
  (a future; ``wait()`` blocks, ``result`` lands asynchronously). A
  dedicated flusher thread pops batches and launches them.
- **double-buffered device flushes** — the flusher runs
  ``_device_launch`` (enqueue only; on the tunneled runtime nothing has
  executed yet) and hands the in-flight batch to a finish worker that
  does the forced value read, the minor8 decode, result materialization
  and forest banking. While batch k finishes there, batch k+1's
  dispatch is already in flight — a bounded in-flight window
  (``max_inflight``, default 2 = classic double buffering) keeps the
  flusher from running unboundedly ahead.
- **deadline-based flushing** — ``max_wait_ms`` is a latency SLO: a
  sub-crossover queue flushes when its OLDEST query has waited that
  long, instead of waiting forever for depth (the synchronous engine's
  behavior). No submitted query waits in the queue longer than
  ``max_wait_ms`` plus one in-flight batch time.
- **two-stage host route** — below the crossover (and on the CPU
  substrate, where the device program cannot beat the host runtime it
  shares cores with) the flusher solves the whole batch through the
  threaded native C batch (ONE GIL-free ctypes call; the C side
  parallelizes internally) and the finish worker banks and resolves —
  so batch k+1's solve leaves Python entirely while batch k's
  Python-side resolution runs. Backlog-adaptive batching falls out for
  free: under load the flusher pops everything queued (up to
  ``max_batch``), amortizing the C batch's fixed per-call cost far
  better than any fixed flush depth.
- **instrumentation** — a lock-free-to-read latency histogram
  (p50/p95/p99), queue-depth and flush-cause counters, and a
  stage-concurrency clock whose ``overlap`` block reports how much of
  the busy time ≥2 pipeline stages really ran simultaneously, all in
  :meth:`PipelinedQueryEngine.stats`.

The shared caches are safe by construction: :class:`DistanceCache` and
:class:`ExecutableCache` lock internally, and engine counters are only
mutated under the engine lock or on the single finish worker.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.dtrace import FLIGHT, emit_span
from bibfs_tpu.obs.metrics import REGISTRY, LogHistogram, MetricBank
from bibfs_tpu.obs.trace import span
from bibfs_tpu.serve.engine import QueryEngine, _Pending
from bibfs_tpu.serve.resilience import (
    HealthMonitor,
    QueryError,
    to_query_error,
)
from bibfs_tpu.solvers.api import BFSResult

# The latency histogram grew into the general observability type
# (bibfs_tpu/obs/metrics.LogHistogram): same geometric buckets, same
# percentile reads, now also registry-attachable and Prometheus-rendered.
# The name stays importable from here (tests and downstream code use it).
LatencyHistogram = LogHistogram


def _pipe_counter_bank(label: str) -> MetricBank:
    """The pipelined engine's registry cells (stable names documented in
    README "Observability"): flush causes as one labeled counter family,
    watermarks as gauges — same keys the pre-obs ``pipe_counters`` dict
    had."""
    flushes = REGISTRY.counter(
        "bibfs_flushes_total", "Background flusher batches popped",
        ("engine",),
    )
    cause = REGISTRY.counter(
        "bibfs_flush_cause_total",
        "Flushes by trigger (depth/deadline/drain)",
        ("engine", "cause"),
    )
    blocked = REGISTRY.counter(
        "bibfs_submit_blocked_total",
        "Admissions throttled by the max_queue bound",
        ("engine",),
    )
    depth_max = REGISTRY.gauge(
        "bibfs_serve_queue_depth_max", "Deepest queue seen", ("engine",)
    )
    wait_max = REGISTRY.gauge(
        "bibfs_queue_wait_max_ms",
        "Worst submit->pop queue wait (the deadline-compliance witness)",
        ("engine",),
    )
    service_max = REGISTRY.gauge(
        "bibfs_batch_service_max_ms",
        "Worst launch->resolved batch service time",
        ("engine",),
    )
    return MetricBank({
        "flushes": flushes.labels(engine=label),
        "depth_flushes": cause.labels(engine=label, cause="depth"),
        "deadline_flushes": cause.labels(engine=label, cause="deadline"),
        "drain_flushes": cause.labels(engine=label, cause="drain"),
        "max_queue_depth": depth_max.labels(engine=label),
        "queue_wait_max_ms": wait_max.labels(engine=label),
        "batch_service_max_ms": service_max.labels(engine=label),
        "submit_blocked": blocked.labels(engine=label),
    })


class _StageClock:
    """Time-weighted pipeline-stage concurrency accounting.

    Every stage (a device dispatch on the flusher, a finish job, a host
    worker slice) brackets itself with ``enter()``/``exit()``; the clock
    accumulates wall time at each concurrency level. ``overlap_s`` (time
    at level >= 2) over ``busy_s`` is the pipeline's occupancy — the
    number that says whether dispatch and finish really overlapped or
    the "pipeline" degenerated to taking turns."""

    def __init__(self):
        self._lock = threading.Lock()
        self._level = 0
        self._t_mark = None
        self._at_level: dict[int, float] = {}
        self._t_first = None
        self._t_last = None

    def _shift(self, delta: int) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._t_first is None:
                self._t_first = now
            if self._level > 0 and self._t_mark is not None:
                self._at_level[self._level] = (
                    self._at_level.get(self._level, 0.0) + now - self._t_mark
                )
            self._t_mark = now
            self._t_last = now
            self._level += delta

    def enter(self) -> None:
        self._shift(+1)

    def exit(self) -> None:
        self._shift(-1)

    def stats(self) -> dict:
        with self._lock:
            busy = sum(self._at_level.values())
            overlap = sum(
                v for lvl, v in self._at_level.items() if lvl >= 2
            )
            wall = (
                (self._t_last - self._t_first)
                if self._t_first is not None else 0.0
            )
            return {
                "busy_s": round(busy, 4),
                "overlap_s": round(overlap, 4),
                "wall_s": round(wall, 4),
                "occupancy": round(overlap / busy, 4) if busy > 0 else 0.0,
                "max_concurrency": max(self._at_level, default=0),
            }


class QueryTicket(_Pending):
    """A submitted query's future: ``result`` lands asynchronously when
    the background pipeline resolves it; ``wait()`` blocks for it.

    Deliberately cheap to mint: no per-ticket Event (a lock allocation
    plus a set() handoff per query is real money at 10k+ qps) — waiters
    park on the engine's single condition variable, which resolution
    broadcasts once per BATCH."""

    __slots__ = ("t_submit", "t_launch", "t_done", "_engine")

    def __init__(self, src: int, dst: int, engine=None,
                 graph: str | None = None, ctx=None):
        super().__init__(src, dst, graph, ctx)
        self.t_submit = time.perf_counter()
        self.t_launch: float | None = None  # stamped at batch pop
        self.t_done: float | None = None
        self._engine = engine

    def done(self) -> bool:
        return self.result is not None or self.error is not None

    def cancel(self) -> bool:
        """Abandon this ticket: if it is still QUEUED it is removed
        from the engine's queue and batch accounting (so a later
        ``flush()``/``close()`` never waits on it) and fails with a
        ``kind='timeout'`` :class:`QueryError`. Returns True if this
        call cancelled it; False if it already resolved, failed, or
        was popped into an in-flight batch (in-flight tickets resolve
        normally — the pipeline never tears a launched batch apart).

        This is the post-``wait(timeout=...)`` cleanup: a timed-out
        waiter that walks away without cancelling leaves the ticket
        parked in the queue accounting forever."""
        eng = self._engine
        if eng is None or self.done():
            return False
        with eng._cv:
            if self.done():
                return False
            try:
                eng._queue.remove(self)
            except ValueError:
                return False  # already launched; it will resolve
            eng._outstanding -= 1
            eng._g_queue_depth.set(len(eng._queue))
            self.t_done = time.perf_counter()
            self.error = QueryError(
                "cancelled while queued", kind="timeout",
                query=(self.src, self.dst),
            )
            eng._count_error(self.error)
            eng._cv.notify_all()
        return True

    def wait(self, timeout: float | None = None, *,
             cancel_on_timeout: bool = False) -> BFSResult:
        """Block until the pipeline resolves this query and return its
        :class:`BFSResult`; re-raises a pipeline-side failure, raises
        ``TimeoutError`` if ``timeout`` seconds pass first
        (``cancel_on_timeout=True`` additionally :meth:`cancel` s the
        ticket so the abandoned query leaves the batch accounting)."""
        if self.result is None and self.error is None:
            eng = self._engine
            deadline = (
                None if timeout is None else time.monotonic() + timeout
            )
            with eng._cv:
                while self.result is None and self.error is None:
                    remaining = 0.5
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            if (cancel_on_timeout and not self.cancel()
                                    and self.done()):
                                # resolved in the deadline->cancel
                                # window: deliver the result we have
                                # rather than discarding it
                                break
                            raise TimeoutError(
                                f"query ({self.src}, {self.dst}) "
                                f"unresolved after {timeout}s"
                            )
                    eng._cv.wait(remaining)
        if self.error is not None:
            raise self.error
        return self.result


# _lock and _cv alias ONE RLock (the Condition wraps it): every queue/
# accounting mutation goes through that lock, whichever name the call
# site uses — the "mutated under the engine lock or on the single
# finish worker" contract, now machine-checked
@guarded_by(("_lock", "_cv"), "_queue", "_outstanding", "_flush_req",
            "_closed", "_errors")
class PipelinedQueryEngine(QueryEngine):
    """Asynchronous, deadline-flushing :class:`QueryEngine` (module
    docstring). Extra parameters on top of the base engine's:

    max_wait_ms : latency SLO — the longest a queued query may wait for
        batch-mates before the flusher force-flushes the queue
        (default 5.0; None restores depth-only flushing).
    max_inflight : launched-but-unfinished batch window (default 2 =
        double buffering: one batch finishing, the next dispatching).
    max_queue : admission control — ``submit()`` blocks (GIL released)
        once this many queries are queued, so a saturating producer
        gets throttled instead of growing the queue without bound and
        starving the very threads that drain it. Default
        ``max(max_batch, 4 * flush_threshold)``; None removes the
        bound.

    Submissions are thread-safe; call :meth:`close` (or use the engine
    as a context manager) to drain and tear down the worker threads.
    """

    _OBS_PREFIX = "pipe"

    def __init__(
        self,
        n: int | None = None,
        edges=None,
        *,
        max_wait_ms: float | None = 5.0,
        max_inflight: int = 2,
        max_queue: int | None = None,
        **kwargs,
    ):
        # own-argument validation BEFORE super(): the base ctor of a
        # store-backed engine acquires a snapshot pin that a raise here
        # would leak
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        super().__init__(n, edges, **kwargs)
        self.max_wait_ms = max_wait_ms
        self._wait_s = (
            None if max_wait_ms is None else max(float(max_wait_ms), 0.0) / 1e3
        )
        if max_queue is None:
            max_queue = max(self.max_batch, 4 * self.flush_threshold)
        self.max_queue = int(max_queue)
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque[QueryTicket] = deque()
        self._outstanding = 0  # queued + launched-but-unresolved tickets
        self._flush_req = False
        self._closed = False
        self._inflight = threading.BoundedSemaphore(int(max_inflight))
        self.latency = REGISTRY.histogram(
            "bibfs_query_latency_seconds",
            "Per-query submit-to-resolve latency",
            ("engine",),
        ).labels(engine=self.obs_label)
        self._g_queue_depth = REGISTRY.gauge(
            "bibfs_serve_queue_depth", "Queries currently queued",
            ("engine",),
        ).labels(engine=self.obs_label)
        self.stages = _StageClock()
        # registry-backed view; keys unchanged from the pre-obs dict:
        # flushes, depth/deadline/drain_flushes (drain = explicit
        # flush()/close() induced), max_queue_depth, queue_wait_max_ms
        # (submit -> batch pop, worst case), batch_service_max_ms
        # (launch -> batch resolved), submit_blocked (admissions
        # throttled by max_queue)
        self.pipe_counters = _pipe_counter_bank(self.obs_label)
        self._errors: list[str] = []
        # rebuild the health monitor with the queue-pressure input the
        # base ctor could not have (max_queue exists only now): a queue
        # at >= 90% of the admission bound reads as degraded
        self.health = HealthMonitor(
            breaker=self._breaker,
            window_s=self._health_window_s,
            queue_depth=lambda: len(self._queue),
            max_queue=self.max_queue,
            gauge=self._res_cells.health_gauge,
        )
        self.health.set_ready()
        # host solving serializer: the device->host RECOVERY path runs
        # host solves on the finish worker, concurrently with the
        # flusher's _launch_host — but the per-query native solver
        # reuses one NativeGraph scratch (solvers/native.py: explicitly
        # NOT thread-safe), and the host-solver lazy init is not
        # synchronized either. Uncontended (the no-failure case: only
        # the flusher ever takes it), so the fast path pays one free
        # lock acquisition per host batch. Reentrant: the bisection
        # isolator recurses through this same override.
        self._host_solve_lock = threading.RLock()
        self._finish_pool = ThreadPoolExecutor(
            1, thread_name_prefix="bibfs-finish"
        )
        self._flusher = threading.Thread(
            target=self._flusher_main, name="bibfs-flusher", daemon=True
        )
        self._flusher.start()

    # ---- submission --------------------------------------------------
    def submit(self, src: int, dst: int, graph: str | None = None,
               ctx=None) -> QueryTicket:
        """Queue one query WITHOUT blocking on any solve (``graph``
        names a store graph on a store-backed engine). Trivial queries
        and cache hits resolve before returning; everything else
        resolves when the background flusher's batch lands (depth,
        deadline, or drain — whichever comes first). ``ctx`` is a
        sampled distributed-trace context (:mod:`bibfs_tpu.obs.dtrace`)
        — the ticket carries it so resolution emits queue/resolve spans
        and dispatch routes propagate it; None (the default, every
        unsampled query) adds one attribute store and nothing else."""
        if self._draining:
            if self._closed:
                # a killed/closed engine is TERMINAL — it must not
                # masquerade as a retryable draining refusal (the sync
                # engine's post-kill submit raises closed the same way)
                raise RuntimeError("engine is closed")
            # draining-replica contract (see the sync engine's submit):
            # structured capacity refusal, queued tickets still resolve
            raise QueryError(
                "engine is draining", kind="capacity",
                query=(int(src), int(dst)),
            )
        src, dst = int(src), int(dst)
        name, rt = self._resolve_graph(graph)
        if not (0 <= src < rt.n and 0 <= dst < rt.n):
            raise ValueError(f"src/dst out of range for n={rt.n}")
        t = QueryTicket(src, dst, self, name, ctx)
        if src == dst:
            with self._lock:
                if self._closed:
                    raise RuntimeError("engine is closed")
                self._c_queries.inc()
                self._c_trivial.inc()
            self._finish_ticket(t, BFSResult(True, 0, [src], src, 0.0, 0, 0))
            self.latency.record(t.t_done - t.t_submit)
            return t
        # the oracle tier answers BEFORE the distance cache, at submit
        # time (no queueing, no flusher handoff): the consult is two
        # int16 row reads over an immutable index, and a store oracle is
        # only returned when its index describes the CURRENT live graph
        # (overlay included), so it may also answer ahead of the overlay
        # route. A non-exact consult arms t.cutoff for the host rungs.
        if self._consult_oracle(t, name):
            with self._lock:
                if self._closed:
                    raise RuntimeError("engine is closed")
                self._c_queries.inc()
                self._c_oracle.inc()
            self._finish_ticket(t, t.result)
            self.latency.record(t.t_done - t.t_submit)
            return t
        if not self._queue and self._overlay_pending(name) is None:
            # idle fast path: a cache hit answers inline with ~0 latency.
            # Under load the lookup moves to the flusher (_serve_cached,
            # one pass per batch) — at 10k+ qps a per-submit cache-lock
            # handoff between the producer and the resolving stages is a
            # GIL convoy, and the flush-time lookup even sees results
            # that land AFTER submission. (A graph with pending live
            # updates skips the cache outright: its entries describe the
            # base snapshot, not the overlaid graph.) Re-resolve the
            # runtime AFTER the overlay read — overlay-read-then-resolve
            # is the swap-race-safe ordering (see the sync submit).
            rt = self._graph_rt(name)
            hit = self.dist_cache.lookup(rt.graph_id, src, dst)
            if hit is not None:
                found, hops, path = hit
                with self._lock:
                    if self._closed:
                        raise RuntimeError("engine is closed")
                    self._c_queries.inc()
                    self._c_cache_served.inc()
                self._finish_ticket(t, BFSResult(
                    found, hops if found else None, path if found else None,
                    None, 0.0, 0, 0,
                ))
                self.latency.record(t.t_done - t.t_submit)
                return t
        with self._cv:
            if self._closed:
                raise RuntimeError("engine is closed")
            if len(self._queue) >= self.max_queue:
                # admission control: block the producer (GIL released in
                # the wait) until the flusher makes room — a saturated
                # server throttles arrivals instead of hoarding them
                self.pipe_counters["submit_blocked"] += 1
                while len(self._queue) >= self.max_queue:
                    if not self._flusher.is_alive():
                        raise RuntimeError(
                            "pipeline flusher died: "
                            + "; ".join(self._errors)
                        )
                    self._cv.wait(timeout=0.1)
                    if self._closed:
                        raise RuntimeError("engine is closed")
            self._c_queries.inc()
            self._queue.append(t)
            self._outstanding += 1
            depth = len(self._queue)
            self._g_queue_depth.set(depth)
            self.pipe_counters.cell("max_queue_depth").set_max(depth)
            # wake the flusher only when this submit can change its
            # decision: arming the deadline timer (empty -> 1), crossing
            # the depth trigger, or filling the admission queue —
            # notifying every submit costs a syscall per query at high
            # rates
            if (depth == 1 or depth == self.flush_threshold
                    or depth >= self.max_queue):
                self._cv.notify_all()
        return t

    def query(self, src: int, dst: int, graph: str | None = None
              ) -> BFSResult:
        """Submit one query and block for its result (the deadline — or
        queue depth — decides when it actually flushes)."""
        return self.submit(src, dst, graph).wait()

    def submit_query(self, q, graph: str | None = None) -> QueryTicket:
        """The typed taxonomy submit (:meth:`QueryEngine.submit_query`),
        pipelined flavor: a point-to-point query rides the background
        flusher unchanged; the other kinds are host-tier solves with
        no dispatch to overlap, so they resolve ON THE SUBMITTING
        THREAD through the same kind-route machinery (breaker, retry,
        fallback, caching) and return an already-done ticket — the
        pipeline stays dedicated to the dispatch-shaped work it
        exists to overlap."""
        from bibfs_tpu.query.types import PointToPoint, coerce_query

        q = coerce_query(q)
        if isinstance(q, PointToPoint):
            self._query_cells.cell("pt", "ladder").inc()
            return self.submit(q.src, q.dst, graph)
        if self._draining:
            if self._closed:
                raise RuntimeError("engine is closed")
            raise QueryError(
                "engine is draining", kind="capacity",
                query=self._query_rep_pair(q),
            )
        name, rt = self._resolve_graph(graph)
        q.validate(rt.n)
        src, dst = self._query_rep_pair(q)
        t = QueryTicket(src, dst, self, name)
        t.query = q
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._c_queries.inc()
        overlay = self._overlay_pending(name)
        if overlay is None:
            rt = self._graph_rt(name)  # overlay-read-then-resolve
            hit = self._kind_cache.lookup(rt.graph_id, q.cache_key())
            if hit is not None:
                self._query_cells.cell(q.kind, "cache").inc()
                self._finish_ticket(t, hit)
                self.latency.record(t.t_done - t.t_submit)
                return t
            res = self._consult_analytics_store(name, rt, q)
            if res is not None:
                self._finish_ticket(t, res)
                self.latency.record(t.t_done - t.t_submit)
                return t
        rt = self._pin_rt(name)
        # the host-solve serializer also covers taxonomy solves: the
        # kind fallbacks share the per-runtime serial machinery with
        # the flusher's host rung
        with self._host_solve_lock, self._bound(rt):
            self._flush_taxonomy(name, [t], overlay)
        t.t_done = time.perf_counter()
        self.latency.record(t.t_done - t.t_submit)
        with self._cv:
            self._cv.notify_all()  # wake any wait() already parked
        return t

    def query_one(self, q, graph: str | None = None):
        """Submit one typed query and block for its kind's result."""
        return self.submit_query(q, graph).wait()

    def query_many(self, pairs, *, graph: str | None = None,
                   return_errors: bool = False) -> list:
        """Submit a whole query list, drain, and return the results.

        ``return_errors=True`` is the partial-failure mode (same
        contract as the synchronous engine's): per-pair
        ``BFSResult | QueryError`` instead of raising on the first
        failed ticket."""
        tickets = self._submit_collect(pairs, return_errors, graph)
        if not tickets:
            return []
        if any(isinstance(t, QueryTicket) for t in tickets):
            self.flush()
        out = []
        for t in tickets:
            if isinstance(t, QueryError):
                out.append(t)
                continue
            try:
                out.append(t.wait(timeout=60.0))
            except Exception as e:
                if not return_errors:
                    raise
                out.append(to_query_error(e, (t.src, t.dst)))
        return out

    # ---- flushing ----------------------------------------------------
    def flush(self, timeout: float | None = None) -> None:
        """Force the background flusher to drain the queue NOW, then
        block until every previously submitted query has resolved.
        ``timeout`` bounds the drain wait (seconds) — on expiry a
        ``TimeoutError`` reports how many tickets are still
        outstanding, which is how the chaos harness detects a stranded
        ticket instead of hanging on it."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._cv:
            self._flush_req = True
            self._cv.notify_all()
            while self._outstanding > 0:
                if not self._flusher.is_alive():
                    raise RuntimeError(
                        "pipeline flusher died: " + "; ".join(self._errors)
                    )
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    raise TimeoutError(
                        f"flush timed out after {timeout}s with "
                        f"{self._outstanding} tickets outstanding"
                    )
                self._cv.wait(timeout=0.1)

    def kill(self) -> None:
        """Crash-semantics teardown for chaos drills: tickets still
        QUEUED fail NOW with ``kind='internal'`` :class:`QueryError` s
        (a crashed replica cannot solve them — a fleet router reroutes
        the failures to a peer) instead of being drained by the
        flusher; batches already launched still resolve through their
        finish jobs (they are past the point a real crash could
        silently unwind without losing tickets, and zero-lost is the
        invariant every chaos gate holds). Workers are then joined and
        the snapshot pins drop. Contrast :meth:`close`, which drains
        the whole queue first."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._draining = True
            self.health.set_draining()
            leftovers = [t for t in self._queue if not t.done()]
            self._queue.clear()
            for t in leftovers:
                self._fail_ticket(t, QueryError(
                    "replica killed: engine torn down with queries "
                    "queued", kind="internal", query=(t.src, t.dst),
                ))
            self._outstanding -= len(leftovers)
            self._g_queue_depth.set(0)
            self._cv.notify_all()
        self._flusher.join(timeout=60.0)
        self._finish_pool.shutdown(wait=True)
        self._release_runtimes()

    def close(self) -> None:
        """Drain the queue, stop the flusher, and join every worker.
        Idempotent; the engine rejects submissions afterwards (and
        ``/healthz`` reports ``draining`` from the first moment, so a
        load balancer stops sending traffic while the drain runs).
        Tickets already submitted are resolved by the drain; anything
        left queued after the workers stop (a wedged or dead flusher)
        is failed with a clear ``engine is closed`` error rather than
        stranding its waiters."""
        with self._cv:
            if self._closed:
                already = True
            else:
                already = False
                self._closed = True
                self.health.set_draining()
                self._cv.notify_all()
        self._flusher.join(timeout=60.0)
        if not already:
            self._finish_pool.shutdown(wait=True)
            with self._cv:
                leftovers = [t for t in self._queue if not t.done()]
                self._queue.clear()
                for t in leftovers:
                    # kind=capacity, per the taxonomy ("engine
                    # draining"): a routine shutdown must not land in
                    # the internal-failure series operators alert on
                    self._fail_ticket(t, QueryError(
                        "engine is closed", kind="capacity",
                        query=(t.src, t.dst),
                    ))
                self._outstanding -= len(leftovers)
                self._g_queue_depth.set(0)
                self._cv.notify_all()
            self._release_runtimes()

    # ---- the background flusher --------------------------------------
    def _flush_reason_locked(self):
        if not self._queue:
            self._flush_req = False  # nothing left to force
            return "exit" if self._closed else None
        if len(self._queue) >= self.flush_threshold:
            return "depth"
        if len(self._queue) >= self.max_queue:
            # a full admission queue is itself pressure: flush it even
            # below the crossover, or a producer blocked in submit()
            # with depth-only flushing (max_wait_ms=None,
            # max_queue < flush_threshold) would deadlock forever
            return "depth"
        if self._flush_req or self._closed:
            return "drain"
        if self._wait_s is not None:
            age = time.perf_counter() - self._queue[0].t_submit
            if age >= self._wait_s:
                return "deadline"
        return None

    def _wait_timeout_locked(self):
        if not self._queue or self._wait_s is None:
            return None
        # sleep exactly until the oldest query's deadline
        age = time.perf_counter() - self._queue[0].t_submit
        return max(self._wait_s - age, 0.0)

    def _flusher_main(self):
        while True:
            with self._cv:
                while True:
                    reason = self._flush_reason_locked()
                    if reason is not None:
                        break
                    self._cv.wait(self._wait_timeout_locked())
                if reason == "exit":
                    return
                batch = [
                    self._queue.popleft()
                    for _ in range(min(len(self._queue), self.max_batch))
                ]
                self._g_queue_depth.set(len(self._queue))
                self._cv.notify_all()  # wake producers blocked on max_queue
                now = time.perf_counter()
                for t in batch:
                    t.t_launch = now  # queue stage ends at the pop
                wait_ms = (now - batch[0].t_submit) * 1e3
                self.pipe_counters.cell("queue_wait_max_ms").set_max(
                    wait_ms)
                self.pipe_counters["flushes"] += 1
                self.pipe_counters[f"{reason}_flushes"] += 1
            try:
                with span("flush", queued=len(batch), cause=reason):
                    self._launch(batch)
            except Exception as e:  # never strand a waiter
                self._record_error(e)
                self._fail_batch(batch, e)

    def _launch(self, batch: list[QueryTicket]) -> None:
        if self._store is None:
            self._launch_group(None, batch)
            return
        # one popped batch can interleave graphs: group per graph, each
        # group bound to the snapshot it resolves NOW (in-flight groups
        # keep their pin across a concurrent hot-swap). Failure is
        # isolated per group: a raise from group k must fail ONLY group
        # k's tickets — letting it reach _flusher_main's _fail_batch
        # would also fail (and double-decrement) tickets of earlier
        # groups already handed to the finish worker.
        groups: "OrderedDict[str, list[QueryTicket]]" = OrderedDict()
        for t in batch:
            groups.setdefault(t.graph, []).append(t)
        for name, group in groups.items():
            try:
                self._launch_group(name, group)
            except Exception as e:
                self._record_error(e)
                self._fail_batch(group, e)

    def _launch_group(self, name, batch: list[QueryTicket]) -> None:
        # dedupe exact repeats within one batch: serving traffic
        # repeats, and a batch slot per duplicate would be pure waste
        unique: "OrderedDict[tuple[int, int], list[QueryTicket]]" = (
            OrderedDict()
        )
        for t in batch:
            unique.setdefault((t.src, t.dst), []).append(t)
        # the flush's sampled trace context rides on the engine for the
        # ladder walk (one descriptor per batch): dispatch routes stamp
        # it onto cross-process descriptors (pod workers). Only the
        # flusher thread runs _launch_group, so this is race-free.
        self._launch_ctx = next(
            (t.ctx for t in batch if t.ctx is not None), None
        )
        try:
            self._launch_group_routed(name, unique)
        finally:
            self._launch_ctx = None

    def _launch_group_routed(self, name, unique) -> None:
        # overlay BEFORE pin — same swap-race ordering as the sync
        # engine's _flush_graph (see the comment there)
        overlay = self._overlay_pending(name)
        rt = self._pin_rt(name)
        with self._bound(rt):
            if overlay is not None:
                self._launch_overlay(overlay, unique)
                return
            pairs = self._serve_cached(unique)
            if not pairs:
                return
            # the fallback ladder, pipelined edition: each eligible
            # dispatch rung (mesh, device) is gated by its OWN breaker
            # (open = the route is known-bad, go straight down; a
            # half-open breaker lets one probe batch through and its
            # outcome closes or re-opens it), launches on the flusher
            # and finishes on the worker; the terminal host rung solves
            # right here behind the bisection isolator
            ladder = self._ladder_for(rt, pairs)
            for i, rung in enumerate(ladder):
                if rung == "host":
                    break
                route = self.routes[rung]
                if not route.eligible(rt, pairs):
                    if rung == "mesh":
                        self._note_crossover()
                    continue
                if route.breaker is None or route.breaker.allow():
                    if self._launch_dispatch(route, rt, pairs, unique):
                        return
                self._note_fallback(
                    rung, self._next_rung(i, rt, pairs, ladder)
                )
            self._launch_host(rt, pairs, unique)

    def _launch_overlay(self, overlay, unique) -> None:
        """Exact answering while the graph has pending live updates,
        pipelined edition: base+delta host solves run right here on the
        flusher (the route is host-bound anyway) and tickets resolve
        inline — no cache banking, the overlaid graph is not any
        snapshot (see the sync engine's ``_flush_overlay``)."""
        t_launch = time.perf_counter()
        self.stages.enter()
        try:
            with span("overlay_batch", batch=len(unique)):
                lats = []
                qlist = []
                served = 0
                for key, res in self.routes["overlay"].solve_iter(
                    overlay, list(unique)
                ):
                    tickets = unique[key]
                    if isinstance(res, QueryError):
                        for t in tickets:
                            if not t.done():
                                self._fail_ticket(t, res)
                        continue
                    served += 1
                    for t in tickets:
                        if self._finish_ticket(t, res):
                            lats.append(t.t_done - t.t_submit)
                            if t.t_launch is not None:
                                qlist.append(t.t_launch - t.t_submit)
                self.latency.record_many(lats)
                with self._lock:
                    self._c_overlay.inc(served)
                self._note_batch_stages(
                    "overlay", len(lats), qlist,
                    resolve_s=time.perf_counter() - t_launch,
                )
        finally:
            self.stages.exit()
            self._note_batch_done(
                t_launch, sum(len(ts) for ts in unique.values())
            )

    def _serve_cached(self, unique) -> list[tuple[int, int]]:
        """One cache pass over the deduped batch (submit skips the
        lookup under load): hits resolve right here with zero solver
        work; the returned misses are what actually launches."""
        pairs = []
        hits = 0
        lats = []
        for key, tickets in unique.items():
            hit = self.dist_cache.lookup(self.graph_id, *key)
            if hit is None:
                pairs.append(key)
                continue
            found, hops, path = hit
            res = BFSResult(
                found, hops if found else None, path if found else None,
                None, 0.0, 0, 0,
            )
            for t in tickets:
                if self._finish_ticket(t, res):
                    lats.append(t.t_done - t.t_submit)
            hits += len(tickets)
        if hits:
            self.latency.record_many(lats)
            with self._cv:
                self._c_cache_served.inc(hits)
                self._outstanding -= hits
                self._cv.notify_all()
        return pairs

    # -- dispatch rungs (mesh, device): launch on the flusher, finish
    # -- on the worker
    def _launch_dispatch(self, route, rt, pairs, unique) -> bool:
        """Resilient dispatch for one ladder rung: bounded retries with
        backoff on the flusher (the route's breaker already admitted
        this batch); when the launch seam stays dead, release the
        in-flight slot and return False — the ladder walk degrades the
        batch to the next rung instead of failing its tickets. The
        breaker's success is recorded at FINISH time (a dispatch that
        enqueues but cannot execute must not close a half-open
        breaker). ``rt`` rides along to the finish worker with its own
        snapshot pin — the finish of batch k must decode and bank on
        the snapshot it launched on, even if the store swaps before the
        worker gets to it."""
        breaker = route.breaker
        retry = route.retry
        self._inflight.acquire()  # double-buffer backpressure
        # "one batch time" (batch_service_max_ms) is measured from AFTER
        # the in-flight window opens: including the acquire wait would
        # make the deadline budget self-referential under backlog
        t_launch = time.perf_counter()
        attempt = 0
        held = True  # our in-flight slot, until handed to the finish job
        job_pin = False  # the finish job's snapshot pin, once taken
        try:
            while True:
                try:
                    t_try = time.perf_counter()
                    self.stages.enter()
                    try:
                        out, finish, t0 = route.launch(rt, pairs)
                    finally:
                        self.stages.exit()
                    # the SUCCESSFUL attempt's launch cost (excludes
                    # failed tries + backoff): half of the adaptive
                    # policy's route-time sample, the finish worker
                    # adds its own half — so the measurement never
                    # includes the finish pool's queue wait, which
                    # would penalize dispatch routes exactly when they
                    # carry traffic
                    launch_s = time.perf_counter() - t_try
                    break
                except Exception as e:
                    breaker.record_failure()
                    self._record_error(e)
                    attempt += 1
                    # gate BEFORE counting/sleeping: when this failure
                    # was the one that opened the breaker, there is no
                    # retry to count and no backoff worth blocking the
                    # flusher for
                    if (retry is not None and attempt < retry.attempts
                            and breaker.allow()):
                        self._res_cells.retry_cell(route.name).inc()
                        time.sleep(retry.delay_s(attempt - 1))
                        continue
                    held = False
                    self._inflight.release()
                    return False
            rt.snapshot.retain()
            job_pin = True
            self._finish_pool.submit(
                self._dispatch_finish_job, route, rt, out, finish, t0,
                pairs, unique, t_launch, launch_s,
            )
            return True
        except BaseException:
            # an escape outside the retry loop (KeyboardInterrupt, a
            # dead finish pool raising on submit) must not leak the
            # in-flight slot — a leaked slot halves the pipeline, two
            # wedge it forever — NOR the breaker's half-open probe
            # claim: the allow() that admitted this batch must get its
            # record (failure, conservatively; an extra record_failure
            # after a counted one is harmless) or allow() returns
            # False forever and the route never recovers
            breaker.record_failure()
            if job_pin:
                rt.snapshot.release()
            if held:
                self._inflight.release()
            raise

    def _dispatch_finish_job(self, route, rt, out, finish, t0, pairs,
                             unique, t_launch, launch_s=0.0):
        self.stages.enter()
        try:
            with self._bound(rt):  # decode/bank on the LAUNCH snapshot
                try:
                    # counters inside route.finish are safe un-locked:
                    # this pool has exactly ONE worker, the only
                    # dispatch-side mutator
                    t_fin = time.perf_counter()
                    results = route.finish(out, finish, t0, pairs)
                except Exception as e:
                    # mid-execution dispatch failure: the batch is
                    # already off the flusher, so recover it right here
                    # on the finish worker through the host ladder —
                    # tickets fail only if every rung fails them
                    # individually
                    route.breaker.record_failure()
                    self._record_error(e)
                    self._note_fallback(route.name, "host")
                    with span("recover_host", batch=len(pairs)):
                        self._deliver_host(
                            pairs, unique, self._solve_host_isolated(
                                pairs,
                                self._cutoffs_for(pairs, unique),
                            )
                        )
                    return
                route.breaker.record_success()
                # the adaptive sample: two upper bounds on the true
                # solve cost exist here — launch_s + finish wall
                # (excludes the finish pool's queue wait, includes the
                # untimed epilogue) and the solver-stamped time_s
                # (t0 -> force: excludes the epilogue, includes the
                # queue wait). min() is tighter than either and
                # collapses to the sync engine's convention
                # (results[0].time_s) whenever the pool is idle, so
                # the shared sidecar never blends a loaded pipeline's
                # queue wait OR a big batch's epilogue into a route's
                # learned latency
                self._note_route_time(
                    rt, route.name, pairs,
                    min(launch_s + time.perf_counter() - t_fin,
                        results[0].time_s if results else 0.0),
                )
                t_resv = time.perf_counter()
                lats = []
                qlist = []
                for (src, dst), res in zip(pairs, results):
                    self.dist_cache.put_result(
                        self.graph_id, src, dst, res.found, res.hops,
                        res.path,
                    )
                    for t in unique[(src, dst)]:
                        if self._finish_ticket(t, res):
                            lats.append(t.t_done - t.t_submit)
                            qlist.append(t.t_launch - t.t_submit)
                self.latency.record_many(lats)
                self._note_batch_stages(
                    route.name, len(lats), qlist, launch_s,
                    finish_s=t_resv - t_fin,
                    resolve_s=time.perf_counter() - t_resv,
                )
        except Exception as e:
            self._record_error(e)
            for key in pairs:
                for t in unique[key]:
                    if not t.done():  # never clobber a delivered result
                        self._fail_ticket(t, e)
        finally:
            self.stages.exit()
            self._inflight.release()
            self._note_batch_done(
                t_launch, sum(len(unique[p]) for p in pairs)
            )

    # -- host route: solve on the flusher, resolve on the worker -------
    def _launch_host(self, rt, pairs, unique) -> None:
        """Host SOLVE stage, run right here on the flusher: on the
        native route this is one GIL-free threaded-C call for the whole
        batch (``_solve_host`` — the C batch parallelizes internally, so
        a Python-side worker pool would only add GIL handoffs), behind
        the bisection isolator, so a poison batch yields per-query
        ``QueryError`` s instead of an exception. The Python-side
        resolution hands off to the finish worker: batch k+1 solves
        here while batch k banks and resolves there — the same
        two-stage overlap the device route gets from its
        dispatch/finish split."""
        self._inflight.acquire()
        t_launch = time.perf_counter()  # post-acquire; see _launch_dispatch
        job_pin = False
        try:
            self.stages.enter()
            try:
                results = self._solve_host_isolated(
                    pairs, self._cutoffs_for(pairs, unique)
                )
                launch_s = time.perf_counter() - t_launch
                self._note_route_time(rt, "host", pairs, launch_s)
            finally:
                self.stages.exit()
            rt.snapshot.retain()  # the resolve job banks on THIS snapshot
            job_pin = True
            self._finish_pool.submit(
                self._host_resolve_job, rt, pairs, unique, t_launch,
                results, launch_s,
            )
        except BaseException:
            if job_pin:
                rt.snapshot.release()
            self._inflight.release()  # never leak the in-flight slot
            raise

    def _host_resolve_job(self, rt, pairs, unique, t_launch,
                          results, launch_s=None) -> None:
        self.stages.enter()
        try:
            with self._bound(rt), span("host_resolve", batch=len(pairs)):
                try:
                    self._deliver_host(pairs, unique, results, launch_s)
                except Exception as e:
                    self._record_error(e)
                    for key in pairs:
                        for t in unique[key]:
                            if not t.done():
                                self._fail_ticket(t, e)
        finally:
            self.stages.exit()
            self._inflight.release()
            self._note_batch_done(
                t_launch, sum(len(unique[p]) for p in pairs)
            )

    def _solve_host_isolated(self, pairs, cutoffs=None):
        # serialize ALL host solving (module comment on
        # _host_solve_lock): flusher host batches and finish-worker
        # recovery share non-thread-safe native scratch
        with self._host_solve_lock:
            return super()._solve_host_isolated(pairs, cutoffs)

    # the resilience cells are the registry's deliberately LOCK-FREE
    # counters (obs/metrics.py: concurrent mutators of one cell must
    # hold the component's lock). In the sync engine the caller thread
    # is the only mutator; here the flusher AND the finish worker both
    # reach the fallback/error cells (device-finish recovery, fail
    # paths), so the increments take the engine lock — cold paths only,
    # the fault-free hot loop never passes through either.
    def _note_fallback(self, frm: str, to: str) -> None:
        FLIGHT.note("route", fallback=frm, to=to)
        with self._lock:
            super()._note_fallback(frm, to)

    def _note_crossover(self) -> None:
        with self._lock:
            super()._note_crossover()

    def _count_error(self, err: BaseException, n: int = 1) -> None:
        with self._lock:
            super()._count_error(err, n)

    def _deliver_host(self, pairs, unique, results, launch_s=None) -> None:
        """Resolve one host-solved batch (finish-worker side) through
        the shared delivery skeleton
        (:meth:`QueryEngine._deliver_host_results`): bank and finish
        the successes, fail exactly the tickets whose query the
        isolator gave up on. Used by the host route (which passes its
        solve time as ``launch_s`` for the stage breakdown) and the
        device->host recovery path."""
        t_resv = time.perf_counter()
        lats = []
        qlist = []

        def resolve_ok(key, res):
            self.dist_cache.put_result(
                self.graph_id, key[0], key[1], res.found, res.hops,
                res.path,
            )
            for t in unique[key]:
                if self._finish_ticket(t, res):
                    lats.append(t.t_done - t.t_submit)
                    if t.t_launch is not None:
                        qlist.append(t.t_launch - t.t_submit)

        def resolve_err(key, err):
            for t in unique[key]:
                if not t.done():
                    self._fail_ticket(t, err)

        n_ok = self._deliver_host_results(
            pairs, results, resolve_ok, resolve_err
        )
        self.latency.record_many(lats)
        with self._lock:
            self._c_host_queries.inc(n_ok)
        self._note_batch_stages(
            "host", len(lats), qlist, launch_s,
            resolve_s=time.perf_counter() - t_resv,
        )

    # ---- resolution --------------------------------------------------
    def _finish_ticket(self, t: QueryTicket, res: BFSResult) -> bool:
        # waiters park on the engine cv and are broadcast to once per
        # batch (_note_batch_done); latency is recorded batchwise by the
        # resolving stage. A cancelled ticket (error already set) is
        # left alone — its waiter already saw the cancellation.
        if t.error is not None:
            return False
        t.t_done = time.perf_counter()
        t.result = res
        if t.ctx is not None:
            # sampled query: its ticket timeline becomes causally-
            # linked spans in this process's spool, parented under the
            # ingress span whose context rode in on the submit
            if t.t_launch is not None:
                emit_span("queue", t.ctx, t.t_submit,
                          t.t_launch - t.t_submit)
                emit_span("resolve", t.ctx, t.t_launch,
                          t.t_done - t.t_launch)
            else:  # resolved inline at submit (trivial/oracle/cache)
                emit_span("resolve", t.ctx, t.t_submit,
                          t.t_done - t.t_submit)
            FLIGHT.note(
                "query", trace=t.ctx.trace_id, src=t.src, dst=t.dst,
                queue_ms=(
                    None if t.t_launch is None
                    else round((t.t_launch - t.t_submit) * 1e3, 3)
                ),
                total_ms=round((t.t_done - t.t_submit) * 1e3, 3),
            )
        return True

    def _fail_ticket(self, t: QueryTicket, err: BaseException) -> None:
        """One ticket fails with a STRUCTURED error: whatever the
        pipeline caught is wrapped into a taxonomy-tagged
        :class:`QueryError` (and counted in ``bibfs_errors_total`` +
        the health window) so callers never see a raw backend
        traceback class."""
        qerr = (
            err if isinstance(err, QueryError)
            else to_query_error(err, (t.src, t.dst))
        )
        self._count_error(qerr)
        t.t_done = time.perf_counter()
        t.error = qerr

    def _fail_batch(self, batch, err) -> None:
        failed = 0
        for t in batch:
            if not t.done():
                self._fail_ticket(t, err)
                failed += 1
        self._note_batch_done(time.perf_counter(), failed)

    def _note_batch_stages(self, route: str, n: int, queue_list: list,
                           launch_s: float | None = None, *,
                           finish_s: float | None = None,
                           resolve_s: float | None = None) -> None:
        """One resolved batch's cost attribution: the per-route/
        per-stage breakdown (under the engine lock — the flusher and
        the finish worker both land here) plus the always-on
        flight-recorder batch entry. launch/finish/resolve are
        batch-grain stages and take one histogram sample each; the
        queue stage is per-query by nature, so the batch's waits are
        histogrammed here in ONE ``record_many`` lock acquisition (the
        per-ticket cost in ``_finish_ticket`` stays a list append)."""
        queue_sum = 0.0
        if queue_list:
            self._stage_cells["queue"].record_many(queue_list)
            queue_sum = sum(queue_list)
        with self._lock:
            if n:
                self._note_stage(route, "queue", queue_sum, n=n,
                                 record=False)
            if launch_s is not None:
                self._note_stage(route, "launch", launch_s)
            if finish_s is not None:
                self._note_stage(route, "finish", finish_s)
            if resolve_s is not None:
                self._note_stage(route, "resolve", resolve_s)
        FLIGHT.note(
            "batch", route=route, queries=n,
            queue_ms=round(queue_sum * 1e3, 3),
            launch_ms=(
                None if launch_s is None else round(launch_s * 1e3, 3)
            ),
            finish_ms=(
                None if finish_s is None else round(finish_s * 1e3, 3)
            ),
            resolve_ms=(
                None if resolve_s is None else round(resolve_s * 1e3, 3)
            ),
        )

    def _note_batch_done(self, t_launch: float, tickets: int) -> None:
        service_ms = (time.perf_counter() - t_launch) * 1e3
        with self._cv:
            self.pipe_counters.cell("batch_service_max_ms").set_max(
                service_ms)
            self._outstanding -= tickets
            self._cv.notify_all()

    def _record_error(self, e: BaseException) -> None:
        with self._lock:
            self._errors.append(f"{type(e).__name__}: {e}"[:300])
            del self._errors[:-20]  # keep the newest few

    # ---- introspection ----------------------------------------------
    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def stats(self) -> dict:
        base = super().stats()
        with self._lock:
            pipe = dict(self.pipe_counters)
            pipe.update(
                outstanding=self._outstanding,
                max_wait_ms=self.max_wait_ms,
                max_queue=self.max_queue,
                errors=list(self._errors),
            )
        base.update(
            pipeline=pipe,
            latency_ms=self.latency.summary_ms(),
            overlap=self.stages.stats(),
        )
        return base
