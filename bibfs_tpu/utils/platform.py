"""Make the documented ``JAX_PLATFORMS`` env contract actually hold.

Some environments boot JAX from ``sitecustomize`` and pin the platform list
via ``jax.config.update("jax_platforms", ...)`` — which silently overrides
the ``JAX_PLATFORMS`` environment variable the docs (and the reference-style
single-machine workflow, SURVEY.md §4.5) tell users to set. Calling
:func:`apply_platform_env` before the first backend access re-asserts the
env var so e.g. ``JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8 bibfs-solve --backend
sharded --devices 8`` works everywhere.
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import sys

    # Only act when something (the sitecustomize boot) already imported jax
    # and may have pinned the config; otherwise the env var will be honored
    # at import time naturally, and serial/native-only runs stay jax-free.
    if "jax" not in sys.modules:
        return
    import jax

    if jax.config.jax_platforms != plat:
        jax.config.update("jax_platforms", plat)
