"""Platform selection that survives sitecustomize boots.

Some environments boot JAX from ``sitecustomize`` and pin the platform list
via ``jax.config.update("jax_platforms", ...)`` — which silently overrides
the ``JAX_PLATFORMS`` environment variable the docs (and the reference-style
single-machine workflow, SURVEY.md §4.5) tell users to set. Worse, the pinned
backend may be a tunneled accelerator whose init hangs for minutes; a test or
dry-run that was supposed to use the virtual CPU mesh then stalls on the very
first ``jax.devices()``.

Two entry points:

- :func:`apply_platform_env` — re-assert the ``JAX_PLATFORMS`` env var over
  any config pin, whether or not jax is imported yet.
- :func:`force_cpu` — unconditionally route this process to the host CPU
  platform with ``n_devices`` virtual devices (the moral equivalent of the
  reference's ``mpirun -n 4`` single-machine fake cluster,
  single_machine_bench.sh:9,52). Safe to call before OR after jax import;
  must be called before the first backend access to take effect.
"""

from __future__ import annotations

import os
import sys


def _set_host_device_count_flag(n_devices: int) -> None:
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    token = "--xla_force_host_platform_device_count"
    if token in flags:
        # replace any stale count (e.g. =1 left by an earlier smoke run)
        flags = re.sub(rf"{token}=\d+", f"{token}={n_devices}", flags)
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = f"{flags} {token}={n_devices}".strip()


def force_cpu(n_devices: int = 1) -> None:
    """Route this process to ``n_devices`` virtual CPU devices, robustly.

    Works in every boot configuration:
    - jax not imported yet: env vars alone are honored at import time;
    - jax imported by a sitecustomize boot that pinned ``jax_platforms``:
      ``jax.config.update`` re-pins before the first backend init;
    - jax 0.5+ exposes ``jax_num_cpu_devices``, which (unlike ``XLA_FLAGS``)
      also applies when the flag env var was already consumed.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    _set_host_device_count_flag(n_devices)
    if "jax" not in sys.modules:
        return
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:  # older jax: XLA_FLAGS path covers it
        pass
    except RuntimeError:
        # backends already initialized (jax raises "...should be updated
        # before backends are initialized") — too late to change the device
        # count in-process; leave whatever is live rather than crash the
        # caller. Callers needing a guaranteed fresh mesh must call
        # force_cpu before any backend access (or use a subprocess).
        pass


def apply_platform_env() -> None:
    """Make the documented ``JAX_PLATFORMS`` env contract actually hold."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    if "jax" not in sys.modules:
        # Honored naturally at import time; nothing pinned yet.
        return
    import jax

    if jax.config.jax_platforms != plat:
        jax.config.update("jax_platforms", plat)
