"""Hardware calibration — turns the solver's tuning constants into
committed, reproducible measurements.

Round 1 shipped three "measured" claims as comments (push/pull crossover
K ~ n_pad/200, ~100ms fresh-arg dispatch stall, gather throughput); this
module measures them on the machine it runs on and persists the result to
``calibration.json``, keyed by device platform. The dense solver's
``_auto_push_cap`` (bibfs_tpu/solvers/dense.py) reads the calibrated
crossover when present, so the Beamer push/pull routing threshold is a
per-hardware fact, not a guess.

Run via ``python bench.py --calibrate`` (writes ``calibration.json`` at the
repo root) or programmatically with :func:`run_calibration`.

What is measured (all medians over repeats, jit-compiled, blocked):

- ``dispatch_cached_us`` / ``dispatch_fresh_us``: one jitted no-op level
  with a cache-reused vs freshly created device scalar argument — the
  tunneled-TPU dispatch stall behind ``_device_scalar``'s cache.
- ``pull_level_us``: amortized cost of one pull level over the n=100k ELL
  table, measured INSIDE a ``lax.while_loop`` of 32 levels (divided by
  32), plus the implied gather throughput in elements/us.
- ``push_level_us``: amortized in-loop cost of one push claim phase at
  each candidate cap K — cost scales with K*width, independent of n.
- ``push_cap``: the largest measured K whose push level is still cheaper
  than the pull level — the Beamer crossover. ``push_cap_divisor`` =
  n_pad // push_cap generalizes it to other graph sizes.

Two further fields are BANKED measurements rather than ones this module
re-runs (the round-5 batch A/B sweep, solvers/batch_minor.py table +
PERF_NOTES.md §3, is a multi-minute device campaign):

- ``batch_crossover``: the measured batch size at which the batched
  device path starts beating per-query dispatch (round-5 A/B: B ~= 32).
  ``batch_minor.small_batch_threshold`` and the serving engine's
  micro-batcher (bibfs_tpu/serve/engine.py) route on it.
- ``batch_flat``: the batch size by which per-query cost has flattened
  to its asymptote (round-5 sweep: ~256) — the serve bench's default
  queue depth.

:func:`write_calibration` MERGES the fresh entry over any existing one,
so re-calibrating never drops these banked fields.

Two methodology rules, both consequences of measured runtime behavior
(full account in bibfs_tpu/solvers/timing.py):

- every measured call FORCES execution with a value read — on the tunneled
  TPU runtime ``block_until_ready`` returns without waiting, so un-forced
  loops time the enqueue, not the work;
- levels are measured INSIDE a ``lax.while_loop`` (amortized over 32
  iterations) rather than as standalone jitted calls, because that is
  where the solver runs them and per-dispatch overhead would otherwise
  swamp the per-level cost being compared.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache

import numpy as np

CAL_ENV = "BIBFS_CALIBRATION"
CAL_FILENAME = "calibration.json"

#: a cached-arg dispatch slower than this means the calibrating probe
#: itself was degraded (PR 16's committed tpu block recorded 66747.8 µs
#: — a tunneled backend timing out on metadata retries, not a healthy
#: device) — :func:`load_calibration` REFUSES such a block: consumers
#: get None and fall back to their uncalibrated defaults, with one
#: visible warning per platform and every refusal counted in
#: :data:`degraded_refusals`
DEGRADED_DISPATCH_US = 1000.0
_warned_degraded: set = set()
#: per-platform count of load_calibration calls that refused a
#: degraded block this process — tests and health surfaces read it to
#: prove the fallback actually fired (it is a running total, not a
#: latch like the warning)
degraded_refusals: dict = {}
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _median_us(fn, repeats: int) -> float:
    """Median wall-clock of ``fn`` in us under the shared forced-execution
    protocol (one place owns that workaround: solvers/timing.py)."""
    from bibfs_tpu.solvers.timing import force_scalar, timed_repeats

    times, _ = timed_repeats(fn, None, repeats, force=force_scalar)
    return float(np.median(times) * 1e6)


def run_calibration(
    n: int = 100_000, avg_deg: float = 2.2, seed: int = 1, repeats: int = 30
) -> dict:
    """Measure the tuning constants on the current default backend and
    return the calibration entry (see module docstring for fields)."""
    import jax
    import jax.numpy as jnp

    from bibfs_tpu.graph.csr import build_ell
    from bibfs_tpu.graph.generate import gnp_random_graph
    from bibfs_tpu.ops.expand import _push_claim, expand_pull
    from bibfs_tpu.solvers.dense import INF32

    platform = jax.devices()[0].platform
    edges = gnp_random_graph(n, avg_deg / n, seed=seed)
    g = build_ell(n, edges, pad_multiple=8)
    nbr = jax.device_put(g.nbr)
    deg = jax.device_put(g.deg)
    width = g.width

    # --- dispatch stall: cached vs fresh device-scalar argument ---------
    def dispatch_probe(d, s):
        return d[s] + 1

    noop = jax.jit(dispatch_probe)
    cached = jnp.int32(3)
    dispatch_cached_us = _median_us(lambda: noop(deg, cached), repeats)
    # a FRESH eager scalar per call is exactly what _device_scalar avoids
    dispatch_fresh_us = _median_us(
        lambda: noop(deg, jnp.int32(int(np.random.default_rng(0).integers(4)))),
        repeats,
    )

    # --- amortized IN-LOOP level costs (module docstring: standalone
    # dispatch of the same computations is wildly unrepresentative on
    # tunneled backends) ------------------------------------------------
    levels = 32
    rng = np.random.default_rng(seed)
    frontier = jax.device_put(rng.random(g.n_pad) < 0.02)
    visited = jax.device_put(rng.random(g.n_pad) < 0.1)

    @jax.jit
    def pull_loop(fr, vis):
        def body(c):
            i, fr = c
            # perturb one element so the level cannot be hoisted out of
            # the loop as loop-invariant; cost: one 1-element scatter
            fr = fr.at[i % g.n_pad].set(i % 2 == 0)
            nf, _par = expand_pull(fr, vis, nbr, deg)
            return i + 1, nf

        return jax.lax.while_loop(lambda c: c[0] < levels, body, (0, fr))

    pull_level_us = (
        _median_us(lambda: pull_loop(frontier, visited), repeats) / levels
    )
    gather_elems_per_us = g.n_pad * width / pull_level_us

    dist0 = jax.device_put(
        np.where(rng.random(g.n_pad) < 0.1, 1, INF32).astype(np.int32)
    )
    par0 = jax.device_put(np.full(g.n_pad, -1, dtype=np.int32))

    def push_at(k):
        fidx0 = jax.device_put(
            rng.choice(g.n_pad, size=k, replace=False).astype(np.int32)
        )

        @jax.jit
        def push_loop(fidx, par, dist):
            def body(c):
                i, fidx, par, dist = c
                fidx = (fidx + 1) % g.n_pad  # iteration-dependent targets
                rows = nbr[fidx]
                valid = (
                    jnp.arange(width, dtype=jnp.int32)[None, :]
                    < deg[fidx][:, None]
                )
                _nf, _nfi, _cnt, par, dist, _sc, _md = _push_claim(
                    fidx, rows, valid, jnp.int32(0), par, dist, deg,
                    i.astype(jnp.int32), inf=INF32,
                )
                return i + 1, fidx, par, dist

            return jax.lax.while_loop(
                lambda c: c[0] < levels, body, (0, fidx, par, dist)
            )

        return (
            _median_us(lambda: push_loop(fidx0, par0, dist0), repeats) / levels
        )

    push_level_us = {}
    push_cap = 0
    for k in (128, 256, 512, 1024, 2048, 4096):
        if k > g.n_pad:
            break
        push_level_us[str(k)] = round(push_at(k), 2)
        if push_level_us[str(k)] < pull_level_us:
            push_cap = k

    import datetime
    import platform as _platform

    entry = {
        # provenance stamp: consumers can tell a fresh measurement from
        # a stale banked block (the degraded-probe warning below names
        # it); pre-stamp blocks simply lack the field
        "measured_on": {
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc
            ).strftime("%Y-%m-%dT%H:%M:%SZ"),
            "machine": _platform.node(),
        },
        "n_pad": g.n_pad,
        "width": width,
        "repeats": repeats,
        "levels_per_measure": levels,
        "dispatch_cached_us": round(dispatch_cached_us, 1),
        "dispatch_fresh_us": round(dispatch_fresh_us, 1),
        "pull_level_us": round(pull_level_us, 2),
        "gather_elems_per_us": round(gather_elems_per_us, 1),
        "push_level_us": push_level_us,
        "push_cap": push_cap,
        "push_cap_divisor": (g.n_pad // push_cap) if push_cap else None,
    }
    return {"platform": platform, "entry": entry}


def write_calibration(path: str | None = None, **kwargs) -> dict:
    """Run and merge into ``calibration.json`` (platform-keyed)."""
    path = path or os.path.join(_REPO_ROOT, CAL_FILENAME)
    result = run_calibration(**kwargs)
    data = {}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    # merge over the existing platform entry: banked fields produced
    # elsewhere (batch_crossover/batch_flat — the round-5 device sweep)
    # must survive a re-run of the local measurements
    data[result["platform"]] = {
        **data.get(result["platform"], {}), **result["entry"]
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    _read_calibration_file.cache_clear()
    return data


def merge_calibration_block(platform: str, key: str, entry: dict,
                            path: str | None = None) -> dict:
    """Merge one named sub-block (e.g. the mesh crossover constants)
    into a platform's calibration entry — the same read-modify-write-
    and-invalidate protocol as :func:`write_calibration`, kept HERE so
    external writers (``bench.py --serve-mesh``) cannot drift from the
    file's merge semantics."""
    path = path or os.path.join(_REPO_ROOT, CAL_FILENAME)
    data = {}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    plat = data.setdefault(platform, {})
    plat[key] = {**plat.get(key, {}), **entry}
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    _read_calibration_file.cache_clear()
    return data


@lru_cache(maxsize=None)
def _read_calibration_file() -> dict:
    path = os.environ.get(CAL_ENV)
    candidates = [path] if path else [
        os.path.join(os.getcwd(), CAL_FILENAME),
        os.path.join(_REPO_ROOT, CAL_FILENAME),
    ]
    for cand in candidates:
        if cand and os.path.exists(cand):
            try:
                with open(cand) as f:
                    return json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
    return {}


def load_calibration() -> dict | None:
    """The calibration entry for the CURRENT default backend's platform, or
    None when absent — callers fall back to their uncalibrated heuristics.
    Never initializes a backend on its own: returns None if jax has not
    been imported yet (calibration only matters once a solver is running,
    by which point the backend exists). Uncached on purpose: the file read
    behind it is cached, and the platform lookup must track whichever
    backend the caller ended up on."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    data = _read_calibration_file()
    if not data:
        return None
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:
        return None
    entry = data.get(platform)
    if entry is not None and _refuse_if_degraded(platform, entry):
        return None
    return entry


def _refuse_if_degraded(platform: str, entry: dict) -> bool:
    """True when the block was measured by a clearly-degraded probe
    (:data:`DEGRADED_DISPATCH_US` — e.g. a 66.7 ms cached dispatch is a
    tunneled backend stalling, and every constant derived from that
    session inherits the stall). A degraded block is REFUSED, not
    merely flagged: :func:`load_calibration` returns None so consumers
    take their uncalibrated defaults — exact answers tuned by
    heuristics beat exact answers tuned by junk. Every refusal is
    counted in :data:`degraded_refusals`; the warning prints once per
    platform."""
    try:
        cached = float(entry.get("dispatch_cached_us", 0.0))
    except (TypeError, ValueError):
        return False
    if cached <= DEGRADED_DISPATCH_US:
        return False
    degraded_refusals[platform] = degraded_refusals.get(platform, 0) + 1
    if platform in _warned_degraded:
        return True
    _warned_degraded.add(platform)
    import sys

    stamp = entry.get("measured_on")
    print(
        f"warning: REFUSING calibration block for platform "
        f"{platform!r}: measured on a degraded substrate "
        f"(dispatch_cached_us={cached:.1f} > "
        f"{DEGRADED_DISPATCH_US:.0f}; measured_on="
        f"{stamp if stamp else 'unstamped'}) — falling back to "
        "uncalibrated defaults; re-run `python bench.py --calibrate` "
        "on healthy hardware",
        file=sys.stderr,
    )
    return True
