"""Deviceless TPU compilation — the round-4 unlock.

``libtpu`` ships in this environment even though no local chip exists
(the bench chip is behind a flaky tunnel). JAX's topology API drives
libtpu's compiler WITHOUT any device: build an abstract v5e topology,
shard abstract avals onto its devices, and ``jit(...).lower(...).
compile()`` runs the FULL XLA:TPU + Mosaic pipeline — including the
Mosaic kernel compile that rounds 2-4 could otherwise only attempt
through the tunnel. This is how round 4 discovered that
``tpu.dynamic_gather`` only lowers single-vreg gathers (the round-3
kernel formulation never compiled) and validated the v2 fused kernel
offline (see PERF_NOTES and the mosaic notes in ops/pallas_fused.py).

The compiled executable cannot RUN here (no device) — runtime behavior
still needs the chip — but "does it compile for TPU" is now a local,
seconds-fast question instead of a tunnel lottery.
"""

from __future__ import annotations

from functools import lru_cache

import jax


@lru_cache(maxsize=1)
def tpu_topology(name: str = "v5e:2x2"):
    """The abstract TPU topology, or None when libtpu / the topology API
    is unavailable (then AOT checks are skipped, not failed)."""
    try:
        from jax.experimental import topologies

        return topologies.get_topology_desc(platform="tpu", topology_name=name)
    except Exception:
        return None


def aot_available() -> bool:
    return tpu_topology() is not None


def aot_compile_tpu(fn, *args) -> tuple[bool, str | None]:
    """Deviceless full-TPU compile of ``jit(fn)(*args)``. ``args`` may be
    concrete arrays or ShapeDtypeStructs; they are re-speced onto the
    abstract topology's first device. Returns ``(ok, error_message)`` —
    the error preserves the Mosaic diagnostic, which names the exact
    unsupported op when a kernel does not lower."""
    topo = tpu_topology()
    if topo is None:
        return False, "TPU topology API unavailable (no libtpu?)"
    sds = jax.sharding.SingleDeviceSharding(topo.devices[0])

    def spec(x):
        if isinstance(x, tuple):
            return tuple(spec(v) for v in x)
        import numpy as np

        a = np.asarray(x) if not hasattr(x, "shape") else x
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sds)

    try:
        # the kernels' interpret flags resolve from default_backend() at
        # TRACE time; on this CPU host that would select interpret mode
        # and skip Mosaic entirely — pin the branch the TPU would take
        from unittest import mock

        with mock.patch.object(jax, "default_backend", lambda: "tpu"):
            jax.jit(fn).lower(*(spec(a) for a in args)).compile()
        return True, None
    except Exception as e:
        return False, f"{type(e).__name__}: {e}"
