"""Deviceless TPU compilation — the round-4 unlock.

``libtpu`` ships in this environment even though no local chip exists
(the bench chip is behind a flaky tunnel). JAX's topology API drives
libtpu's compiler WITHOUT any device: build an abstract v5e topology,
shard abstract avals onto its devices, and ``jit(...).lower(...).
compile()`` runs the FULL XLA:TPU + Mosaic pipeline — including the
Mosaic kernel compile that rounds 2-4 could otherwise only attempt
through the tunnel. This is how round 4 discovered that
``tpu.dynamic_gather`` only lowers single-vreg gathers (the round-3
kernel formulation never compiled) and validated the v2 fused kernel
offline (see PERF_NOTES and the mosaic notes in ops/pallas_fused.py).

The compiled executable cannot RUN here (no device) — runtime behavior
still needs the chip — but "does it compile for TPU" is now a local,
seconds-fast question instead of a tunnel lottery.
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax


@lru_cache(maxsize=1)
def tpu_topology(name: str = "v5e:2x2"):
    """The abstract TPU topology, or None when libtpu / the topology API
    is unavailable (then AOT checks are skipped, not failed)."""
    # deviceless compile needs no cloud metadata, but libtpu init probes
    # the GCP metadata server for worker identity with 30 retries per
    # variable — measured ~460 s of pure stall on the first topology
    # touch off-GCP. Give the probe inert identity defaults ONLY for the
    # duration of the topology construction, then restore the
    # environment: leaking them (os.environ is inherited by every
    # subprocess, e.g. the chip-session legs) would force a wrong
    # accelerator type / worker identity onto a real multi-host TPU
    # init. Explicit pre-set values always win (setdefault semantics).
    inert = {
        "TPU_SKIP_MDS_QUERY": "1",
        "TPU_ACCELERATOR_TYPE": "v5litepod-4",
        "TPU_WORKER_ID": "0",
        "TPU_WORKER_HOSTNAMES": "localhost",
    }
    added = [k for k in inert if k not in os.environ]
    for k in added:
        os.environ[k] = inert[k]
    try:
        from jax.experimental import topologies

        return topologies.get_topology_desc(platform="tpu", topology_name=name)
    except Exception:
        return None
    finally:
        for k in added:
            os.environ.pop(k, None)


def aot_available() -> bool:
    return tpu_topology() is not None


def aot_compile_tpu(fn, *args) -> tuple[bool, str | None]:
    """Deviceless full-TPU compile of ``jit(fn)(*args)``. ``args`` may be
    concrete arrays or ShapeDtypeStructs; they are re-speced onto the
    abstract topology's first device. Returns ``(ok, error_message)`` —
    the error preserves the Mosaic diagnostic, which names the exact
    unsupported op when a kernel does not lower."""
    topo = tpu_topology()
    if topo is None:
        return False, "TPU topology API unavailable (no libtpu?)"
    sds = jax.sharding.SingleDeviceSharding(topo.devices[0])

    def spec(x):
        if isinstance(x, tuple):
            return tuple(spec(v) for v in x)
        import numpy as np

        a = np.asarray(x) if not hasattr(x, "shape") else x
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sds)

    try:
        # the kernels' interpret flags resolve from default_backend() at
        # TRACE time; on this CPU host that would select interpret mode
        # and skip Mosaic entirely — pin the branch the TPU would take
        from unittest import mock

        with mock.patch.object(jax, "default_backend", lambda: "tpu"):
            jax.jit(fn).lower(*(spec(a) for a in args)).compile()
        return True, None
    except Exception as e:
        return False, f"{type(e).__name__}: {e}"
