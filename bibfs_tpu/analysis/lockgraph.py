"""Dynamic lock-order race detector (``BIBFS_LOCK_CHECK=1``).

The static lints prove lexical discipline; what they cannot prove is
the GLOBAL acquisition order across 15 modules' worth of locks — the
property whose violations surfaced as review-round deadlock arguments
in PRs 5-8 (store lock vs WAL writer, replica lock vs reader thread,
engine condvar vs runtime lock). This module proves it dynamically, on
the real test suite:

- :func:`install` monkeypatches ``threading.Lock`` / ``RLock`` /
  ``Condition`` so that every lock **created from bibfs_tpu source**
  (the creation site decides — third-party and interpreter-internal
  locks stay raw and untaxed) is wrapped in an instrumented primitive
  that records, per thread, the stack of currently held locks.
- Every acquisition while other instrumented locks are held records a
  directed edge ``held -> acquiring`` (first-observation acquisition
  stack kept per edge) in one process-global graph. A **new edge that
  closes a cycle raises** :class:`LockOrderError` *before* the inner
  acquire — fail-fast with both acquisition stacks printed, and no
  half-taken lock leaked — and the cycle is also recorded in the
  report, so a cycle raised inside a swallow-and-count background
  thread (a compaction job) still fails the session gate.
- Blocking primitives (``os.fsync``, ``time.sleep``,
  ``subprocess.Popen``) are wrapped to record a **blocking-under-lock
  event** whenever called with instrumented locks held — the dynamic
  counterpart of the ``lock-io`` lint, catching what lexical analysis
  cannot see through call indirection.

Wiring: ``tests/conftest.py`` installs this when ``BIBFS_LOCK_CHECK=1``
*before* the serving modules import, so the whole serving suite doubles
as the race harness, and writes the JSON report
(``BIBFS_LOCK_REPORT``, default ``lockgraph.json``) at session end —
failing the session if any cycle was recorded. ``bibfs-lint
--lock-report FILE`` renders the artifact for humans.

Condition support: an instrumented RLock implements the
``_release_save`` / ``_acquire_restore`` / ``_is_owned`` protocol, so
``threading.Condition(instrumented_rlock)`` waits release (and their
re-acquisition re-records order edges) exactly like the raw primitive.
RLock re-entry by the owning thread records nothing — only the first
acquisition orders.

Soundness note: edges are recorded for every acquisition *attempt*
(including non-blocking ``acquire(False)``), which over-approximates —
a try-lock protocol that tolerates inversion by design would need its
edge suppressed here. The codebase has none; prefer keeping it that
way.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import traceback
import _thread

_REPO_MARKER = os.sep + "bibfs_tpu" + os.sep

# originals captured once, before any patching
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition
_ORIG_FSYNC = os.fsync
_ORIG_SLEEP = None  # captured at install (time may be patched by tests)
_ORIG_POPEN = None

_STATE: "LockGraph | None" = None
_STACK_LIMIT = 18


class LockOrderError(RuntimeError):
    """A lock acquisition would close a cycle in the global
    acquisition-order graph — a latent deadlock."""


def _site(depth: int = 2) -> str:
    """``file.py:line`` of the instrumenting caller, repo-relative."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return "?"
    fn = frame.f_code.co_filename
    i = fn.rfind(_REPO_MARKER)
    if i >= 0:
        fn = fn[i + 1:]
    return f"{fn}:{frame.f_lineno}"


def _in_scope(depth: int = 2) -> bool:
    return _REPO_MARKER in sys._getframe(depth).f_code.co_filename


def _stack() -> list:
    """The current acquisition stack, repo-trimmed and bounded."""
    out = []
    for fr in traceback.extract_stack(limit=_STACK_LIMIT + 6)[:-3]:
        fn = fr.filename
        i = fn.rfind(_REPO_MARKER)
        if i >= 0:
            fn = fn[i + 1:]
        out.append(f"{fn}:{fr.lineno} in {fr.name}")
    return out[-_STACK_LIMIT:]


class LockGraph:
    """The process-global acquisition-order graph (module docstring)."""

    def __init__(self):
        # raw primitives only: the detector must never recurse into
        # itself, and its mutex must never join the graph it guards
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()
        self._seq = 0
        self._locks: dict[int, dict] = {}      # gid -> {site, kind}
        self._edges: dict[tuple, dict] = {}    # (a,b) -> edge record
        self._adj: dict[int, set] = {}         # a -> {b}
        self._cycles: list[dict] = []
        self._blocking: dict[tuple, dict] = {}  # dedup key -> event

    # ---- bookkeeping --------------------------------------------------
    def _register(self, kind: str, site: str) -> int:
        with self._mu:
            self._seq += 1
            gid = self._seq
            self._locks[gid] = {"id": gid, "kind": kind, "site": site,
                                "acquisitions": 0}
            return gid

    def _held(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _path(self, src: int, dst: int):
        """Edge path src -> ... -> dst in the current graph, or None."""
        stack = [(src, ())]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + ((node, nxt),)))
        return None

    def note_acquire(self, lock) -> None:
        """Record order edges for one impending acquisition; raises
        :class:`LockOrderError` (before the caller blocks on the inner
        primitive) when a new edge closes a cycle."""
        held = self._held()
        self._locks[lock._gid]["acquisitions"] += 1
        if not held:
            return
        for holder in held:
            if holder is lock:
                # a re-probe of an already-held lock is not an order
                # edge: Condition's stdlib _is_owned fallback probes
                # acquire(False) on the very lock the thread holds, and
                # a (gid, gid) self-edge would read as a cycle
                continue
            key = (holder._gid, lock._gid)
            edge = self._edges.get(key)
            if edge is not None:
                edge["count"] += 1
                continue
            with self._mu:
                if key in self._edges:
                    self._edges[key]["count"] += 1
                    continue
                back = self._path(lock._gid, holder._gid)
                self._edges[key] = {
                    "from": holder._gid,
                    "to": lock._gid,
                    "count": 1,
                    "thread": threading.current_thread().name,
                    "stack": _stack(),
                }
                self._adj.setdefault(holder._gid, set()).add(lock._gid)
                if back is None:
                    continue
                cycle_edges = [self._edge_info(a, b) for a, b in back]
                cycle_edges.append(self._edge_info(*key))
                record = {
                    "closing_edge": self._edge_info(*key),
                    "cycle": cycle_edges,
                }
                self._cycles.append(record)
            raise LockOrderError(self._format_cycle(record))

    def _edge_info(self, a: int, b: int) -> dict:
        e = self._edges[(a, b)]
        return {
            "from": self._locks[a]["site"],
            "to": self._locks[b]["site"],
            "count": e["count"],
            "thread": e["thread"],
            "stack": e["stack"],
        }

    def _format_cycle(self, record: dict) -> str:
        lines = ["lock-order cycle detected (latent deadlock):"]
        for e in record["cycle"]:
            lines.append(f"  {e['from']}  ->  {e['to']}   "
                         f"[thread {e['thread']}, seen x{e['count']}]")
            for fr in e["stack"]:
                lines.append(f"      {fr}")
        lines.append("every lock pair must be acquired in one global "
                     "order; one of the stacks above must move")
        return "\n".join(lines)

    def push_held(self, lock) -> None:
        self._held().append(lock)

    def pop_held(self, lock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def note_blocking(self, label: str) -> None:
        held = getattr(self._tls, "stack", None)
        if not held:
            return
        # attribute to the innermost repo frame (the wrapped primitive
        # may be reached through stdlib indirection, e.g. Popen via
        # subprocess.run)
        site = _site(3)
        try:
            frame = sys._getframe(3)
        except ValueError:
            frame = None
        while frame is not None:
            fn = frame.f_code.co_filename
            if _REPO_MARKER in fn:
                i = fn.rfind(_REPO_MARKER)
                site = f"{fn[i + 1:]}:{frame.f_lineno}"
                break
            frame = frame.f_back
        locks = tuple(sorted({h.site for h in held}))
        key = (label, site, locks)
        with self._mu:
            ev = self._blocking.get(key)
            if ev is not None:
                ev["count"] += 1
                return
            self._blocking[key] = {
                "call": label,
                "site": site,
                "held": list(locks),
                "count": 1,
                "thread": threading.current_thread().name,
                "stack": _stack(),
            }

    # ---- reporting ----------------------------------------------------
    def cycles(self) -> list:
        with self._mu:
            return list(self._cycles)

    def report(self) -> dict:
        """The JSON artifact, aggregated by creation SITE: the graph is
        tracked per lock instance (cycle precision — two engines' locks
        must not alias), but per-site aggregation is what a human (and
        a stable committed artifact) wants: one row per lock-creation
        site, one row per ordered site pair."""
        with self._mu:
            locks: dict[str, dict] = {}
            for info in self._locks.values():
                row = locks.setdefault(info["site"], {
                    "site": info["site"], "kind": info["kind"],
                    "instances": 0, "acquisitions": 0,
                })
                row["instances"] += 1
                row["acquisitions"] += info["acquisitions"]
            edges: dict[tuple, dict] = {}
            for (a, b), e in self._edges.items():
                key = (self._locks[a]["site"], self._locks[b]["site"])
                row = edges.get(key)
                if row is None:
                    edges[key] = {
                        "from": key[0], "to": key[1],
                        "count": e["count"],
                        "thread": e["thread"],
                        "stack": e["stack"],
                    }
                else:
                    row["count"] += e["count"]
            blocking = sorted(self._blocking.values(),
                              key=lambda e: (e["call"], e["site"]))
            return {
                "schema": "bibfs-lockgraph-v1",
                "locks": sorted(locks.values(), key=lambda r: r["site"]),
                "edges": sorted(edges.values(),
                                key=lambda r: (r["from"], r["to"])),
                "cycles": list(self._cycles),
                "blocking_under_lock": blocking,
            }


class _Instrumented:
    """Shared plumbing for the wrapped primitives."""

    def __init__(self, inner, graph: LockGraph, kind: str, site: str):
        self._inner = inner
        self._graph = graph
        self.site = site
        self._gid = graph._register(kind, site)

    def locked(self):
        return self._inner.locked()

    def __repr__(self):
        return f"<{type(self).__name__} {self.site}>"


class InstrumentedLock(_Instrumented):
    def __init__(self, graph, site):
        super().__init__(_ORIG_LOCK(), graph, "Lock", site)

    def acquire(self, blocking=True, timeout=-1):
        self._graph.note_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._graph.push_held(self)
        return got

    def release(self):
        self._graph.pop_held(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class InstrumentedRLock(_Instrumented):
    def __init__(self, graph, site):
        super().__init__(_ORIG_RLOCK(), graph, "RLock", site)
        self._owner = None
        self._depth = 0

    def acquire(self, blocking=True, timeout=-1):
        me = _thread.get_ident()
        if self._owner == me:
            self._inner.acquire(blocking, timeout)
            self._depth += 1
            return True
        self._graph.note_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._depth = 1
            self._graph.push_held(self)
        return got

    def release(self):
        if self._owner != _thread.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
            self._graph.pop_held(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition protocol — threading.Condition lifts these from a
    # custom lock so cv.wait() fully releases and restores re-entrant
    # holds; the bookkeeping must mirror the real release/acquire
    def _release_save(self):
        depth, self._depth = self._depth, 0
        self._owner = None
        self._graph.pop_held(self)
        state = self._inner._release_save()
        return (state, depth)

    def _acquire_restore(self, saved):
        state, depth = saved
        self._graph.note_acquire(self)
        self._inner._acquire_restore(state)
        self._owner = _thread.get_ident()
        self._depth = depth
        self._graph.push_held(self)

    def _is_owned(self):
        return self._inner._is_owned()

    def locked(self):
        return self._inner._is_owned() or self._owner is not None


# ---- installation -----------------------------------------------------
def _patched_lock():
    if _STATE is not None and _in_scope():
        return InstrumentedLock(_STATE, _site())
    return _ORIG_LOCK()


def _patched_rlock():
    if _STATE is not None and _in_scope():
        return InstrumentedRLock(_STATE, _site())
    return _ORIG_RLOCK()


def _patched_condition(lock=None):
    if lock is None and _STATE is not None and _in_scope():
        lock = InstrumentedRLock(_STATE, _site())
    return _ORIG_CONDITION(lock)


def _wrap_blocking(label, orig):
    def wrapped(*args, **kwargs):
        state = _STATE
        if state is not None:
            state.note_blocking(label)
        return orig(*args, **kwargs)

    wrapped.__name__ = getattr(orig, "__name__", label)
    return wrapped


def install() -> LockGraph:
    """Activate the detector process-wide (idempotent). Must run before
    the modules under test construct their locks — conftest wires it at
    import time, ahead of any serving import."""
    global _STATE, _ORIG_SLEEP, _ORIG_POPEN
    if _STATE is not None:
        return _STATE
    _STATE = LockGraph()
    threading.Lock = _patched_lock
    threading.RLock = _patched_rlock
    threading.Condition = _patched_condition
    import subprocess
    import time

    _ORIG_SLEEP = time.sleep
    _ORIG_POPEN = subprocess.Popen
    os.fsync = _wrap_blocking("os.fsync", _ORIG_FSYNC)
    time.sleep = _wrap_blocking("time.sleep", _ORIG_SLEEP)
    subprocess.Popen = _wrap_blocking("subprocess.Popen", _ORIG_POPEN)
    return _STATE


def enabled() -> bool:
    return _STATE is not None


def graph() -> LockGraph | None:
    return _STATE


def cycles() -> list:
    return [] if _STATE is None else _STATE.cycles()


def save_report(path: str) -> dict:
    """Write the JSON artifact (the committed ``lockgraph.json`` shape)
    and return the report dict. Safe to call with the detector off
    (writes an empty report)."""
    rep = (
        _STATE.report() if _STATE is not None
        else {"schema": "bibfs-lockgraph-v1", "locks": [], "edges": [],
              "cycles": [], "blocking_under_lock": []}
    )
    # graph/io's one atomic-commit idiom (flush + fsync + replace): the
    # --lock-report CI step parses this artifact, and a teardown crash
    # mid-write must leave the previous complete report, never a torn
    # one — the bare tmp+replace this used to hand-roll skipped the
    # fsync, exactly the divergence _atomic_replace exists to end
    from bibfs_tpu.graph.io import _atomic_replace

    def _payload(f):
        json.dump(rep, f, indent=1, sort_keys=True)
        f.write("\n")

    _atomic_replace(path, _payload, mode="w")
    return rep


# ---- renderer (bibfs-lint --lock-report) ------------------------------
def render_report(rep: dict) -> tuple[str, bool]:
    """Human-readable rendering of a report dict; ``ok`` is False when
    the run recorded lock-order cycles."""
    lines = []
    locks = rep.get("locks", [])
    edges = rep.get("edges", [])
    cyc = rep.get("cycles", [])
    blocking = rep.get("blocking_under_lock", [])
    lines.append(
        f"lock graph: {len(locks)} instrumented locks, "
        f"{len(edges)} order edges, {len(cyc)} cycles, "
        f"{len(blocking)} blocking-under-lock sites"
    )
    lines.append("")
    lines.append("acquisition order (held -> acquired):")
    for e in edges:
        lines.append(f"  {e['from']}  ->  {e['to']}   x{e['count']}"
                     f"   [{e['thread']}]")
    if blocking:
        lines.append("")
        lines.append("blocking calls under a held lock "
                     "(deliberate trades show up here too — compare "
                     "against the lock-io allowlist):")
        for ev in blocking:
            held = ", ".join(ev["held"])
            lines.append(f"  {ev['call']} at {ev['site']}   "
                         f"x{ev['count']}   holding [{held}]")
    if cyc:
        lines.append("")
        lines.append("CYCLES (latent deadlocks — the build gate fails):")
        for rec in cyc:
            for e in rec["cycle"]:
                lines.append(f"  {e['from']}  ->  {e['to']}")
                for fr in e["stack"]:
                    lines.append(f"      {fr}")
            lines.append("  ----")
    return "\n".join(lines), not cyc


def render_report_file(path: str) -> tuple[str, bool]:
    with open(path) as f:
        rep = json.load(f)
    return render_report(rep)
