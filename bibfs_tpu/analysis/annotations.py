"""``@guarded_by`` — declare which lock protects which shared attributes.

The serving stack's locking discipline was previously documented only
in comments ("mutated under the engine lock or on the single finish
worker"); this annotation makes it declarative and machine-checkable:

    @guarded_by("_table_lock", "_states", "_versions")
    class Router: ...

reads "``self._states`` and ``self._versions`` may only be MUTATED
inside a ``with self._table_lock:`` block". The ``guarded-by`` lint
rule (:mod:`bibfs_tpu.analysis.rules.guarded_by`) enforces it
statically, with two deliberate exemptions matching the codebase's
conventions:

- ``__init__``/``__new__`` — construction happens-before publication;
- methods named ``*_locked`` — the existing callee-holds-the-lock
  naming convention (``_write_manifest_locked``, ``_swap_locked``, ...).

The first argument may be a tuple of names when several attributes
alias ONE lock (the pipelined engine's ``_lock`` / ``_cv`` pair — the
Condition wraps the same RLock). Lock-free READS remain legal (and are
load-bearing on the hot paths: GIL-atomic snapshot reads are a
documented idiom here); the rule checks mutations only.

At runtime the decorator is inert beyond attaching metadata
(``__bibfs_guarded_by__``: attr -> tuple of guard names, merged down
the MRO) for introspection and tests.
"""

from __future__ import annotations


def guarded_by(lock, *attrs):
    """Class decorator: ``attrs`` are mutated only under ``self.<lock>``
    (``lock`` may be a tuple of aliases for the same underlying lock).
    Stackable — each application merges into the class metadata."""
    guards = (lock,) if isinstance(lock, str) else tuple(lock)
    if not guards or not all(isinstance(g, str) for g in guards):
        raise TypeError("guarded_by needs a lock attribute name (or a "
                        "tuple of alias names)")
    if not attrs or not all(isinstance(a, str) for a in attrs):
        raise TypeError("guarded_by needs at least one guarded "
                        "attribute name")

    def deco(cls):
        merged = {}
        for base in reversed(cls.__mro__[1:]):
            merged.update(getattr(base, "__bibfs_guarded_by__", {}))
        merged.update(cls.__dict__.get("__bibfs_guarded_by__", {}))
        for a in attrs:
            merged[a] = guards
        cls.__bibfs_guarded_by__ = merged
        return cls

    return deco
