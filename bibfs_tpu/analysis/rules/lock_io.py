"""``lock-io`` — no blocking I/O while holding a lock.

Every serving-path stall traced in PRs 5-8 had the same shape: a thread
holding a lock every other thread resolves names/queues through, doing
something that can block for milliseconds-to-seconds — an fsync, a
subprocess spawn, a pipe write into a possibly-full buffer. This rule
flags, lexically, calls to known-blocking primitives made inside a
``with self.<lock>:`` block (any self attribute that reads as a lock —
``common.LOCKISH_RE`` — plus the class's ``@guarded_by`` guards) or
inside a ``*_locked``-named method (the callee-holds-the-lock
convention).

The blocklist (deliberately conservative — the dynamic
``analysis/lockgraph`` harness catches what lexical analysis cannot):

- ``os.fsync`` / ``fsync_dir`` / ``os.replace`` — disk commits;
- ``open(...)`` — file open/creation;
- ``subprocess.Popen/run/call/check_call/check_output``, ``*.communicate``;
- ``time.sleep`` — a backoff under a lock convoys every peer;
- ``socket.*`` calls;
- pipe I/O: ``*.stdin/stdout.write/flush/read/readline``, and the
  ``self._write`` pipe-writer convention (``fleet/replica.py``);
- ``*.wal.append`` — the WAL append whose fsync IS the ack barrier.

**Built-in allowlist.** Two documented, deliberate trades hold blocking
I/O under a lock by design and are allowlisted here (rather than
suppressed inline) because the invariant is structural, argued at
length at the seam itself:

- the fsync-under-store-lock trade in ``store/registry.py``: an acked
  update must be durable BEFORE the overlay commit, and the append must
  be fenced against a checkpoint's capture+segment-switch — so
  ``GraphStore.update`` appends (and, under ``fsync=always``, fsyncs)
  inside the store lock, and ``_write_manifest_locked`` commits
  manifests there (``update()``'s docstring carries the latency
  analysis); ``WalWriter.append``/``_fsync_locked`` are the
  writer-side halves of the same contract.
- ``fleet/replica.py`` ``ProcessReplica``: child-stdin writes happen
  under the replica lock so a concurrent submit's ``use`` switch can
  never redirect an update batch — bounded by ``_CHUNK_LINES`` far
  below pipe capacity, with replies awaited OUTSIDE the lock
  (deadlock-free by construction; the ``_update_commands`` docstring
  carries the proof), and ``_spawn`` swaps the process inside the lock
  so a stale reader's EOF sweep cannot mark the new incarnation dead.

Anything else needs an inline ``# bibfs: allow(lock-io): <why>``.
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import (
    Rule,
    attr_chain,
    guard_decls,
    iter_classes,
    iter_methods,
    iter_nodes_with_held,
)

#: (path suffix, method qualname, reason) — the documented trades above
ALLOWLIST = (
    ("bibfs_tpu/store/registry.py", "GraphStore.update",
     "validate-log-commit under the capture lock is the ack contract"),
    ("bibfs_tpu/store/registry.py", "GraphStore._write_manifest_locked",
     "manifest rename commits under the store lock by design"),
    ("bibfs_tpu/store/wal.py", "WalWriter._fsync_locked",
     "the fsync under the writer lock IS the durability ack barrier"),
    ("bibfs_tpu/fleet/replica.py", "ProcessReplica._spawn",
     "locked process swap defeats the stale-reader EOF sweep race"),
    ("bibfs_tpu/fleet/replica.py", "ProcessReplica.submit",
     "graph-pinned chunked pipe writes (see _CHUNK_LINES)"),
    ("bibfs_tpu/fleet/replica.py", "ProcessReplica._nudge",
     "graph-pinned chunked pipe writes (see _CHUNK_LINES)"),
    ("bibfs_tpu/fleet/replica.py", "ProcessReplica._command",
     "graph-pinned chunked pipe writes (see _CHUNK_LINES)"),
    ("bibfs_tpu/fleet/replica.py", "ProcessReplica._command_use",
     "graph-pinned chunked pipe writes (see _CHUNK_LINES)"),
    ("bibfs_tpu/fleet/replica.py", "ProcessReplica._update_commands",
     "graph-pinned chunked pipe writes (see _CHUNK_LINES)"),
)

_SUBPROCESS = frozenset(("Popen", "run", "call", "check_call",
                         "check_output"))
_PIPE_ENDS = frozenset(("write", "flush", "read", "readline"))


def _blocking_label(call: ast.Call) -> str | None:
    chain = attr_chain(call.func)
    last = chain[-1]
    if chain[-2:] in (("os", "fsync"), ("os", "replace")):
        return ".".join(chain[-2:])
    if last == "fsync_dir":
        return "fsync_dir"
    if chain == ("open",):
        return "open"
    if len(chain) >= 2 and chain[-2] == "subprocess" and last in _SUBPROCESS:
        return f"subprocess.{last}"
    if last == "communicate":
        return "communicate"
    if chain[-2:] == ("time", "sleep"):
        return "time.sleep"
    if "socket" in chain[:-1]:
        return ".".join(chain[-2:])
    if last in _PIPE_ENDS and any(p in ("stdin", "stdout") for p in chain):
        return ".".join(chain[-3:])
    if last == "_write" and chain[0] == "self":
        return "self._write (pipe write)"
    if chain[-2:] == ("wal", "append"):
        return "wal.append (fsync-bearing)"
    return None


def _allowlisted(rel: str, qual: str) -> bool:
    for suffix, method, _reason in ALLOWLIST:
        if rel.endswith(suffix) and qual == method:
            return True
    return False


def _check(project):
    findings = []
    for pf in project.files:
        for cls_qual, cls in iter_classes(pf.tree):
            guards = {g for gs in guard_decls(cls).values() for g in gs}
            for method in iter_methods(cls):
                qual = f"{cls_qual}.{method.name}"
                initial = (
                    frozenset((f"<{method.name}>",))
                    if method.name.endswith("_locked") else frozenset()
                )
                if _allowlisted(pf.rel, qual):
                    continue
                for node, held in iter_nodes_with_held(
                        method, extra_locks=guards, initial=initial):
                    if not held or not isinstance(node, ast.Call):
                        continue
                    label = _blocking_label(node)
                    if label is None:
                        continue
                    lock = ", ".join(sorted(h.strip("<>") for h in held))
                    findings.append(Finding(
                        "lock-io", pf.rel, node.lineno,
                        f"{qual} calls blocking {label} while holding "
                        f"`{lock}` — move the I/O off the lock or "
                        "document the trade",
                    ))
    return findings


RULE = Rule(
    "lock-io",
    "no blocking I/O (fsync/spawn/pipe/socket/sleep) under a held lock",
    _check,
)
