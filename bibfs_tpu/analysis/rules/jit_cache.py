"""``jit-cache`` — every compiled program must be paid for once and
accounted for.

The serving stack's compile discipline has two lexical halves, and this
rule checks both:

1. **Every ``jax.jit`` site in the compiled-program modules
   (``serve/``, ``solvers/``, ``ops/``) must sit inside a memoized
   builder** — a function decorated ``functools.lru_cache`` /
   ``functools.cache``. An anonymous module-level jit (or a fresh
   ``jax.jit(...)`` in straight-line code) creates a NEW traced
   callable per call: jax's program cache keys on the callable's
   identity, so the program retraces and recompiles per padded shape
   per call — a ~20 µs dispatch becomes a multi-second compile under
   live traffic — and the leak never shows in
   ``ExecutableCache.program_counts()`` because the cache only counts
   what dispatch code notes into it. The builder-memo idiom
   (``@lru_cache def _get_kernel(shape...): return jax.jit(build(...))``)
   is what every kernel in the tree uses; the dynamic sentinel
   (``analysis/compilegraph.py``) proves the same property at runtime.

2. **Route-level dispatch accounting keys on placement.** In
   ``serve/routes/``, every ``exec_cache.note(...)`` must derive its
   key through ``placement_bucket_key(...)`` (a bare padded-shape key
   silently collides a mesh/blocked/kind program with the
   single-device executable of the same shape — the bug
   ``placement_bucket_key`` was built to end), and every dispatch
   route (``is_dispatch = True``) must note its programs at all —
   either its own ``exec_cache.note`` call or by delegating to the
   engine's ``_device_launch`` (which notes the single-device base
   key).
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import (
    Rule,
    attr_chain,
    is_jit_call,
    iter_classes,
    jit_decorator,
)

#: the modules whose jits compile serving programs; analysis fixtures
#: and utils probes are out of scope (utils/tpu_aot compiles ON PURPOSE
#: per audit entry, utils/calibrate per measurement)
SCOPE_PREFIXES = (
    "bibfs_tpu/serve/",
    "bibfs_tpu/solvers/",
    "bibfs_tpu/ops/",
)

_MEMO_DECORATORS = frozenset(("lru_cache", "cache"))


def _in_scope(rel: str) -> bool:
    return rel.replace("\\", "/").startswith(SCOPE_PREFIXES)


def _has_memo_decorator(fn) -> bool:
    for deco in fn.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        if attr_chain(target)[-1] in _MEMO_DECORATORS:
            return True
    return False


def _jit_sites(tree):
    """``(node, enclosing_defs)`` for every jit call/decorator, with
    the lexical chain of enclosing FunctionDefs (outermost first).
    Decorators are attributed to the ENCLOSING scope (the def they
    decorate is not 'inside' itself) and visited exactly once — the
    body recursion below deliberately excludes ``decorator_list`` so a
    call-form ``@jax.jit(...)`` is not double-counted."""
    out = []

    def walk(children, chain):
        for child in children:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in child.decorator_list:
                    if jit_decorator(deco) is not None:
                        out.append((deco, chain))
                    # a decorator's arguments may still CONTAIN a jit
                    # call of their own (recursing over the CHILDREN
                    # never re-visits the decorator node itself)
                    walk(ast.iter_child_nodes(deco), chain)
                walk(ast.iter_child_nodes(child.args), chain)
                walk(child.body, chain + (child,))
                continue
            if is_jit_call(child):
                out.append((child, chain))
            walk(ast.iter_child_nodes(child), chain)

    walk(ast.iter_child_nodes(tree), ())
    return out


def check(project):
    findings = []
    for pf in project.files:
        rel = pf.rel.replace("\\", "/")
        if not _in_scope(rel):
            continue
        for node, chain in _jit_sites(pf.tree):
            if any(_has_memo_decorator(fn) for fn in chain):
                continue
            where = (f"in {chain[-1].name}" if chain
                     else "at module level")
            findings.append(Finding(
                "jit-cache", pf.rel, node.lineno,
                f"jax.jit {where} outside a memoized builder — an "
                "un-memoized jit retraces+recompiles per call per "
                "padded shape and never appears in "
                "ExecutableCache.program_counts(); wrap the builder "
                "in functools.lru_cache and declare the program in "
                "analysis/compilegraph.PROGRAM_BUDGETS",
            ))
        if not rel.startswith("bibfs_tpu/serve/routes/"):
            continue
        # half 2a: route-level notes must key on placement
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Call)
                    and attr_chain(node.func)[-1] == "note"
                    and len(attr_chain(node.func)) >= 3
                    and attr_chain(node.func)[-2] == "exec_cache"):
                continue
            arg = node.args[0] if node.args else None
            if not (isinstance(arg, ast.Call)
                    and attr_chain(arg.func)[-1]
                    == "placement_bucket_key"):
                findings.append(Finding(
                    "jit-cache", pf.rel, node.lineno,
                    "route-level exec_cache.note() without a "
                    "placement_bucket_key(...)-derived key — a bare "
                    "padded-shape key counts a mesh/blocked/kind "
                    "program as a hit on the single-device executable "
                    "of the same shape",
                ))
        # half 2b: every dispatch route accounts its programs
        for qual, cls in iter_classes(pf.tree):
            if not any(
                isinstance(stmt, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "is_dispatch"
                        for t in stmt.targets)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is True
                for stmt in cls.body
            ):
                continue
            notes = any(
                isinstance(n, ast.Call)
                and attr_chain(n.func)[-1] == "note"
                and "exec_cache" in attr_chain(n.func)
                for n in ast.walk(cls)
            )
            delegates = any(
                isinstance(n, ast.Call)
                and attr_chain(n.func)[-1].endswith("_device_launch")
                for n in ast.walk(cls)
            )
            if not notes and not delegates:
                findings.append(Finding(
                    "jit-cache", pf.rel, cls.lineno,
                    f"dispatch route {qual} never notes its compiled "
                    "programs into an ExecutableCache (and does not "
                    "delegate to the engine's _device_launch) — its "
                    "executables are invisible to the reuse counters "
                    "and the zero_recompiles gates",
                ))
    return findings


RULE = Rule(
    "jit-cache",
    "jax.jit only inside lru_cache'd builders; route dispatch "
    "accounting keys on placement_bucket_key",
    check,
)
