"""``metric-mint`` — one canonical metric-name list, everywhere, minted
at construction.

The stable metric names are an API: dashboards, the soak gates, the
live-endpoint CI probe and the README tables all key on them. Before
``obs/names.py`` they were declared in five places by hand; this rule
pins every surface to that one list:

- every ``REGISTRY.counter/gauge/histogram("name", ...)`` mint in
  ``bibfs_tpu/`` uses a string literal (a computed name can't be
  audited) that is in ``obs.names.ALL_METRIC_NAMES``;
- every other ``bibfs_*`` string literal in the package resolves to a
  canonical family (modulo the histogram ``_bucket``/``_count``/
  ``_sum`` exposition suffixes) — a gate list or test helper cannot
  drift from the registry;
- [full-project scans only] every canonical name is actually minted
  somewhere — the list cannot grow dead entries — and the README's
  ``bibfs_*`` tokens reconcile with it in BOTH directions: nothing
  documented that doesn't exist, nothing existing that isn't
  documented.

The "minted at registry/ctor init" half of the invariant is structural:
because every mint site must use a canonical literal, and the soak
gates assert the families render at zero before traffic, a name that
only appears at first-use would fail the render gates — the lint keeps
the name set closed, the gates keep minting eager.
"""

from __future__ import annotations

import ast
import re

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import Rule, attr_chain
from bibfs_tpu.obs.names import (
    ALL_METRIC_NAMES,
    NON_METRIC_TOKENS,
    canonical_family,
)

_MINT_METHODS = frozenset(("counter", "gauge", "histogram"))
_METRIC_TOKEN = re.compile(r"^bibfs_[a-z0-9_]+$")
_README_TOKEN = re.compile(r"\bbibfs_[a-z0-9_]+\b")
_NAMES_MODULE = "bibfs_tpu/obs/names.py"


def _mint_name(call: ast.Call):
    """The literal name a ``*.counter/gauge/histogram(...)`` mint call
    registers, or (None, True) when the call mints with a non-literal
    name, or (None, False) when it is not a mint call."""
    func = call.func
    if not (isinstance(func, ast.Attribute) and func.attr in _MINT_METHODS):
        return None, False
    chain = attr_chain(func)
    # REGISTRY.counter(...), self.gauge(...) in the registry itself;
    # anything else named .counter() (e.g. itertools.count) won't have
    # a bibfs_ literal and is filtered by the argument check below
    if chain[0] not in ("REGISTRY", "self"):
        return None, False
    if not call.args:
        return None, False
    name = call.args[0]
    if isinstance(name, ast.Constant) and isinstance(name.value, str):
        if name.value.startswith("bibfs_"):
            return name.value, True
        return None, False
    return None, chain[0] == "REGISTRY"


def _check(project):
    findings = []
    minted: set[str] = set()
    for pf in project.files:
        rel = pf.rel.replace("\\", "/")
        if rel.endswith("obs/names.py"):
            continue  # the canonical list itself
        mint_lines = set()
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call):
                name, is_mint = _mint_name(node)
                if name is not None:
                    minted.add(name)
                    mint_lines.add(node.lineno)
                    if name not in ALL_METRIC_NAMES:
                        findings.append(Finding(
                            "metric-mint", pf.rel, node.lineno,
                            f"mints {name!r}, which is not in the "
                            "canonical list (bibfs_tpu/obs/names.py) — "
                            "add it there (and to the README table)",
                        ))
                elif is_mint:
                    findings.append(Finding(
                        "metric-mint", pf.rel, node.lineno,
                        "REGISTRY mint with a non-literal metric name "
                        "— names must be auditable string literals "
                        "from obs/names.py",
                    ))
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _METRIC_TOKEN.match(node.value)):
                continue
            tok = node.value
            if tok in NON_METRIC_TOKENS or node.lineno in mint_lines:
                continue
            if canonical_family(tok) is None:
                findings.append(Finding(
                    "metric-mint", pf.rel, node.lineno,
                    f"string literal {tok!r} looks like a metric name "
                    "but is not in the canonical list "
                    "(bibfs_tpu/obs/names.py)",
                ))
    if not project.complete:
        return findings
    for name in sorted(ALL_METRIC_NAMES - minted):
        findings.append(Finding(
            "metric-mint", _NAMES_MODULE, 1,
            f"canonical metric {name!r} is never minted by any "
            "REGISTRY call — dead documentation; remove it or mint it",
        ))
    readme = project.readme()
    if readme is not None:
        documented: set[str] = set()
        for i, line in enumerate(readme.splitlines(), start=1):
            for tok in _README_TOKEN.findall(line):
                if tok in NON_METRIC_TOKENS:
                    continue
                fam = canonical_family(tok)
                if fam is None:
                    findings.append(Finding(
                        "metric-mint", "README.md", i,
                        f"README names {tok!r}, which is not a "
                        "canonical metric family "
                        "(bibfs_tpu/obs/names.py)",
                    ))
                else:
                    documented.add(fam)
        for name in sorted(ALL_METRIC_NAMES - documented):
            findings.append(Finding(
                "metric-mint", "README.md", 1,
                f"canonical metric {name!r} is missing from the README "
                "metric tables",
            ))
    return findings


RULE = Rule(
    "metric-mint",
    "metric names come from the one canonical list (obs/names.py), "
    "minted as literals, README-reconciled",
    _check,
)
