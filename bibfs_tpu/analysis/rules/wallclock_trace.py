"""``no-wallclock-in-trace`` — no ``time.*`` inside traced bodies.

A ``time.time()`` / ``time.perf_counter()`` inside a function that jax
traces does not measure anything: it runs ONCE, at trace time, and the
Python float it returns is baked into the compiled program as a
constant — every later dispatch replays the stale value. Worse, a
``time.sleep`` traces into nothing at all (the compiled program skips
it) while still stalling every RE-trace, so a retrace leak shows up as
mysterious latency. Timing belongs outside the program (the
``solvers/timing.py`` force-read protocol); traced bodies own math
only.

Traced bodies are resolved lexically per file: jit-decorated defs,
defs passed by name to ``jax.jit`` (unwrapped through ``vmap`` /
``shard_map``), every def nested inside a builder whose call result
feeds a jit (``jax.jit(_build_kernel(...))``), and defs passed to
``lax.while_loop`` / ``fori_loop`` / ``scan`` / ``cond``.
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import (
    Rule,
    attr_chain,
    traced_functions,
)

_TIME_CALLS = frozenset((
    "time", "perf_counter", "perf_counter_ns", "monotonic",
    "monotonic_ns", "process_time", "sleep", "time_ns",
))


def _time_names(tree):
    """``(module_aliases, bare_names)``: every local name the ``time``
    module (or one of its clock functions) is bound to in this file —
    ``import time``, ``import time as _time``, ``from time import
    perf_counter [as pc]`` all count; the call-site check resolves
    through them so an alias is not a lint bypass."""
    modules, bare = {"time"}, {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    modules.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _TIME_CALLS:
                    bare[a.asname or a.name] = a.name
    return modules, bare


def check(project):
    findings = []
    for pf in project.files:
        traced = traced_functions(pf.tree)
        if not traced:
            continue
        modules, bare = _time_names(pf.tree)
        seen_lines = set()
        for fn, why in traced.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if len(chain) == 1 and chain[0] in bare:
                    chain = ("time", bare[chain[0]])
                if len(chain) >= 2 and chain[0] in modules \
                        and chain[-1] in _TIME_CALLS:
                    if node.lineno in seen_lines:
                        continue  # nested defs are marked twice
                    seen_lines.add(node.lineno)
                    findings.append(Finding(
                        "no-wallclock-in-trace", pf.rel, node.lineno,
                        f"time.{chain[-1]}() inside traced body "
                        f"{fn.name} ({why}) — it runs once at trace "
                        "time and bakes a constant into the compiled "
                        "program; time outside the program "
                        "(solvers/timing.py's force-read protocol)",
                    ))
    return findings


RULE = Rule(
    "no-wallclock-in-trace",
    "no time.* calls inside jit/lax-traced bodies",
    check,
)
