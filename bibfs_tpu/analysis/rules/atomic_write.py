"""``atomic-write`` — served data files commit by tmp + ``os.replace``.

The durability layer's whole recovery argument rests on one property:
a reader (or a recovering process) sees either the old complete file or
the new complete file, never a torn middle. ``graph/io.write_graph_bin``
and ``store/registry._write_manifest_locked`` earn that with the
same-directory-tmp + ``os.replace`` idiom; a future helper that opens a
served ``.bin``/manifest path for writing directly would silently void
it — exactly the class of regression a reviewer won't spot in a +500
line PR.

The rule: in the served-data modules (``bibfs_tpu/store/``,
``bibfs_tpu/graph/``), any ``open(...)`` with a write-creating mode
(``"w"``, ``"wb"``, ``"w+"``, ...) must sit in a function that also
calls ``os.replace`` (the tmp+rename idiom — the open is then the tmp
side). Append (``"ab"`` — the WAL's own format is append-only with CRC
framing) and in-place repair (``"r+b"`` — ``repair_wal``'s tail
truncation) modes are legal.
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import Rule, attr_chain

_SCOPES = ("bibfs_tpu/store/", "bibfs_tpu/graph/")


def _write_mode(call: ast.Call) -> str | None:
    """The mode string when this ``open`` creates/truncates a file."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value if "w" in mode.value or "x" in mode.value else None
    return "<dynamic>"  # a computed mode can't be proven read-only


def _own_nodes(func):
    """Every AST node lexically owned by ``func``, EXCLUDING nested
    function/lambda bodies — those are analyzed as their own units (an
    ``os.replace`` inside a nested helper must not legalize the
    enclosing function's direct write, and a nested function's open
    belongs to the nested function)."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from walk(child)

    yield from walk(func)


def _check(project):
    findings = []
    for pf in project.files:
        if not any(s in pf.rel.replace("\\", "/") for s in _SCOPES):
            continue
        # each function (nested ones included) is its own unit: the
        # open and the os.replace must live in the SAME function
        for func in [n for n in ast.walk(pf.tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]:
            opens = []
            replaces = False
            for node in _own_nodes(func):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if chain[-2:] == ("os", "replace"):
                    replaces = True
                elif chain == ("open",):
                    mode = _write_mode(node)
                    if mode is not None:
                        opens.append((node, mode))
            if replaces:
                continue  # the tmp side of the tmp+replace idiom
            for node, mode in opens:
                findings.append(Finding(
                    "atomic-write", pf.rel, node.lineno,
                    f"{func.name} opens a served-data path with mode "
                    f"{mode!r} and never os.replace()s — write to a "
                    "same-directory tmp file and commit by rename "
                    "(graph/io.write_graph_bin is the idiom)",
                ))
    return findings


RULE = Rule(
    "atomic-write",
    "served .bin/manifest writes commit via tmp + os.replace",
    _check,
)
