"""``atomic-write`` — served data files commit by tmp + rename.

The durability layer's whole recovery argument rests on one property:
a reader (or a recovering process) sees either the old complete file or
the new complete file, never a torn middle. ``graph/io.write_graph_bin``
and ``store/registry._write_manifest_locked`` earn that with the
same-directory-tmp + ``os.replace`` idiom; a future helper that opens a
served ``.bin``/manifest path for writing directly would silently void
it — exactly the class of regression a reviewer won't spot in a +500
line PR.

Two commit idioms are recognized:

- **single file**: write a same-directory tmp, ``os.replace`` onto the
  final path (``graph/io._atomic_replace``);
- **directory manifest**: populate a same-directory tmp DIRECTORY
  (several array files + a manifest), then publish it with ONE
  ``os.rename`` (``store/sidecar.write_sidecar`` — the arrays-sidecar
  checkpoint recovery ``np.memmap``s).

The rules:

- in the served-data modules (``bibfs_tpu/store/``,
  ``bibfs_tpu/graph/``), any ``open(...)`` with a write-creating mode
  (``"w"``, ``"wb"``, ``"w+"``, ...) must sit in a function that also
  calls ``os.replace``/``os.rename`` (the open is then the tmp side),
  OR in a helper every same-module caller of which commits by rename
  AFTER calling it (the sidecar's per-array writer);
- **rename-last**: in a committing function, every write-mode open must
  precede the final rename — a write landing after the commit mutates
  the already-published path, which is exactly the torn state the idiom
  exists to rule out.

Append (``"ab"`` — the WAL's own format is append-only with CRC
framing) and in-place repair (``"r+b"`` — ``repair_wal``'s tail
truncation) modes are legal.
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import Rule, attr_chain

_SCOPES = ("bibfs_tpu/store/", "bibfs_tpu/graph/")


def _write_mode(call: ast.Call) -> str | None:
    """The mode string when this ``open`` creates/truncates a file."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value if "w" in mode.value or "x" in mode.value else None
    return "<dynamic>"  # a computed mode can't be proven read-only


def _own_nodes(func):
    """Every AST node lexically owned by ``func``, EXCLUDING nested
    function/lambda bodies — those are analyzed as their own units (an
    ``os.replace`` inside a nested helper must not legalize the
    enclosing function's direct write, and a nested function's open
    belongs to the nested function)."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from walk(child)

    yield from walk(func)


class _FuncInfo:
    __slots__ = ("func", "opens", "commit_lines", "calls")

    def __init__(self, func):
        self.func = func
        self.opens: list = []        # (node, mode)
        self.commit_lines: list = []  # linenos of os.replace/os.rename
        self.calls: list = []         # (callee name, lineno)


def _scan_file(pf):
    """Per-function facts + a same-module call map (by bare name)."""
    infos = {}
    for func in [n for n in ast.walk(pf.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        info = _FuncInfo(func)
        for node in _own_nodes(func):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain[-2:] in (("os", "replace"), ("os", "rename")):
                info.commit_lines.append(node.lineno)
            elif chain == ("open",):
                mode = _write_mode(node)
                if mode is not None:
                    info.opens.append((node, mode))
            elif len(chain) == 1 and chain[0]:
                info.calls.append((chain[0], node.lineno))
        # methods shadow by bare name too rarely to matter; last def wins
        infos[func.name] = info
    return infos


def _committing_caller_covers(infos, name) -> bool:
    """True when every same-module caller of ``name`` commits by
    rename/replace AFTER the call site — the helper is then provably
    the tmp side of its callers' commit (the sidecar per-array writer
    pattern). No caller at all is NOT covered: an unreferenced writer
    must carry its own commit."""
    covered = False
    for info in infos.values():
        for callee, lineno in info.calls:
            if callee != name:
                continue
            if not info.commit_lines or max(info.commit_lines) < lineno:
                return False
            covered = True
    return covered


def _check(project):
    findings = []
    for pf in project.files:
        if not any(s in pf.rel.replace("\\", "/") for s in _SCOPES):
            continue
        infos = _scan_file(pf)
        for name, info in infos.items():
            if not info.opens:
                continue
            if info.commit_lines:
                # the tmp side of the tmp+rename idiom — but only
                # writes BEFORE the publishing rename are the tmp side
                last = max(info.commit_lines)
                for node, mode in info.opens:
                    if node.lineno > last:
                        findings.append(Finding(
                            "atomic-write", pf.rel, node.lineno,
                            f"{name} opens a served-data path with mode "
                            f"{mode!r} AFTER its committing rename "
                            f"(line {last}) — the directory/file is "
                            "already published; all writes must land "
                            "before the rename-last commit",
                        ))
                continue
            if _committing_caller_covers(infos, name):
                continue  # helper: every caller renames after it
            for node, mode in info.opens:
                findings.append(Finding(
                    "atomic-write", pf.rel, node.lineno,
                    f"{name} opens a served-data path with mode "
                    f"{mode!r} and never os.replace()s — write to a "
                    "same-directory tmp file and commit by rename "
                    "(graph/io.write_graph_bin is the idiom)",
                ))
    return findings


RULE = Rule(
    "atomic-write",
    "served .bin/manifest writes commit via tmp + rename (rename-last)",
    _check,
)
