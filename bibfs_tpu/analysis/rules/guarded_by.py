"""``guarded-by`` — shared attributes mutate only under their declared lock.

Classes annotate their locking discipline with
:func:`bibfs_tpu.analysis.guarded_by`::

    @guarded_by("_table_lock", "_states", "_versions")
    class Router: ...

and this rule enforces the mutation half statically: every assignment,
augmented assignment, deletion or in-place container call on a declared
``self.<attr>`` must sit lexically inside a ``with self.<guard>:``
block. Lock-free reads stay legal — GIL-atomic snapshot reads are a
documented hot-path idiom in this codebase (the router's routing table,
the engines' runtime map); it is unsynchronized WRITES that the PR 5-8
review cycles kept catching.

Exemptions (the package's existing conventions, see
``analysis/annotations.py``): ``__init__``/``__new__`` (construction
happens-before publication) and ``*_locked``-named methods (the callee-
holds-the-lock convention). A mutation inside a nested function counts
as unguarded even when the ``def`` sits in a locked block — the closure
runs later, wherever it is called.

Declarations are INHERITED: a subclass is checked against its own
``@guarded_by`` merged over every base class's (resolved project-wide
by class name, transitively — the static mirror of the decorator's MRO
merge), so ``PipelinedQueryEngine`` cannot silently mutate the base
engine's ``_runtimes`` outside ``_rt_lock`` just because its own
decorator only declares the queue attributes.
"""

from __future__ import annotations

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import (
    Rule,
    attr_chain,
    guard_decls,
    iter_classes,
    iter_methods,
    iter_nodes_with_held,
    self_mutations,
)

_EXEMPT = ("__init__", "__new__")


def _class_table(project):
    """Project-wide class registry: simple name -> (base names, own
    @guarded_by decls). Simple-name resolution matches how the bases
    are spelled at the class statement; a cross-file name collision
    resolves to the last definition (acceptable for one package's
    annotated classes, which are unique here)."""
    table = {}
    for pf in project.files:
        for _qual, cls in iter_classes(pf.tree):
            bases = [attr_chain(b)[-1] for b in cls.bases
                     if attr_chain(b)[-1] != "?"]
            table[cls.name] = (bases, guard_decls(cls))
    return table


def _resolved_decls(name, table, seen=frozenset()):
    """``guard_decls`` merged down the (statically resolved) MRO:
    bases first, own declarations override — the same merge the
    runtime decorator performs."""
    entry = table.get(name)
    if entry is None or name in seen:
        return {}
    bases, own = entry
    merged = {}
    for base in bases:
        merged.update(_resolved_decls(base, table, seen | {name}))
    merged.update(own)
    return merged


def _check(project):
    findings = []
    table = _class_table(project)
    for pf in project.files:
        for qual, cls in iter_classes(pf.tree):
            decls = _resolved_decls(cls.name, table)
            if not decls:
                continue
            all_guards = {g for gs in decls.values() for g in gs}
            for method in iter_methods(cls):
                if method.name in _EXEMPT or method.name.endswith("_locked"):
                    continue
                for node, held in iter_nodes_with_held(
                        method, extra_locks=all_guards):
                    for attr, site in self_mutations(node):
                        guards = decls.get(attr)
                        if guards is None or held.intersection(guards):
                            continue
                        findings.append(Finding(
                            "guarded-by", pf.rel, site.lineno,
                            f"{qual}.{method.name} mutates self.{attr} "
                            f"outside `with self."
                            f"{'`/`self.'.join(guards)}`"
                            f" (declared @guarded_by)",
                        ))
    return findings


RULE = Rule(
    "guarded-by",
    "@guarded_by-declared attributes mutate only under their lock",
    _check,
)
