"""``chaos-site`` — the fault-seam registry reconciled, both ways.

``serve/faults.KNOWN_SITES`` is the contract between the chaos harness
and the engine seams, and it rots silently in two directions:

- a seam is renamed/removed in engine code while its site stays
  declared (or a spec keeps referencing the old name): ``fire()`` on an
  unknown site is a no-op, so a chaos soak "passes" while injecting
  nothing — the dead-seam failure mode the KNOWN_SITES parse guard only
  catches for *parsed* specs;
- a seam is fired in engine code under a name the registry never
  declared, so no spec can ever reach it.

Checks (the first two run on any scan, the rest need the full tree):

1. every literal site fired in package code
   (``*.fire("<site>", ...)`` / ``self._fire("<site>")``) is declared
   in KNOWN_SITES;
2. every fault-spec string literal in package code and ``bench.py``
   (the soak drivers — tests are exempt: they construct bad specs on
   purpose to assert rejection) names only declared sites;
3. every declared site is actually fired somewhere in the package
   (a declared-but-never-fired site is a dead seam);
4. every declared site is exercised by at least one test or soak — a
   spec string or literal site reference under ``tests/`` /
   ``bench.py`` / the loadgen soak drivers. A seam no chaos run can
   reach proves nothing.
"""

from __future__ import annotations

import ast
import glob
import os
import re

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import Rule, attr_chain

_FAULTS_REL = "bibfs_tpu/serve/faults.py"
_SPEC_RE = re.compile(
    r"([a-z][a-z0-9_]*):(?:p|every|times|kind|ms|pair)=", re.ASCII
)


def _known_sites(pf):
    """(KNOWN_SITES tuple, lineno) parsed from the faults module."""
    for node in ast.walk(pf.tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            sites = tuple(
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
            return sites, node.lineno
    return None, 0


def _fired_sites(pf):
    """``(site, lineno)`` for every literal first arg of a
    ``*.fire(...)`` / ``*._fire(...)`` call."""
    out = []
    for node in ast.walk(pf.tree):
        if (isinstance(node, ast.Call)
                and attr_chain(node.func)[-1] in ("fire", "_fire")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append((node.args[0].value, node.lineno))
    return out


def _code_strings(tree):
    """``(text, lineno)`` for every string constant that is CODE, not
    prose — f-string literal fragments included (spec prefixes live in
    the literal half of ``f"{site}:every={n}"``-style strings).
    Docstring positions (a bare string expression opening a
    module/class/def body) are excluded, so a docstring *mentioning* a
    site neither counts as exercising it nor fails the build when it
    quotes a stale spec example. Comments never reach the AST at
    all."""
    doc_ids = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                doc_ids.add(id(body[0].value))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in doc_ids):
            yield node.value, node.lineno


def check(project):
    findings = []
    faults_pf = None
    for pf in project.files:
        if pf.rel.replace("\\", "/").endswith("serve/faults.py"):
            faults_pf = pf
            break
    if faults_pf is None:
        return findings  # fixture scans without the registry: nothing to do
    sites, decl_line = _known_sites(faults_pf)
    if sites is None:
        findings.append(Finding(
            "chaos-site", faults_pf.rel, 1,
            "KNOWN_SITES tuple not found/parseable in the faults module",
        ))
        return findings
    known = set(sites)

    fired: dict[str, list] = {}
    for pf in project.files:
        if pf is faults_pf:
            continue  # FaultPlan.fire's own definition is not a seam
        for site, lineno in _fired_sites(pf):
            fired.setdefault(site, []).append((pf.rel, lineno))
            if site not in known:
                findings.append(Finding(
                    "chaos-site", pf.rel, lineno,
                    f"fired fault site {site!r} is not declared in "
                    "serve/faults.KNOWN_SITES — no spec can ever "
                    "target it (fire() on an unknown site injects "
                    "nothing, silently)",
                ))
        # spec literals in package drivers must parse to known sites
        for text, lineno in _code_strings(pf.tree):
            for m in _SPEC_RE.finditer(text):
                if m.group(1) not in known:
                    findings.append(Finding(
                        "chaos-site", pf.rel, lineno,
                        f"fault spec references unknown site "
                        f"{m.group(1)!r} — the seam was renamed or "
                        "never existed; this spec injects nothing",
                    ))

    if not project.complete:
        return findings

    # bench.py is a soak DRIVER outside the package walk: its spec
    # literals must parse to known sites too (direction 2) — a renamed
    # seam in a bench soak spec is exactly the silent dead-seam this
    # rule exists for. Tests stay exempt from this direction: they
    # construct bad specs on purpose to assert rejection.
    bench_path = os.path.join(project.root, "bench.py")
    try:
        with open(bench_path, encoding="utf-8") as f:
            bench_tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        bench_tree = None
    if bench_tree is not None:
        for text, lineno in _code_strings(bench_tree):
            for m in _SPEC_RE.finditer(text):
                if m.group(1) not in known:
                    findings.append(Finding(
                        "chaos-site", "bench.py", lineno,
                        f"fault spec references unknown site "
                        f"{m.group(1)!r} — the seam was renamed or "
                        "never existed; this spec injects nothing",
                    ))

    # full-tree cross-checks: declared => fired, declared => exercised.
    # "Exercised" means a site reference in an actual string literal —
    # AST-collected, docstrings excluded — under tests/, bench.py, or
    # the loadgen soak drivers: a deleted injection test must not stay
    # green because prose somewhere still quotes the site name.
    exercised: set[str] = set()
    scan_paths = sorted(glob.glob(os.path.join(project.root, "tests",
                                               "*.py")))
    scan_paths.append(bench_path)
    literals = []
    for path in scan_paths:
        try:
            with open(path, encoding="utf-8") as f:
                literals.extend(
                    t for t, _ in _code_strings(ast.parse(f.read()))
                )
        except (OSError, SyntaxError):
            continue
    # the loadgen soak drivers count as soaks (bench.py drives them)
    for pf in project.files:
        if pf.rel.replace("\\", "/").endswith("serve/loadgen.py"):
            literals.extend(t for t, _ in _code_strings(pf.tree))
    for site in sites:
        pat = re.compile(
            rf"(?<![a-z0-9_]){re.escape(site)}(?![a-z0-9_])"
        )
        if any(pat.search(text) for text in literals):
            exercised.add(site)

    for site in sites:
        if site not in fired:
            findings.append(Finding(
                "chaos-site", faults_pf.rel, decl_line,
                f"declared fault site {site!r} is never fired by any "
                "engine seam — a dead seam: remove it or wire the "
                "fire() call",
            ))
        if site not in exercised:
            findings.append(Finding(
                "chaos-site", faults_pf.rel, decl_line,
                f"declared fault site {site!r} is not exercised by "
                "any test or soak (no spec or site literal under "
                "tests/, bench.py, or the loadgen drivers) — an "
                "uninjected seam proves nothing",
            ))
    return findings


RULE = Rule(
    "chaos-site",
    "serve/faults.KNOWN_SITES reconciled: every declared site fired "
    "and exercised, every fired/spec'd site declared",
    check,
)
