"""Shared machinery for lint rules: the Rule type plus AST helpers for
attribute chains, class/method iteration, ``@guarded_by`` declarations,
and lexical with-lock region tracking."""

from __future__ import annotations

import ast
import re


class Rule:
    """One registered lint rule: a name, a one-line summary, and a
    ``check(project) -> list[Finding]`` callable."""

    def __init__(self, name: str, summary: str, check):
        self.name = name
        self.summary = summary
        self._check = check

    def check(self, project):
        return self._check(project)


def attr_chain(node) -> tuple:
    """The dotted-name chain of a Name/Attribute expression:
    ``self._proc.stdin.write`` -> ``("self", "_proc", "stdin",
    "write")``. A non-name base (a call result, a subscript) appears as
    ``"?"`` so suffix matches still work."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        parts.append("?")
    return tuple(reversed(parts))


def iter_classes(tree):
    """Top-level and nested ClassDefs with their qualnames."""
    def walk(nodes, prefix):
        for node in nodes:
            if isinstance(node, ast.ClassDef):
                qual = f"{prefix}{node.name}"
                yield qual, node
                yield from walk(node.body, f"{qual}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from walk(node.body, f"{prefix}{node.name}.")
    yield from walk(tree.body, "")


def iter_methods(classdef):
    for node in classdef.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def guard_decls(classdef) -> dict:
    """The class's ``@guarded_by`` declarations: attr -> tuple of guard
    names (empty dict when unannotated). Stacked decorators merge."""
    out: dict[str, tuple] = {}
    for deco in classdef.decorator_list:
        if not (isinstance(deco, ast.Call)
                and attr_chain(deco.func)[-1] == "guarded_by"
                and deco.args):
            continue
        lock = deco.args[0]
        if isinstance(lock, ast.Constant) and isinstance(lock.value, str):
            guards = (lock.value,)
        elif isinstance(lock, (ast.Tuple, ast.List)):
            guards = tuple(
                e.value for e in lock.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
        else:
            continue
        for arg in deco.args[1:]:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out[arg.value] = guards
    return out


#: attribute names that read as locks for the lexical with-lock scan
#: (the package convention: ``_lock``, ``_rt_lock``, ``_table_lock``,
#: ``compact_lock``, ``_cv``, ``_host_solve_lock``, ...)
LOCKISH_RE = re.compile(r"(^|_)(lock|locks|cv|cond|condition|mutex)$")


def with_lock_names(stmt, extra=()) -> set:
    """The self-attribute locks a ``with`` statement acquires (empty
    set when it is not a lock acquisition)."""
    names = set()
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            expr = item.context_expr
            # `with self._lock:` / `with self._cv:`; a Call
            # (`with span(...)`) is not a lock
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and (LOCKISH_RE.search(expr.attr) or expr.attr in extra)):
                names.add(expr.attr)
    return names


def iter_nodes_with_held(func, extra_locks=(), initial=frozenset()):
    """Yield ``(node, held)`` for every AST node in ``func``'s body,
    where ``held`` is the frozenset of self-lock attribute names
    lexically held at that node. Nested function/lambda bodies reset to
    no-locks-held (a closure runs later, wherever it is called);
    nested class bodies are skipped (their methods are visited as
    their own functions by callers)."""

    def walk(node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                yield child, held
                yield from walk(child, frozenset())
                continue
            if isinstance(child, ast.ClassDef):
                continue
            new = with_lock_names(child, extra=extra_locks)
            yield child, held
            yield from walk(child, held | new if new else held)

    yield from walk(func, frozenset(initial))


def is_jit_call(node) -> bool:
    """True when ``node`` is a ``jax.jit(...)`` / ``jit(...)`` call."""
    return (isinstance(node, ast.Call)
            and attr_chain(node.func)[-1] == "jit"
            and attr_chain(node.func)[0] in ("jax", "jit"))


def jit_decorator(deco):
    """The jit Call/Name when ``deco`` is a jit decorator — handles
    ``@jax.jit``, ``@jit``, and ``@partial(jax.jit, ...)`` /
    ``@functools.partial(jit, ...)`` — else None."""
    if isinstance(deco, ast.Call):
        if attr_chain(deco.func)[-1] == "partial" and deco.args:
            inner = deco.args[0]
            if attr_chain(inner)[-1] == "jit" \
                    and attr_chain(inner)[0] in ("jax", "jit"):
                return deco
        if is_jit_call(deco):
            return deco
    elif attr_chain(deco)[-1] == "jit" \
            and attr_chain(deco)[0] in ("jax", "jit"):
        return deco
    return None


def jit_static_decls(call) -> tuple[set, set]:
    """``(static_argnums, static_argnames)`` literals declared on a jit
    call (or a partial(jax.jit, ...) decorator); non-literal
    declarations contribute nothing."""
    nums: set[int] = set()
    names: set[str] = set()
    if not isinstance(call, ast.Call):
        return nums, names
    for kw in call.keywords:
        vals = []
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            vals = list(kw.value.elts)
        elif isinstance(kw.value, ast.Constant):
            vals = [kw.value]
        if kw.arg == "static_argnums":
            nums.update(v.value for v in vals
                        if isinstance(v, ast.Constant)
                        and isinstance(v.value, int))
        elif kw.arg == "static_argnames":
            names.update(v.value for v in vals
                         if isinstance(v, ast.Constant)
                         and isinstance(v.value, str))
    return nums, names


#: call names whose function arguments trace (their bodies run under
#: jax's tracer, same as a jitted body)
TRACING_CALLS = frozenset((
    "while_loop", "fori_loop", "scan", "cond", "switch", "vmap",
    "shard_map", "pmap", "checkpoint", "remat", "custom_vjp", "grad",
))


def traced_functions(tree) -> dict:
    """``{FunctionDef: why}`` for every def in ``tree`` whose body runs
    under the jax tracer: jit-decorated defs, defs passed by name to a
    jit call (unwrapped through vmap/shard_map wrappers), every def
    nested inside a *builder* whose call result feeds a jit call (the
    memoized-builder idiom: ``jax.jit(_build_kernel(...))`` traces the
    kernel the builder returns), defs passed to ``lax.while_loop`` /
    ``scan`` / ``cond`` / ... by name, and defs nested inside any of
    the above."""
    defs_by_name: dict[str, list] = {}
    parent_func: dict = {}

    def index(node, parent):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(child.name, []).append(child)
                parent_func[child] = parent
                index(child, child)
            else:
                index(child, parent)

    index(tree, None)

    traced: dict = {}

    def mark(fn, why):
        if fn in traced:
            return
        traced[fn] = why
        for child in ast.walk(fn):
            if child is not fn and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                traced.setdefault(child, why)

    def mark_name(name, why):
        for fn in defs_by_name.get(name, ()):
            mark(fn, why)

    def mark_jit_operand(node, why):
        """A jit (or wrapper) operand: a Name marks that def; a Call of
        a local function marks the defs nested in it (the builder
        pattern) and recurses into wrapper args (vmap(build(...)))."""
        if isinstance(node, ast.Name):
            mark_name(node.id, why)
        elif isinstance(node, ast.Call):
            fname = attr_chain(node.func)[-1]
            for fn in defs_by_name.get(fname, ()):
                for child in ast.walk(fn):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        mark(child, why)
            for arg in node.args:
                mark_jit_operand(arg, why)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if jit_decorator(deco) is not None:
                    mark(node, "jit-decorated")
        if not isinstance(node, ast.Call):
            continue
        if is_jit_call(node):
            for arg in node.args:
                mark_jit_operand(arg, "passed to jax.jit")
        elif attr_chain(node.func)[-1] in TRACING_CALLS:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    mark_name(arg.id, f"passed to "
                              f"{attr_chain(node.func)[-1]}")
    return traced


#: container methods that mutate their receiver in place
MUTATING_METHODS = frozenset((
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "update", "add", "discard",
    "setdefault", "sort", "reverse",
))


def _self_attr_of(node):
    """``X`` when ``node`` is ``self.X`` or ``self.X[...]``, else None."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def self_mutations(node):
    """``(attr, node)`` pairs for every mutation of a ``self``
    attribute this single AST node performs: assignment / augmented
    assignment / deletion of ``self.X`` or ``self.X[...]``, and calls
    of in-place container methods (``self.X.append(...)``)."""
    out = []
    if isinstance(node, ast.Assign):
        targets = []
        for t in node.targets:
            targets.extend(t.elts if isinstance(t, (ast.Tuple, ast.List))
                           else [t])
        for t in targets:
            attr = _self_attr_of(t)
            if attr is not None:
                out.append((attr, node))
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        attr = _self_attr_of(node.target)
        if attr is not None and not (isinstance(node, ast.AnnAssign)
                                     and node.value is None):
            out.append((attr, node))
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            attr = _self_attr_of(t)
            if attr is not None:
                out.append((attr, node))
    elif (isinstance(node, ast.Call)
          and isinstance(node.func, ast.Attribute)
          and node.func.attr in MUTATING_METHODS):
        attr = _self_attr_of(node.func.value)
        if attr is not None:
            out.append((attr, node))
    return out
