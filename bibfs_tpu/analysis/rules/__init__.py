"""The ``bibfs-lint`` rule registry.

Each rule module exports ``RULE`` (a
:class:`bibfs_tpu.analysis.rules.common.Rule`); registration order is
display order. Adding a rule: write the module, import it here, add a
good/bad fixture pair to ``tests/test_lint.py`` (every rule must both
fire and stay quiet) and a row to the README "Static analysis" table.
"""

from bibfs_tpu.analysis.rules import (
    atomic_write,
    bare_except,
    error_kind,
    guarded_by,
    lock_io,
    metric_mint,
)

RULES = (
    atomic_write.RULE,
    guarded_by.RULE,
    lock_io.RULE,
    error_kind.RULE,
    metric_mint.RULE,
    bare_except.RULE,
)
