"""The ``bibfs-lint`` rule registry.

Each rule module exports ``RULE`` (a
:class:`bibfs_tpu.analysis.rules.common.Rule`); registration order is
display order. Adding a rule: write the module, import it here, add a
good/bad fixture pair to ``tests/test_lint.py`` (every rule must both
fire and stay quiet) and a row to the README "Static analysis" table.
"""

from bibfs_tpu.analysis.rules import (
    atomic_write,
    bare_except,
    chaos_site,
    error_kind,
    guarded_by,
    jit_cache,
    jit_static_args,
    launch_host_sync,
    lock_io,
    metric_mint,
    wallclock_trace,
)

RULES = (
    atomic_write.RULE,
    guarded_by.RULE,
    lock_io.RULE,
    error_kind.RULE,
    metric_mint.RULE,
    bare_except.RULE,
    jit_cache.RULE,
    jit_static_args.RULE,
    launch_host_sync.RULE,
    wallclock_trace.RULE,
    chaos_site.RULE,
)
