"""``launch-host-sync`` — no host synchronization in the launch stage.

The pipelined engine's whole point (PR 2) is the launch/finish overlap:
``launch`` enqueues one batched device program and returns while the
previous batch decodes on the finish worker. A host sync lexically
inside launch-stage code — ``force_scalar`` / ``.block_until_ready()``
/ ``jax.device_get`` / ``.item()`` / ``np.asarray(out)`` on the
dispatch result — serializes the two stages: the flusher blocks on
batch k's execution before batch k+1 can dispatch, silently reverting
the pipeline to synchronous serving. Host syncs belong to ``finish``.

Scope: ``launch`` / ``_launch_*`` methods of dispatch routes
(``is_dispatch = True`` classes under ``serve/routes/``, resolved
through locally-visible base classes) and the engines' own
``_device_launch``. Host-shaped routes (overlay, taxonomy host rungs)
deliberately solve inside ``launch`` and are out of scope — their
``finish`` is the identity and there is nothing to overlap.

What fires:

- ``*.block_until_ready(...)``, ``jax.device_get(...)``,
  ``force_scalar(...)``, ``*.item()`` — unconditional: these exist to
  block on device values;
- ``np.asarray(v)`` / ``np.array(v)`` / ``float(v)`` / ``int(v)``
  where ``v`` tracks to the dispatch output (a name bound by calling a
  hook unpacked from a ``*_dispatch(...)`` call) — reading the output
  forces execution on lazy runtimes (PERF_NOTES.md: values execute at
  the read). Host-array construction (``np.zeros`` padding,
  ``np.asarray(pairs)`` over Python lists) stays legal.
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import Rule, attr_chain, iter_classes

_ALWAYS_SYNC_ATTRS = frozenset(("block_until_ready", "item"))
_ALWAYS_SYNC_CALLS = frozenset(("force_scalar", "device_get"))
_READERS = frozenset(("asarray", "array", "float", "int"))


def _class_index(project):
    """One project-wide pass shared by every file check: ``direct`` =
    class names setting ``is_dispatch = True`` in their own body,
    ``by_file`` = every ClassDef by name (bases resolve by name across
    the project)."""
    direct: set[str] = set()
    by_file: dict = {}
    for qpf in project.files:
        for qual, cls in iter_classes(qpf.tree):
            by_file.setdefault(cls.name, []).append(cls)
            if any(
                isinstance(stmt, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "is_dispatch"
                        for t in stmt.targets)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is True
                for stmt in cls.body
            ):
                direct.add(cls.name)
    return direct, by_file


def _dispatch_classes(pf, index):
    """ClassDefs in ``pf`` that are dispatch routes: ``is_dispatch =
    True`` in their own body, or inherited from a base (by name) that
    sets it anywhere in the project."""
    direct, by_file = index

    def dispatchy(cls, seen=()):
        if cls.name in direct:
            return True
        for base in cls.bases:
            name = attr_chain(base)[-1]
            if name in seen:
                continue
            for bcls in by_file.get(name, ()):
                if dispatchy(bcls, seen + (name,)):
                    return True
        return False

    return [
        (qual, cls) for qual, cls in iter_classes(pf.tree)
        if dispatchy(cls)
    ]


def _launch_functions(pf, index):
    rel = pf.rel.replace("\\", "/")
    out = []
    if rel.startswith("bibfs_tpu/serve/routes/"):
        for qual, cls in _dispatch_classes(pf, index):
            for stmt in cls.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and (
                        stmt.name == "launch"
                        or stmt.name.startswith("_launch")):
                    out.append((f"{qual}.{stmt.name}", stmt))
    if rel.startswith("bibfs_tpu/serve/"):
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "_device_launch":
                out.append((node.name, node))
    return out


def _device_output_names(fn) -> set:
    """Names in ``fn`` that hold the dispatch output: hooks unpacked
    from ``*_dispatch(...)`` calls, and results of calling a hook."""
    hooks: set[str] = set()
    outs: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if isinstance(value, ast.Call):
            callee = attr_chain(value.func)[-1]
            targets = []
            for t in node.targets:
                targets.extend(
                    t.elts if isinstance(t, (ast.Tuple, ast.List))
                    else [t])
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if callee.endswith("_dispatch") or callee == "dispatch":
                hooks.update(names)
            elif isinstance(value.func, ast.Name) \
                    and value.func.id in hooks:
                outs.update(names)
    return outs


def _base_name(node):
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def check(project):
    findings = []
    index = _class_index(project)
    for pf in project.files:
        if not pf.rel.replace("\\", "/").startswith("bibfs_tpu/serve/"):
            continue
        for qual, fn in _launch_functions(pf, index):
            outs = _device_output_names(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if chain[-1] in _ALWAYS_SYNC_ATTRS \
                        and len(chain) > 1:
                    findings.append(Finding(
                        "launch-host-sync", pf.rel, node.lineno,
                        f"{chain[-1]}() in launch-stage {qual} — a "
                        "host sync here serializes the launch/finish "
                        "overlap; move it to the finish stage",
                    ))
                    continue
                if chain[-1] in _ALWAYS_SYNC_CALLS:
                    findings.append(Finding(
                        "launch-host-sync", pf.rel, node.lineno,
                        f"{'.'.join(chain)}(...) in launch-stage "
                        f"{qual} — forcing execution belongs to the "
                        "finish stage (the pipelined engine overlaps "
                        "batch k+1's launch with batch k's finish)",
                    ))
                    continue
                if chain[-1] in _READERS and node.args:
                    base = _base_name(node.args[0])
                    if base is not None and base in outs:
                        findings.append(Finding(
                            "launch-host-sync", pf.rel, node.lineno,
                            f"{chain[-1]}({base}...) reads the "
                            f"dispatch output in launch-stage {qual} "
                            "— on lazy runtimes the value read IS the "
                            "execution barrier; decode in finish",
                        ))
    return findings


RULE = Rule(
    "launch-host-sync",
    "no host syncs (force_scalar/block_until_ready/device reads) in "
    "dispatch-route launch stages",
    check,
)
