"""``error-kind`` — ``QueryError`` carries only the four taxonomy kinds.

The error taxonomy (``serve/resilience.ERROR_KINDS``: ``invalid`` /
``timeout`` / ``capacity`` / ``internal``) is load-bearing far beyond
logging: the fleet router re-routes ``internal``/``capacity`` and never
``invalid``; only server-side kinds degrade ``/healthz``; the chaos
gates assert per-kind counters. A ``QueryError(..., kind="transient")``
would parse, serialize over the subprocess protocol, and silently fall
into the ``internal`` bucket at the far end — the ctor raises at
runtime, but only on the path that constructs it, which chaos coverage
may never drive.

The rule: every ``QueryError(...)`` construction must either omit
``kind`` or pass a string literal from the taxonomy. Non-literal kinds
are allowed only in ``serve/resilience.py`` itself (``to_query_error``
is the one sanctioned dynamic constructor — it validates through the
ctor on a path tests do drive).
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import Rule, attr_chain

_TAXONOMY_HOME = "bibfs_tpu/serve/resilience.py"


def _check(project):
    from bibfs_tpu.serve.resilience import ERROR_KINDS

    findings = []
    for pf in project.files:
        if pf.rel.replace("\\", "/").endswith(_TAXONOMY_HOME):
            continue
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Call)
                    and attr_chain(node.func)[-1] == "QueryError"):
                continue
            kind = None
            for kw in node.keywords:
                if kw.arg == "kind":
                    kind = kw.value
            if kind is None:
                continue  # defaults to "internal"
            if isinstance(kind, ast.Constant) and kind.value in ERROR_KINDS:
                continue
            shown = (
                repr(kind.value) if isinstance(kind, ast.Constant)
                else "<non-literal>"
            )
            findings.append(Finding(
                "error-kind", pf.rel, node.lineno,
                f"QueryError kind={shown} is not a literal taxonomy "
                f"kind {ERROR_KINDS}; use to_query_error() for dynamic "
                "classification",
            ))
    return findings


RULE = Rule(
    "error-kind",
    "QueryError constructed only with the four taxonomy kinds",
    _check,
)
