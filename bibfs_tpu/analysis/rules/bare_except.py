"""``no-bare-except`` — failure handling names what it catches.

The breaker/flush/drain seams are exactly where a bare ``except:`` does
the most damage: it swallows ``KeyboardInterrupt`` and ``SystemExit``,
which is how a Ctrl-C mid-probe leaks a half-open breaker claim or a
drain loop becomes unkillable — both bugs this codebase has already
fixed once (CHANGES.md PR 4 review hardening) and must not re-grow.
``except Exception:`` (and deliberate ``except BaseException:`` with a
re-raise) remain legal; it is the anonymous catch-everything that is
banned.
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import Rule


def _check(project):
    findings = []
    for pf in project.files:
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                findings.append(Finding(
                    "no-bare-except", pf.rel, node.lineno,
                    "bare `except:` swallows KeyboardInterrupt/"
                    "SystemExit — catch Exception (or BaseException "
                    "with a re-raise) and name the intent",
                ))
    return findings


RULE = Rule(
    "no-bare-except",
    "no bare `except:` at failure-handling seams",
    _check,
)
