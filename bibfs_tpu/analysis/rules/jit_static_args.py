"""``jit-static-args`` — Python-static parameters of jitted callables
must be DECLARED static.

The weak-type retrace trap: a jitted callable that takes a Python
scalar (an ``int`` crossover, a ``str`` mode, a ``tuple`` of tier
metadata) without declaring it in ``static_argnums``/``static_argnames``
gets that value embedded as a weakly-typed traced operand — jax then
specializes (retraces + recompiles) on every distinct VALUE, or worse,
silently promotes dtypes per call. The serving stack's convention is to
close static config over the builder (``_build_kernel(mode, cap)``
returns a kernel whose jit signature is arrays only); when a def IS
jitted directly, its scalar-shaped parameters must be declared.

Two checks, both lexical:

- a def that is jit-decorated (``@jax.jit`` / ``@partial(jax.jit,
  ...)``) or passed by name to ``jax.jit(...)`` in the same file, with
  a parameter whose annotation or default value is a Python scalar /
  tuple (``int``, ``float``, ``bool``, ``str``, tuple literal), where
  that parameter is not covered by the jit call's literal
  ``static_argnums``/``static_argnames``;
- a call of a known-jitted name passing an **unhashable literal**
  (list/dict/set display) in a declared-static position — static args
  key the program cache, and an unhashable key raises at dispatch
  time, under traffic, instead of at review time.
"""

from __future__ import annotations

import ast

from bibfs_tpu.analysis.lint import Finding
from bibfs_tpu.analysis.rules.common import (
    Rule,
    attr_chain,
    is_jit_call,
    jit_decorator,
    jit_static_decls,
)

_SCALAR_ANNOTATIONS = frozenset(("int", "float", "bool", "str", "tuple"))


def _scalar_param_reason(arg, default):
    ann = arg.annotation
    if ann is not None:
        names = {n.id for n in ast.walk(ann) if isinstance(n, ast.Name)}
        hit = names & _SCALAR_ANNOTATIONS
        if hit:
            return f"annotated {sorted(hit)[0]}"
    if default is not None:
        if isinstance(default, ast.Constant) and isinstance(
                default.value, (int, float, bool, str)
        ) and not isinstance(default.value, type(...)):
            return f"default {default.value!r}"
        if isinstance(default, ast.Tuple):
            return "tuple default"
    return None


def _param_defaults(fn):
    """``(arg, default_node|None, positional_index|None)`` over every
    named parameter: positional-only and positional-or-keyword params
    carry their ``static_argnums`` index; keyword-only params carry
    ``None`` — only ``static_argnames`` can declare those static."""
    pos = list(fn.args.posonlyargs) + list(fn.args.args)
    defaults = [None] * (len(pos) - len(fn.args.defaults)) \
        + list(fn.args.defaults)
    rows = [(a, d, i) for i, (a, d) in enumerate(zip(pos, defaults))]
    rows += [(a, d, None) for a, d in
             zip(fn.args.kwonlyargs, fn.args.kw_defaults)]
    return rows


def _check_def(pf, fn, jit_call, findings):
    nums, names = jit_static_decls(jit_call)
    for arg, default, idx in _param_defaults(fn):
        if arg.arg in ("self", "cls"):
            continue
        reason = _scalar_param_reason(arg, default)
        if reason is None:
            continue
        if (idx is not None and idx in nums) or arg.arg in names:
            continue
        findings.append(Finding(
            "jit-static-args", pf.rel, fn.lineno,
            f"jitted {fn.name}(...{arg.arg}...) takes a Python-static "
            f"parameter ({reason}) not declared in static_argnums/"
            "static_argnames — jax retraces per distinct value (the "
            "weak-type retrace trap); declare it static or close it "
            "over the builder",
        ))


def check(project):
    findings = []
    for pf in project.files:
        defs_by_name = {
            n.name: n for n in ast.walk(pf.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # names bound to a jit call in a single-target assignment —
        #   g = jax.jit(f, static_argnums=(1,)); ... g(x, [..])
        # — mapped by the Call node's identity so the main walk can
        # look the target name up without re-walking the tree per call
        assign_target_by_call: dict[int, str] = {}
        for node in ast.walk(pf.tree):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                assign_target_by_call[id(node.value)] = node.targets[0].id
        jitted_statics: dict[str, set] = {}
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    jd = jit_decorator(deco)
                    if jd is not None:
                        _check_def(pf, node, jd, findings)
            if not (isinstance(node, ast.Call) and is_jit_call(node)):
                continue
            if node.args and isinstance(node.args[0], ast.Name):
                fn = defs_by_name.get(node.args[0].id)
                if fn is not None:
                    _check_def(pf, fn, node, findings)
            target = assign_target_by_call.get(id(node))
            if target is not None:
                nums, _names = jit_static_decls(node)
                if nums:
                    jitted_statics[target] = nums
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            nums = jitted_statics.get(node.func.id)
            if not nums:
                continue
            for idx in nums:
                if idx < len(node.args) and isinstance(
                        node.args[idx],
                        (ast.List, ast.Dict, ast.Set)):
                    findings.append(Finding(
                        "jit-static-args", pf.rel, node.lineno,
                        f"unhashable literal passed in static position "
                        f"{idx} of jitted {node.func.id}(...) — static "
                        "args key the program cache and must hash; "
                        "this raises at dispatch time under traffic",
                    ))
    return findings


RULE = Rule(
    "jit-static-args",
    "Python-scalar/tuple params of jitted defs must be declared "
    "static; static positions must receive hashables",
    check,
)
