"""``bibfs-lint`` — static invariant lints for the serving stack.

The framework half of :mod:`bibfs_tpu.analysis` (the rules live in
:mod:`bibfs_tpu.analysis.rules`): parse every package source file once,
run each registered rule over the project, apply per-line suppressions,
and exit non-zero on any unsuppressed finding — the CI gate shape.

**Suppressions.** A finding is silenced by a marker on its own line or
on a standalone comment line directly above it::

    self._f.write(rec)  # bibfs: allow(lock-io): WAL append IS the ack

The justification after the colon is REQUIRED — a suppression without
one is itself a finding (``suppression``), as is a suppression that no
finding matched (the allow-list must not rot). Suppressions are for
deliberate, documented trades; bugs get fixed.

**Scope.** The default project is every ``*.py`` under ``bibfs_tpu/``
plus the README cross-checks; rules narrow further where the invariant
is local (``atomic-write`` covers the served-data modules ``store/`` +
``graph/``). Tests and benches are out of scope — they may construct
whatever bad states they like.

CLI::

    bibfs-lint [PATHS...]          # lint (default: the whole package)
    bibfs-lint --list-rules        # one line per rule
    bibfs-lint --json              # machine-readable findings
    bibfs-lint --lock-report F     # render a lockgraph JSON artifact
                                   # (exit 1 if it recorded cycles)
    bibfs-lint --compile-report F  # render a compilegraph JSON artifact
                                   # (exit 1 on anonymous/over-budget)
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize

_SUPPRESS_RE = re.compile(
    r"#\s*bibfs:\s*allow\(\s*([a-z0-9_\-, ]+?)\s*\)\s*(?::\s*(\S.*))?$"
)


class Finding:
    """One lint finding, anchored to file:line."""

    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message

    def __repr__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "message": self.message}


class _Suppression:
    __slots__ = ("line", "rules", "justification", "used")

    def __init__(self, line: int, rules, justification):
        self.line = line
        self.rules = frozenset(rules)
        self.justification = justification
        self.used = False


class ParsedFile:
    """One source file: AST + lines + its suppression markers."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        # line -> suppression; a marker on a pure-comment line also
        # covers the next line (long expressions keep their markers
        # readable). Markers are read from COMMENT tokens only — a
        # docstring that merely quotes the syntax is not a suppression.
        self.suppressions: dict[int, _Suppression] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline
            ))
        except tokenize.TokenError:
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            i = tok.start[0]
            rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
            supp = _Suppression(i, rules, (m.group(2) or "").strip())
            self.suppressions[i] = supp
            if self.lines[i - 1].lstrip().startswith("#"):
                self.suppressions.setdefault(i + 1, supp)


class Project:
    """The lint unit of work: a set of parsed files under one root.

    ``complete=True`` (the default full-package scan) additionally
    enables the whole-project cross-checks — "every canonical metric
    name is minted somewhere", the README table reconciliation — that
    make no sense over a test fixture's file or two."""

    def __init__(self, root: str, files, *, complete: bool):
        self.root = os.path.abspath(root)
        self.files: list[ParsedFile] = list(files)
        self.complete = complete
        self.errors: list[Finding] = []

    @classmethod
    def load(cls, root: str, paths=None) -> "Project":
        root = os.path.abspath(root)
        complete = paths is None
        if paths is None:
            paths = []
            pkg = os.path.join(root, "bibfs_tpu")
            for dirpath, dirnames, filenames in os.walk(pkg):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        paths.append(os.path.join(dirpath, fn))
        files, errors = [], []
        for p in sorted(paths):
            rel = os.path.relpath(p, root)
            try:
                with open(p, encoding="utf-8") as f:
                    src = f.read()
                files.append(ParsedFile(p, rel, src))
            except (OSError, SyntaxError) as e:
                errors.append(Finding(
                    "parse", rel, getattr(e, "lineno", 0) or 0,
                    f"unparseable: {type(e).__name__}: {e}",
                ))
        proj = cls(root, files, complete=complete)
        proj.errors = errors
        return proj

    def readme(self) -> str | None:
        path = os.path.join(self.root, "README.md")
        try:
            with open(path, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


def run(project: Project):
    """Run every registered rule; returns
    ``(findings, suppressed, suppression_findings)`` where ``findings``
    is the unsuppressed list the gate fails on."""
    from bibfs_tpu.analysis.rules import RULES

    raw: list[Finding] = list(project.errors)
    for rule in RULES:
        raw.extend(rule.check(project))
    by_rel = {f.rel: f for f in project.files}
    open_findings: list[Finding] = []
    suppressed: list[Finding] = []
    for finding in raw:
        pf = by_rel.get(finding.path)
        supp = None
        if pf is not None:
            supp = pf.suppressions.get(finding.line)
            if supp is not None and finding.rule not in supp.rules:
                supp = None
        if supp is None:
            open_findings.append(finding)
        else:
            supp.used = True
            suppressed.append(finding)
    # the suppression ledger must stay honest: every marker needs a
    # justification, and must actually silence something
    for pf in project.files:
        seen = set()
        for supp in pf.suppressions.values():
            if id(supp) in seen:
                continue
            seen.add(id(supp))
            if not supp.justification:
                open_findings.append(Finding(
                    "suppression", pf.rel, supp.line,
                    "suppression without a justification — write "
                    "`# bibfs: allow(<rule>): <why this trade is "
                    "deliberate>`",
                ))
            if not supp.used:
                open_findings.append(Finding(
                    "suppression", pf.rel, supp.line,
                    f"unused suppression for "
                    f"{', '.join(sorted(supp.rules))} — nothing fires "
                    "here; remove the stale marker",
                ))
    open_findings.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return open_findings, suppressed


def _repo_root() -> str:
    """The repository root: the directory holding ``bibfs_tpu/``."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bibfs-lint",
        description="static invariant lints for the bibfs serving "
                    "stack (+ lock-order report renderer)",
    )
    ap.add_argument("paths", nargs="*", help="files to lint (default: "
                    "every bibfs_tpu/ source + project cross-checks)")
    ap.add_argument("--root", default=None,
                    help="project root (default: this checkout)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print findings silenced by allow markers")
    ap.add_argument("--lock-report", metavar="JSON", default=None,
                    help="render a lock-graph artifact recorded under "
                    "BIBFS_LOCK_CHECK=1 instead of linting")
    ap.add_argument("--compile-report", metavar="JSON", default=None,
                    help="render a compile-graph artifact recorded "
                    "under BIBFS_COMPILE_CHECK=1 instead of linting "
                    "(exit 1 on anonymous or over-budget compiles)")
    args = ap.parse_args(argv)

    if args.lock_report is not None or args.compile_report is not None:
        renders = []
        if args.lock_report is not None:
            from bibfs_tpu.analysis.lockgraph import (
                render_report_file as render_lock,
            )
            renders.append((render_lock, args.lock_report))
        if args.compile_report is not None:
            from bibfs_tpu.analysis.compilegraph import (
                render_report_file as render_compile,
            )
            renders.append((render_compile, args.compile_report))

        # both flags render both artifacts; exit 1 if EITHER gate is
        # red. Every verdict is computed BEFORE any printing so a
        # consumer closing the pipe early (`... | head`) cannot skip a
        # red gate.
        rendered = [render(path) for render, path in renders]
        all_ok = all(ok for _text, ok in rendered)
        try:
            print("\n\n".join(text for text, _ok in rendered))
        except BrokenPipeError:
            # `bibfs-lint --lock-report f | head` closing the pipe is
            # not an error; the verdict is what matters
            sys.stderr.close()
        return 0 if all_ok else 1

    from bibfs_tpu.analysis.rules import RULES

    if args.list_rules:
        for rule in RULES:
            print(f"{rule.name:16s} {rule.summary}")
        return 0

    root = args.root or _repo_root()
    project = Project.load(root, args.paths or None)
    findings, suppressed = run(project)
    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": [f.to_dict() for f in suppressed],
        }, indent=1))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
        if args.show_suppressed:
            for f in suppressed:
                print(f"{f.path}:{f.line}: [{f.rule}] (suppressed) "
                      f"{f.message}")
        print(
            f"bibfs-lint: {len(findings)} finding(s), "
            f"{len(suppressed)} suppressed, "
            f"{len(project.files)} files",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
