"""Dynamic retrace sentinel (``BIBFS_COMPILE_CHECK=1``) — the
lockgraph's compile-discipline twin.

The static ``jit-cache`` / ``jit-static-args`` lints prove the lexical
half of compile discipline: every ``jax.jit`` site sits in a memoized
builder and static Python config is declared static. What they cannot
prove is the DYNAMIC property the serving stack actually depends on —
that under live traffic **no compiled program is created outside the
declared program families, and no family compiles more often than its
shape ladder allows**. One weak-typed scalar or anonymously-jitted
helper turns a ~20 µs dispatch into a multi-second XLA compile, and it
never shows up in ``ExecutableCache.program_counts()`` because nothing
routed it there. This module proves the property on the real test
suite:

- :func:`install` hooks JAX's lowering choke point (the
  ``Compiling <fun> with global shapes and types <avals>`` record that
  ``jax._src.interpreters.pxla`` emits once per trace+lower+compile —
  the log *record* is the hook, no jax internals are monkeypatched, and
  the handler never lets an instrumentation error escape into the
  compile itself). Every compilation event records its **program
  label** (the traced callable's name), its **creation call-site frame
  in repo code** (the innermost ``bibfs_tpu`` frame on the stack at
  compile time — compiles are synchronous, so the dispatching line is
  on the stack), its **abstract-value signature**, and the
  **ExecutableCache key** the dispatch was accounted under, if any
  (``ExecutableCache.note`` publishes the key thread-locally just
  before the solve that may compile).
- Programs are identified as ``<repo-module>:<label>`` and must appear
  in :data:`PROGRAM_BUDGETS` with a **declared compile budget** — the
  number of distinct shape/mode specializations a full serving-suite
  run is allowed to pay for that family. A compile whose program id is
  undeclared is **anonymous**; a family that exceeds its budget is a
  **retrace leak**. Both fail the session gate.
- ``tests/conftest.py`` installs this under ``BIBFS_COMPILE_CHECK=1``
  and writes the JSON report (``BIBFS_COMPILE_REPORT``, default
  ``compilegraph.json``) at session end, failing the session on any
  violation; ``bibfs-lint --compile-report FILE`` renders the artifact
  for humans; the bench soaks' ``zero_recompiles`` gates re-derive
  from :meth:`CompileGraph.total_compiles` deltas instead of
  hand-diffed ``program_counts()`` snapshots — the sentinel counts
  *actual XLA compiles*, which is strictly stronger than cache-key
  accounting.

Compiles triggered with **no** repo frame on the stack (a test or
script jitting directly) are recorded under ``external`` and reported
but not gated — the package cannot own their discipline.

Soundness note: the hook fires once per trace+lowering. A persistent
XLA compilation cache could make the *backend* compile cheap while the
retrace still burns the dispatch path — counting lowerings (not
backend compiles) is therefore the right currency for the serving
invariant. While installed, the sentinel owns the pxla compile log
record (``propagate`` is disabled on that one logger) so enabling it
does not spray DEBUG lines through the session's logging config.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
import _thread

ENV_VAR = "BIBFS_COMPILE_CHECK"
REPORT_ENV = "BIBFS_COMPILE_REPORT"
DEFAULT_REPORT = "compilegraph.json"

_REPO_MARKER = os.sep + "bibfs_tpu" + os.sep
_ANALYSIS_MARKER = os.sep + "analysis" + os.sep

#: declared compile budgets per program family, keyed
#: ``<repo-module>:<traced-callable name>`` where the module is the
#: repo-relative file of the DISPATCH call site (the innermost
#: bibfs_tpu frame at compile time — stable across line churn, unlike
#: line numbers). The budget is the number of distinct compiled
#: specializations a full serving-suite session may pay: one per
#: (padded shape x mode x batch rung x mesh geometry) the suite's
#: traffic legitimately reaches, with ~2x headroom so a new test adds
#: a shape without tripping the gate — while a per-call retrace leak
#: (hundreds of compiles) still fails loudly. A program NOT in this
#: table is an anonymous compile and fails the session outright: new
#: kernels must declare themselves here (and route their dispatch
#: accounting through ExecutableCache — the jit-cache lint's other
#: half).
PROGRAM_BUDGETS: dict[str, int] = {
    # single-device point-to-point kernels (solvers/dense.py builders,
    # dispatched from the batch-minor/dense dispatch seams)
    "bibfs_tpu/solvers/batch_minor.py:minor_kernel": 256,
    "bibfs_tpu/solvers/dense.py:dense_kernel": 192,
    "bibfs_tpu/solvers/dense.py:dense_fused_kernel": 32,
    "bibfs_tpu/solvers/dense.py:dense_fused_alt_kernel": 32,
    "bibfs_tpu/solvers/dense.py:traced_side_step": 64,
    "bibfs_tpu/solvers/dense.py:traced_meet_vote": 16,
    "bibfs_tpu/solvers/dense.py:blocked_kernel": 96,
    "bibfs_tpu/solvers/batch_minor.py:blocked_kernel": 96,
    # multi-source / weighted / k-shortest device programs
    "bibfs_tpu/ops/msbfs_device.py:msbfs_kernel": 96,
    "bibfs_tpu/ops/msbfs_device.py:msbfs_blocked_kernel": 48,
    "bibfs_tpu/oracle/trees.py:msbfs_kernel": 48,
    "bibfs_tpu/solvers/query_device.py:delta_kernel": 64,
    "bibfs_tpu/solvers/query_device.py:restricted_kernel": 96,
    # mesh-sharded programs (1D vertex-sharded, dp query-sharded, 2D)
    "bibfs_tpu/solvers/sharded.py:sharded_kernel": 96,
    "bibfs_tpu/solvers/sharded.py:sharded_fused_kernel": 32,
    "bibfs_tpu/solvers/sharded2d.py:sharded2d_kernel": 64,
    "bibfs_tpu/solvers/batch_minor.py:dp_minor_kernel": 96,
    # checkpoint/resume chunked drives + pallas table prep
    "bibfs_tpu/solvers/checkpoint.py:dense_chunk_kernel": 48,
    "bibfs_tpu/solvers/checkpoint.py:sharded_chunk_kernel": 48,
    "bibfs_tpu/solvers/checkpoint.py:sharded2d_chunk_kernel": 48,
    "bibfs_tpu/solvers/checkpoint.py:prepare_pallas_tables": 16,
    "bibfs_tpu/ops/pallas_expand.py:prepare_pallas_tables": 16,
    # calibration probes (bench-time only; tiny)
    "bibfs_tpu/utils/calibrate.py:dispatch_probe": 16,
    "bibfs_tpu/utils/calibrate.py:pull_loop": 16,
    "bibfs_tpu/utils/calibrate.py:push_loop": 16,
}

#: incidental jax-library programs legitimately compiled FROM repo code
#: (device uploads, scalar reads, implicit conversions) — a shared
#: generous budget each, still bounded so an accidental per-call
#: host-op in a hot loop cannot hide here. Keyed by label only: these
#: are jax-internal callables reached from many repo modules.
INCIDENTAL_BUDGET = 64
INCIDENTAL_LABELS = frozenset((
    # jnp wrapper closures and the eager-op jits jax compiles when repo
    # host code runs jnp operations outside a kernel (decode paths, the
    # blocked route's chunked eager matmuls, upload prep). The names
    # are jax's own (lax primitive wrappers); a session hitting a NEW
    # one fails with the exact label to add here — deliberate review
    # friction, since an unrecognized label is also what a leaked
    # helper looks like. Generic throwaway names (fn, kernel, wrapped)
    # stay OUT of this list on purpose: they are what a leaked helper
    # is actually called.
    "_where", "where", "select_n",
    "_threefry_seed", "_threefry_split", "_uniform",
    "convert_element_type", "_convert_element_type",
    "reshape", "ravel", "_squeeze", "squeeze", "expand_dims",
    "broadcast_in_dim", "concatenate", "transpose", "tile", "pad",
    "iota", "_multi_slice", "dynamic_slice", "_take", "take",
    "_take_along_axis", "gather", "scatter", "dot_general",
    "add", "subtract", "multiply", "true_divide", "floor_divide",
    "remainder", "_power", "maximum", "minimum", "clip",
    "greater", "greater_equal", "less", "less_equal", "equal",
    "not_equal", "logical_or", "logical_and", "logical_not",
    "bitwise_or", "bitwise_and", "invert",
    "_reduce_sum", "_reduce_max", "_reduce_min", "_reduce_or",
    "_reduce_and", "sum", "amax", "amin", "any", "all",
    "argmax", "argmin", "cumsum", "sort", "argsort", "searchsorted",
))

#: anonymous events retained in full (stack and avals); the total
#: count keeps incrementing past the cap and still fails the gate
_ANON_KEEP = 100

#: routed-key claim window: a first compile starts within microseconds
#: of its dispatch's note() — generous slack for a slow trace under
#: load, still far below the gap to an unrelated later compile
_KEY_TTL_S = 10.0

_STATE: "CompileGraph | None" = None
_INSTALLED: "tuple | None" = None  # (handler, [(logger, level, propagate)])


class CompileGraph:
    """The process-global compile-event graph (module docstring)."""

    def __init__(self):
        # raw primitive: under BIBFS_LOCK_CHECK the lockgraph patches
        # threading.Lock for bibfs-created locks — the sentinels must
        # not tax (or deadlock-order) each other
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()
        self._total = 0
        self._programs: dict[str, dict] = {}
        self._anonymous: list[dict] = []
        self._anonymous_total = 0
        self._external: dict[str, dict] = {}

    # ---- dispatch-side attribution -----------------------------------
    def note_routed_key(self, key) -> None:
        """Publish the ExecutableCache key of the dispatch this thread
        is about to run — a compile event on this thread attributes to
        it (compiles are synchronous with the dispatch that pays them).

        The ``routed`` column is best-effort DIAGNOSTIC attribution
        (the gates never read it); three bounds keep it honest: the
        key is SINGLE-SHOT (the first declared-family compile that
        reads it consumes it), superseded by the next publication on
        the thread, and it EXPIRES after ``_KEY_TTL_S`` seconds — so a
        key published for a dispatch that never compiled (first-seen
        cache key over an already-warm kernel memo, or an accounting
        call with no solve) cannot be claimed by an unrelated compile
        long after. :meth:`clear_routed_key` retires it early on a
        cache HIT: no first compile is expected there, and a retrace
        that reuses a noted key is exactly a compile the accounting
        layer did NOT pay for — reporting it unrouted is the signal."""
        self._tls.key = str(key)
        self._tls.key_ts = time.monotonic()

    def clear_routed_key(self) -> None:
        self._tls.key = None

    def _take_routed_key(self) -> str | None:
        key = getattr(self._tls, "key", None)
        self._tls.key = None
        if key is None:
            return None
        if time.monotonic() - getattr(self._tls, "key_ts", 0.0) > _KEY_TTL_S:
            return None  # expired: published for a dispatch long gone
        return key

    # ---- the compile hook --------------------------------------------
    def note_compile(self, label: str, avals: str) -> None:
        """Record one compilation event (called by the log hook)."""
        site, module = _repo_site()
        if module is None:
            self._note_external(label, site)
            return
        pid = f"{module}:{label}"
        declared = pid in PROGRAM_BUDGETS
        budget = PROGRAM_BUDGETS.get(pid)
        if budget is None and label in INCIDENTAL_LABELS:
            budget = INCIDENTAL_BUDGET
        # only a DECLARED family's compile consumes the published key:
        # incidental jax-library programs compiled mid-trace must not
        # eat (or claim) the dispatch's attribution
        key = self._take_routed_key() if declared else None
        with self._mu:
            self._total += 1
            if budget is None:
                # bounded retention: in the pathological case this
                # sentinel exists for (a per-call retrace leak in a
                # long soak) the event list must not grow with the
                # leak — keep the first _ANON_KEEP full events, count
                # the rest (the count still fails the gate)
                self._anonymous_total += 1
                if len(self._anonymous) >= _ANON_KEEP:
                    return
                self._anonymous.append({
                    "program": pid,
                    "label": label,
                    "site": site,
                    "avals": avals,
                    "routed_key": key,
                    "thread": threading.current_thread().name,
                    "stack": _stack(),
                })
                return
            row = self._programs.get(pid)
            if row is None:
                row = self._programs[pid] = {
                    "program": pid,
                    "label": label,
                    "budget": budget,
                    "compiles": 0,
                    "sites": set(),
                    "routed_keys": set(),
                    "avals_sample": avals,
                }
            row["compiles"] += 1
            row["sites"].add(site)
            if key is not None:
                row["routed_keys"].add(key)

    def _note_external(self, label: str, site: str | None) -> None:
        key = f"{site or '?'}:{label}"
        with self._mu:
            self._total += 1
            row = self._external.get(key)
            if row is None:
                self._external[key] = {
                    "label": label, "site": site or "?", "compiles": 1,
                }
            else:
                row["compiles"] += 1

    # ---- introspection -----------------------------------------------
    def total_compiles(self) -> int:
        """Every compilation event recorded so far — the soak gates'
        currency: a ``zero_recompiles`` window is a zero DELTA here."""
        with self._mu:
            return self._total

    def violations(self) -> dict:
        """``{"anonymous": [...], "over_budget": [...]}`` — the session
        gate fails when either list is non-empty."""
        with self._mu:
            over = [
                {
                    "program": r["program"],
                    "compiles": r["compiles"],
                    "budget": r["budget"],
                    "sites": sorted(r["sites"]),
                }
                for r in self._programs.values()
                if r["compiles"] > r["budget"]
            ]
            return {
                "anonymous": list(self._anonymous),
                "over_budget": over,
            }

    def report(self) -> dict:
        """The JSON artifact (the committed ``compilegraph.json``
        shape): one row per declared program family, the anonymous and
        external event lists, and the gate verdicts."""
        with self._mu:
            programs = sorted((
                {
                    "program": r["program"],
                    "label": r["label"],
                    "compiles": r["compiles"],
                    "budget": r["budget"],
                    "over_budget": r["compiles"] > r["budget"],
                    "routed": bool(r["routed_keys"]),
                    "sites": sorted(r["sites"]),
                    "routed_keys": sorted(r["routed_keys"])[:8],
                    "avals_sample": r["avals_sample"][:200],
                }
                for r in self._programs.values()
            ), key=lambda r: r["program"])
            return {
                "schema": "bibfs-compilegraph-v1",
                "total_compiles": self._total,
                "programs": programs,
                "anonymous": list(self._anonymous),
                "anonymous_total": self._anonymous_total,
                "external": sorted(
                    self._external.values(),
                    key=lambda r: (r["site"], r["label"]),
                ),
            }


def _repo_site() -> tuple[str | None, str | None]:
    """``(site, module)`` of the innermost repo frame on the stack:
    ``site`` is ``file.py:line``, ``module`` the repo-relative file the
    program id keys on. ``(external_site, None)`` when no repo frame is
    present (a test/script compiling directly)."""
    fallback = None
    for fr in reversed(traceback.extract_stack()):
        fn = fr.filename
        i = fn.rfind(_REPO_MARKER)
        if i >= 0:
            rel = fn[i + 1:]
            if _ANALYSIS_MARKER in rel:
                continue  # the sentinel itself never owns a program
            return f"{rel}:{fr.lineno}", rel
        if (fallback is None
                and "site-packages" not in fn
                and os.sep + "logging" + os.sep not in fn
                and not fn.startswith("<")):
            fallback = f"{os.path.basename(fn)}:{fr.lineno}"
    return fallback, None


_STACK_LIMIT = 14


def _stack() -> list:
    out = []
    for fr in traceback.extract_stack(limit=_STACK_LIMIT + 8)[:-3]:
        fn = fr.filename
        i = fn.rfind(_REPO_MARKER)
        if i >= 0:
            fn = fn[i + 1:]
        out.append(f"{fn}:{fr.lineno} in {fr.name}")
    return out[-_STACK_LIMIT:]


def _make_handler(state: CompileGraph):
    """The hook: a logging.Handler over the one pxla record emitted per
    trace+lower+compile. Defined lazily (logging imported at install)
    so this module stays import-light for bench.py/CI scripts."""
    import logging

    class Handler(logging.Handler):
        def emit(self, record):
            try:
                if not str(record.msg).startswith("Compiling"):
                    return
                args = record.args or ()
                label = str(args[0]) if args else "?"
                avals = str(args[1]) if len(args) > 1 else ""
                state.note_compile(label, avals)
            except Exception:  # pragma: no cover - never break a compile
                pass

    return Handler(level=logging.DEBUG)


#: the loggers that emit the per-compile record (both the pjit path and
#: the jit(pmap) legacy path log from interpreters/pxla)
_HOOKED_LOGGERS = ("jax._src.interpreters.pxla",)


def install() -> CompileGraph:
    """Activate the sentinel process-wide (idempotent). Needs no jax
    import and no patching of jax internals — attaching the handler
    before jax itself imports is fine (logger objects are created on
    first ``getLogger`` and shared). :func:`uninstall` undoes it
    completely (handler off, logger level/propagate restored) — a
    scoped user like the churn soak must not leave jax's own compile
    logging hijacked for the rest of an embedding process."""
    global _STATE, _INSTALLED
    if _STATE is not None:
        return _STATE
    import logging

    _STATE = CompileGraph()
    handler = _make_handler(_STATE)
    saved = []
    for name in _HOOKED_LOGGERS:
        lg = logging.getLogger(name)
        saved.append((lg, lg.level, lg.propagate))
        lg.addHandler(handler)
        lg.setLevel(logging.DEBUG)
        # the sentinel owns this record while installed: without this a
        # DEBUG-configured root handler would spray one line per compile
        lg.propagate = False
    _INSTALLED = (handler, saved)
    return _STATE


def uninstall() -> None:
    """Deactivate the sentinel and restore every hooked logger to its
    pre-install level/propagation (no-op when not installed)."""
    global _STATE, _INSTALLED
    if _INSTALLED is None:
        return
    handler, saved = _INSTALLED
    for lg, level, propagate in saved:
        lg.removeHandler(handler)
        lg.setLevel(level)
        lg.propagate = propagate
    _INSTALLED = None
    _STATE = None


def enabled() -> bool:
    return _STATE is not None


def graph() -> CompileGraph | None:
    return _STATE


def note_routed_key(key) -> None:
    """ExecutableCache's attribution seam — no-op when the sentinel is
    off (one global read on the dispatch path)."""
    state = _STATE
    if state is not None:
        state.note_routed_key(key)


def clear_routed_key() -> None:
    """Retire the published key (a cache HIT: the dispatch expects no
    first compile, so nothing later may claim its attribution)."""
    state = _STATE
    if state is not None:
        state.clear_routed_key()


def total_compiles() -> int:
    return 0 if _STATE is None else _STATE.total_compiles()


def save_report(path: str) -> dict:
    """Write the JSON artifact (the committed ``compilegraph.json``
    shape) atomically and return the report dict. Safe with the
    sentinel off (writes an empty report)."""
    rep = (
        _STATE.report() if _STATE is not None
        else {"schema": "bibfs-compilegraph-v1", "total_compiles": 0,
              "programs": [], "anonymous": [], "anonymous_total": 0,
              "external": []}
    )
    from bibfs_tpu.graph.io import _atomic_replace

    def _payload(f):
        f.write(json.dumps(rep, indent=1, sort_keys=True))
        f.write("\n")

    _atomic_replace(path, _payload, mode="w")
    return rep


# ---- renderer (bibfs-lint --compile-report) ---------------------------
def render_report(rep: dict) -> tuple[str, bool]:
    """Human-readable rendering of a report dict; ``ok`` is False when
    the run recorded anonymous or over-budget compiles."""
    programs = rep.get("programs", [])
    anonymous = rep.get("anonymous", [])
    anon_total = rep.get("anonymous_total", len(anonymous))
    external = rep.get("external", [])
    over = [r for r in programs if r.get("over_budget")]
    lines = [
        f"compile graph: {rep.get('total_compiles', 0)} compile events, "
        f"{len(programs)} declared program families, "
        f"{anon_total} anonymous, {len(over)} over budget, "
        f"{len(external)} external",
        "",
        "declared programs (compiles/budget, routed = accounted in an "
        "ExecutableCache):",
    ]
    for r in programs:
        routed = "routed" if r.get("routed") else "unrouted"
        lines.append(
            f"  {r['program']:48s} {r['compiles']:4d}/{r['budget']:<4d}"
            f" {routed}"
        )
    if external:
        lines.append("")
        lines.append("external compiles (no repo frame — not gated):")
        for r in external:
            lines.append(f"  {r['site']:40s} {r['label']:24s}"
                         f" x{r['compiles']}")
    if anonymous:
        lines.append("")
        lines.append("ANONYMOUS COMPILES (undeclared program families — "
                     "the build gate fails):")
        for ev in anonymous:
            lines.append(f"  {ev['program']}  at {ev['site']}")
            for fr in ev.get("stack", []):
                lines.append(f"      {fr}")
        if anon_total > len(anonymous):
            lines.append(f"  ... and {anon_total - len(anonymous)} more "
                         "(event retention capped)")
    if over:
        lines.append("")
        lines.append("OVER-BUDGET PROGRAMS (retrace leaks — the build "
                     "gate fails):")
        for r in over:
            lines.append(f"  {r['program']}: {r['compiles']} compiles "
                         f"> budget {r['budget']}")
            for s in r["sites"][:6]:
                lines.append(f"      dispatched at {s}")
    return "\n".join(lines), not anonymous and not over


def render_report_file(path: str) -> tuple[str, bool]:
    with open(path) as f:
        rep = json.load(f)
    return render_report(rep)
