"""Correctness tooling for the serving stack: ``bibfs-lint`` + the
dynamic lock-order detector.

PRs 4-8 turned the reproduction into a concurrent serving system whose
hardest bugs were never solver math — they were lock-ordering,
atomicity and ack-durability invariants that lived only in prose
(CHANGES.md's "round-2/round-3 hardening" entries are the fossil
record). This package turns those invariants into machine checks that
fail CI when a future change regresses them silently:

- :mod:`bibfs_tpu.analysis.lint` — static AST lints over the package
  (rule framework + the rules in :mod:`bibfs_tpu.analysis.rules`):
  atomic served-file writes, ``@guarded_by`` lock-discipline on shared
  attributes, no blocking I/O under locks, the ``QueryError`` taxonomy,
  the canonical metric-name list, no bare excepts. ``bibfs-lint`` is
  the CLI; CI gates on zero unsuppressed findings.
- :mod:`bibfs_tpu.analysis.lockgraph` — an opt-in
  (``BIBFS_LOCK_CHECK=1``) instrumented wrapper for ``threading.Lock``
  / ``RLock`` / ``Condition`` that records per-thread held-lock stacks,
  builds the global lock-acquisition-order graph, fails fast on cycles
  (both acquisition stacks printed), and flags blocking calls made
  while holding an instrumented lock. Wired through
  ``tests/conftest.py``, so the serving test suite doubles as the race
  harness; ``bibfs-lint --lock-report`` renders the JSON artifact.
- :mod:`bibfs_tpu.analysis.compilegraph` — the lockgraph's
  compile-discipline twin (``BIBFS_COMPILE_CHECK=1``): every JAX
  compilation event attributed to a declared program family with a
  compile budget; anonymous or over-budget compiles fail the session
  with the repo call site named. ``bibfs-lint --compile-report``
  renders ``compilegraph.json``.

:func:`guarded_by` is the runtime-inert class annotation the
``guarded-by`` rule reads.
"""

from bibfs_tpu.analysis.annotations import guarded_by  # noqa: F401
