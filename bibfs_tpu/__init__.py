"""bibfs_tpu — a TPU-native bidirectional-BFS framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
Bidirectional-BFS project (four solver backends: serial CPU, MPI bitset,
CUDA single-GPU, hybrid MPI+CUDA). Instead of four copy-pasted mains, this
framework exposes ONE solver API with multiple backends:

- ``serial``  — host NumPy oracle (reference v1, v1/main-v1.cpp:50-81)
- ``native``  — C++ serial solver via ctypes (native-runtime v1 parity)
- ``dense``   — single-chip JAX solver, device-resident ``lax.while_loop``
                (reference v3, v3/bibfs_cuda_only.cu:173-203, without the
                per-level host round-trips of v4/comp.cu:84-107)
- ``sharded`` — multi-chip ``shard_map`` solver over a 1D vertex-partitioned
                mesh with psum/all_gather collectives (reference v2+v4,
                v2/second_try.cpp:68-129 + v4/mpi_bas.cpp:79-132, with real
                owner-computes partitioning instead of full replication)
- ``sharded2d`` — Graph500-style 2D block partition over an R x C mesh:
                per-level frontier traffic O(n/C + n/R) instead of O(n)
                (beyond-reference; solvers/sharded2d.py)

Graph data layer is bit-compatible with the reference binary format
(uint32 N, uint32 M, M uint32 pairs; graphs/generate_graph.py:35-39).
"""

__version__ = "0.1.0"

from bibfs_tpu.solvers.api import BFSResult, solve, SOLVERS  # noqa: F401
