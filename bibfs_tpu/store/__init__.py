"""Versioned graph store: immutable snapshots, live edge updates, and
atomic multi-graph hot-swap.

The serving-stack analog of model hot-swap in an inference stack: a
:class:`GraphStore` names graphs and versions them as content-addressed
:class:`GraphSnapshot` s; a :class:`DeltaOverlay` holds batched edge
inserts/deletes with exact overlay-corrected query answering until a
background compaction folds them into a fresh snapshot; the engines
(``bibfs_tpu/serve``) resolve names to snapshots per flush and finish
in-flight batches on the version they started on. With ``wal_dir``
set, a per-graph write-ahead log (``store/wal``) makes every acked
update crash-durable, compactions double as crash-consistent
checkpoints (atomic ``.bin`` + manifest rename + WAL segment switch),
and ``GraphStore.from_dir(durable=True)`` recovers manifest + replay.
Checkpoints additionally commit an **arrays sidecar**
(``store/sidecar``) that recovery ``np.memmap``s instead of
rebuilding — replicas on one store directory share a single
page-cache-resident copy — and a ``residency_budget`` arms the
cold-tier accountant (``graph/compress``).
"""

from bibfs_tpu.store.delta import DeltaOverlay  # noqa: F401
from bibfs_tpu.store.registry import GraphStore  # noqa: F401
from bibfs_tpu.store.sidecar import (  # noqa: F401
    SidecarMap,
    load_sidecar,
    sidecar_dir_name,
    write_sidecar,
)
from bibfs_tpu.store.snapshot import (  # noqa: F401
    GraphSnapshot,
    content_digest,
    next_version,
)
from bibfs_tpu.store.wal import (  # noqa: F401
    DURABLE_METRIC_FAMILIES,
    FSYNC_POLICIES,
    WalWriter,
    read_wal,
    repair_wal,
)
