"""Per-graph write-ahead log — the durability floor under live updates.

Every live edge update a :class:`~bibfs_tpu.store.GraphStore` acks used
to live only in the in-process :class:`~bibfs_tpu.store.DeltaOverlay`:
a SIGKILL'd serving process respawned from its seed ``.bin`` at v1,
silently discarding every acknowledged update. The WAL closes that
hole: :meth:`GraphStore.update` appends the batch here BEFORE it
commits to the overlay, and the ack only goes out once the record is
durable under the active fsync policy — so "acked" means "survives a
crash", by construction.

**Record format** (little-endian, length-prefixed, CRC-checked)::

    file   := header record*
    header := b"BWAL1\\n"                     (6 bytes)
    record := u32 payload_len | u32 crc32(payload) | payload
    payload:= u64 snapshot_version | u32 n_adds | u32 n_dels
              | n_adds x (u32 u, u32 v) | n_dels x (u32 u, u32 v)

A batch is one record: replay applies it atomically or not at all,
mirroring the overlay's staged-apply contract. Replay
(:func:`read_wal`) stops at the first torn or bad-CRC record — a crash
mid-append leaves a tail the next open truncates away
(:func:`repair_wal`); everything before it is intact because appends
are serialized and flushed in record order.

**Fsync policy** (``always`` / ``batch`` / ``off``) defines what
"durable" means for the ack:

- ``always`` — ``os.fsync`` after every append: an acked record
  survives OS/power loss. The strongest (and slowest) setting; the
  crash soak's regression gate ("an acked update is provably served
  after SIGKILL") runs under it.
- ``batch`` — group commit: the record is flushed to the OS on every
  append (surviving PROCESS death, the SIGKILL case) and fsync'd every
  ``batch_records`` appends and at every checkpoint/close. A bounded
  window of acked records can be lost to OS/power failure — the
  standard throughput trade, and the default.
- ``off`` — flush to the OS only; fsync only at checkpoint/close.

**Segments, not offsets.** One logical WAL per graph is stored as a
sequence of segment files ``<graph>.wal.<seq>``: a checkpoint captures
the overlay under the store lock and *switches to a fresh segment* in
the same locked section, so every record that races the checkpoint
build lands in the new segment and replays cleanly against the new
snapshot. The manifest records the first segment a recovery must
replay (``wal_seq``, with ``wal_offset`` always 0 — the byte offset a
single-file WAL would need is exactly what the segment switch makes
unnecessary); superseded segments are deleted after the manifest
commits, which is the crash-safe form of "truncate the WAL". Recovery
replays all surviving segments ``>= wal_seq`` in sequence order —
segments that outrun the manifest (a checkpoint that crashed between
the segment switch and the manifest commit) simply replay on top, in
the exact order their records were acked.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

from bibfs_tpu.analysis import guarded_by

# the durability metric families (README "Observability") — re-exported
# from the ONE canonical list (obs/names.py) the crash soak's render
# gate, the bench CI gate and the metric-mint lint all share
from bibfs_tpu.obs.names import DURABLE_METRIC_FAMILIES  # noqa: F401

_MAGIC = b"BWAL1\n"
_REC_HEAD = struct.Struct("<II")        # payload_len, crc32
_PAYLOAD_HEAD = struct.Struct("<QII")   # version, n_adds, n_dels

#: fsync policies (module docstring); parse/ctor reject anything else —
#: a typo'd policy must fail loudly, not silently weaken durability
FSYNC_POLICIES = ("always", "batch", "off")


def _encode_record(version: int, adds, dels) -> bytes:
    parts = [_PAYLOAD_HEAD.pack(int(version), len(adds), len(dels))]
    for u, v in adds:
        parts.append(struct.pack("<II", int(u), int(v)))
    for u, v in dels:
        parts.append(struct.pack("<II", int(u), int(v)))
    payload = b"".join(parts)
    return _REC_HEAD.pack(len(payload), zlib.crc32(payload)) + payload


def _decode_payload(payload: bytes):
    version, n_adds, n_dels = _PAYLOAD_HEAD.unpack_from(payload, 0)
    need = _PAYLOAD_HEAD.size + 8 * (n_adds + n_dels)
    if len(payload) != need:
        raise ValueError(
            f"payload length {len(payload)} != declared {need}"
        )
    off = _PAYLOAD_HEAD.size
    adds = [
        struct.unpack_from("<II", payload, off + 8 * i)
        for i in range(n_adds)
    ]
    off += 8 * n_adds
    dels = [
        struct.unpack_from("<II", payload, off + 8 * i)
        for i in range(n_dels)
    ]
    return version, adds, dels


def read_wal(path) -> tuple[list, int, bool]:
    """Replay one segment file. Returns ``(records, good_bytes, torn)``
    where ``records`` is a list of ``(version, adds, dels)`` batches,
    ``good_bytes`` is the byte length of the valid prefix, and ``torn``
    flags a torn/bad-CRC tail after it (replay stops there — the
    records beyond a corrupt point cannot be trusted). A missing file
    reads as empty; a file with a bad magic header reads as torn at
    byte 0 (nothing salvageable)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return [], 0, False
    if not data.startswith(_MAGIC):
        return [], 0, bool(data)
    records = []
    off = len(_MAGIC)
    while off < len(data):
        if off + _REC_HEAD.size > len(data):
            return records, off, True  # torn record header
        length, crc = _REC_HEAD.unpack_from(data, off)
        end = off + _REC_HEAD.size + length
        if length > len(data) or end > len(data):
            return records, off, True  # torn payload
        payload = data[off + _REC_HEAD.size: end]
        if zlib.crc32(payload) != crc:
            return records, off, True  # bad CRC
        try:
            records.append(_decode_payload(payload))
        except (ValueError, struct.error):
            return records, off, True  # internally inconsistent
        off = end
    return records, off, False


def repair_wal(path) -> tuple[list, bool]:
    """Replay a segment and TRUNCATE any torn/bad-CRC tail in place, so
    subsequent appends extend a provably-valid prefix. Returns
    ``(records, truncated)``."""
    records, good, torn = read_wal(path)
    if torn:
        with open(path, "r+b") as f:
            f.truncate(good)
    return records, torn


@guarded_by("_lock", "records", "fsyncs", "_since_fsync", "_f")
class WalWriter:
    """Append side of one segment file (module docstring format).

    Thread-safe (the store appends under its own lock anyway, but a
    checkpoint's final ``sync()`` may race a closing writer). ``fire``
    is the store's fault-injection hook — called with ``"wal_write"``
    before each append and ``"wal_fsync"`` before each fsync, so a
    chaos plan can fail exactly the seams a dying disk would.
    ``on_record``/``on_fsync`` are metric callbacks (registry counter
    cells in the store)."""

    def __init__(self, path, *, fsync: str = "batch",
                 batch_records: int = 64, fire=None,
                 on_record=None, on_fsync=None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"unknown fsync policy {fsync!r} "
                f"(known: {', '.join(FSYNC_POLICIES)})"
            )
        self.path = os.fspath(path)
        self.fsync = fsync
        self.batch_records = max(int(batch_records), 1)
        self._fire = fire
        self._on_record = on_record
        self._on_fsync = on_fsync
        self._lock = threading.Lock()
        self.records = 0
        self.fsyncs = 0
        self._since_fsync = 0
        self._f = open(self.path, "ab")
        if self._f.tell() == 0:
            self._f.write(_MAGIC)
            self._f.flush()

    def append(self, version: int, adds=(), dels=()) -> None:
        """Append one update batch and make it durable under the active
        policy (module docstring). Raises on write/fsync failure — the
        caller must NOT ack (or commit in-memory state) if this does —
        and ROLLS THE FILE BACK to the pre-append offset first: a
        refused append may leave no bytes behind. Without the rollback
        a post-write fsync failure leaves a valid record the caller was
        told was refused (replayed on recovery, and a retried batch
        then replays as a duplicate the graph refuses wholesale), and a
        partial write leaves a mid-file tear every LATER acked record
        would vanish behind. If even the rollback fails the segment is
        POISONED (closed — subsequent appends raise, so the store
        refuses acks): no log beats a forked one."""
        rec = _encode_record(version, adds, dels)
        with self._lock:
            if self._f.closed:
                raise OSError(
                    f"WAL segment {self.path} poisoned by an earlier "
                    "failed append (or closed); refusing the ack"
                )
            if self._fire is not None:
                self._fire("wal_write")
            pos = self._f.tell()
            try:
                self._f.write(rec)
                self._f.flush()
                if self.fsync == "always" or (
                    self.fsync == "batch"
                    and self._since_fsync + 1 >= self.batch_records
                ):
                    self._fsync_locked()
                else:
                    self._since_fsync += 1
            except BaseException:
                try:
                    self._f.truncate(pos)
                    self._f.seek(pos)
                    self._f.flush()
                except OSError:
                    self._f.close()
                raise
            self.records += 1
            if self._on_record is not None:
                self._on_record()

    def _fsync_locked(self) -> None:
        if self._fire is not None:
            self._fire("wal_fsync")
        os.fsync(self._f.fileno())
        self.fsyncs += 1
        self._since_fsync = 0
        if self._on_fsync is not None:
            self._on_fsync()

    def sync(self) -> None:
        """Force an fsync now (checkpoint/close barrier) regardless of
        policy — except a closed writer, where it is a no-op."""
        with self._lock:
            if not self._f.closed and self._since_fsync:
                self._fsync_locked()

    def close(self) -> None:
        """Close the segment, fsyncing any pending records first under
        EVERY policy — close is the checkpoint/shutdown barrier the
        ``batch``/``off`` policies promise (module docstring): a
        checkpoint's segment switch closes the completed segment, so
        its records are on stable storage before the manifest that
        supersedes them can commit."""
        with self._lock:
            if self._f.closed:
                return
            if self._since_fsync:
                self._fsync_locked()
            self._f.close()

    def stats(self) -> dict:
        with self._lock:
            return {
                "path": os.path.basename(self.path),
                "fsync": self.fsync,
                "records": self.records,
                "fsyncs": self.fsyncs,
            }


def segment_path(wal_dir, name: str, seq: int) -> str:
    return os.path.join(os.fspath(wal_dir), f"{name}.wal.{int(seq)}")


def list_segments(wal_dir, name: str) -> list[tuple[int, str]]:
    """All of ``name``'s segment files, sorted by sequence number."""
    prefix = f"{name}.wal."
    out = []
    for fname in os.listdir(os.fspath(wal_dir)):
        if not fname.startswith(prefix):
            continue
        tail = fname[len(prefix):]
        if tail.isdigit():
            out.append((int(tail), os.path.join(os.fspath(wal_dir), fname)))
    out.sort()
    return out


def fsync_dir(path) -> None:
    """Best-effort directory fsync after an ``os.replace`` — makes the
    rename itself durable on POSIX; harmless where unsupported."""
    try:
        fd = os.open(os.fspath(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
