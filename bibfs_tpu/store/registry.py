"""Named multi-graph store with atomic hot-swap.

One serving process, many graphs, each one live-updatable: the
:class:`GraphStore` maps names to their current
:class:`~bibfs_tpu.store.snapshot.GraphSnapshot` (plus a pending
:class:`~bibfs_tpu.store.delta.DeltaOverlay` when edge updates have
arrived since the last compaction). The engines resolve a name to a
snapshot at flush time and pin it for the flush, so a swap is:

1. build the replacement snapshot (compaction — background thread, or
   any externally built snapshot handed to :meth:`swap`);
2. under the store lock, point the name at the new snapshot — the swap
   itself is a pointer flip plus metrics, so serving traffic never
   waits on a rebuild;
3. in-flight flushes finish on the OLD snapshot through their pins; the
   old snapshot retires when the last pin drops
   (refcount — ``snapshot.release``).

Updates below the compaction threshold serve exactly through the
overlay (``serve/engine`` routes those queries to
:meth:`DeltaOverlay.solve`); once ``delta_edges`` reaches
``compact_threshold`` the store kicks a background compaction that
rebuilds the ELL into a fresh snapshot off the hot path and swaps it
in. An overlay is never mutated once handed out: a compaction REBASES
the updates that raced its build into a fresh overlay over the new
snapshot, so a flush that grabbed the old overlay keeps answering the
exact old-base+full-delta graph — which is, by construction, the same
edge set the new snapshot + rebased overlay describes.

**Distance-oracle tier** (``oracle_k=K``): each graph additionally
carries a landmark :class:`~bibfs_tpu.oracle.DistanceOracle` built as
background work off the serving path — the same compaction-style
discipline: build from a consistent capture off the store lock, commit
under it only if nothing moved. The follow-the-graph invariant is one
integer: every mutation of a graph's *live* edge state (an update
batch, a hot-swap, a compaction commit) bumps ``graph_gen``, every
index is stamped with the gen it was built for, and :meth:`oracle`
refuses to return an index whose gen is not current — a stale index can
never answer for a newer graph, by construction rather than by timing.
Adds-only update batches are repaired INTO a fresh index synchronously
(exact — see ``oracle/trees.py``; bounded by ``oracle_repair_max``,
past which a full rebuild is scheduled instead); a delete invalidates
the index until the next compaction folds it into a snapshot the
builder can traverse.

**Durability** (``wal_dir=DIR``): every acked update batch is appended
to a per-graph write-ahead log (:mod:`bibfs_tpu.store.wal`) BEFORE it
commits to the overlay — validate, log, commit, in that order under
the store lock — and the ack goes out only once the record is durable
under the ``fsync`` policy (``always``/``batch``/``off``), so a crash
can never un-ack an acknowledged write. Compactions double as
crash-consistent checkpoints: the folded snapshot lands as an
atomically-replaced ``<name>.v<V>.bin`` (``graph/io.write_graph_bin``
is tmp-file + ``os.replace``), the ``<name>.manifest.json`` commits by
atomic rename, and the WAL "truncates" by segment switch — the capture
and the switch share one locked section, so every record is either
folded into the checkpoint or replays on top of it, never both, never
neither (the full scheme: ``store/wal.py`` module docstring). Recovery
(:meth:`from_dir` with ``durable=True``) is always manifest + replay:
load the manifest's snapshot, replay surviving segments in order
(truncating a torn tail), re-arm the overlay, and rebuild the landmark
index at the recovered generation. The fault sites ``wal_write`` /
``wal_fsync`` / ``manifest_rename`` (``serve/faults``) inject exactly
the disk failures this machinery must survive: a faulted append
refuses the ack with nothing committed; a faulted manifest rename
leaves the previous checkpoint governing recovery with the WAL intact.

**Memory tiers** (``store/sidecar.py``, ``store/snapshot.py`` module
docstrings): on a durable store every checkpoint commit also writes
the snapshot's **arrays sidecar** (``<name>.v<V>.<digest12>.arrays/``
— canonical pairs, CSR row pointers and the native int32 column table
as raw files under a digest-verified manifest, committed rename-last),
and the manifest's ``arrays`` key points at it. Recovery then MAPS
instead of rebuilding: ``np.memmap`` views over the sidecar
(``GraphSnapshot.from_sidecar``, content-digest verified on the mapped
bytes) — so M replicas recovering the same store directory share ONE
page-cache-resident copy and respawn is bounded by a verify pass, not
an O(E log E) canonicalization (counted in
``bibfs_store_remap_total``; the ``.bin`` rebuild path remains the
fallback whenever the sidecar is missing, torn, or ``mmap_arrays``
is off). A **residency budget** (``residency_budget=`` bytes) arms the
store-level accountant: when the private resident total exceeds it,
least-recently-acquired hot graphs are demoted to the compressed cold
tier (varint+delta CSR — ``graph/compress.py``); any access promotes
back, exactly. Per-graph tier, mapped bytes and budget headroom are
reported by :meth:`memory_stats` (the ``bibfs-serve`` stdin ``memory``
command) and refreshed into ``bibfs_store_mmap_bytes`` /
``bibfs_store_tier`` at scrape time.

Observability: ``bibfs_store_graphs`` (gauge), ``bibfs_store_swaps_total``
/ ``bibfs_store_compactions_total`` / ``bibfs_store_compact_failures_total``
(counters, per graph), ``bibfs_store_delta_edges`` (gauge, per graph),
``bibfs_oracle_index_builds_total`` (counter, per graph) and
``bibfs_oracle_index_age_seconds`` (gauge, per graph, refreshed at
scrape time) in the process registry — durable stores add
``bibfs_wal_records_total`` / ``bibfs_wal_fsyncs_total`` /
``bibfs_checkpoints_total`` (counters, per graph),
``bibfs_recovery_replayed_records`` (counter) and
``bibfs_recovery_seconds`` (gauge, last recovery) — plus ``store_swap``
/ ``store_compact`` / ``store_checkpoint`` / ``store_recover`` /
``store_index_build`` trace spans.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
import weakref

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.metrics import REGISTRY, next_instance_label
from bibfs_tpu.obs.trace import span
from bibfs_tpu.store.delta import DeltaOverlay, canonical_edge
from bibfs_tpu.store.snapshot import GraphSnapshot
from bibfs_tpu.store.wal import (
    FSYNC_POLICIES,
    WalWriter,
    fsync_dir,
    list_segments,
    read_wal,
    repair_wal,
    segment_path,
)

#: checkpoint snapshots (``<name>.v<V>.<digest12>.bin``) — excluded
#: from :meth:`GraphStore.from_dir`'s seed enumeration (the manifest,
#: not the directory listing, says which one is current). The digest
#: suffix makes the filename content-unique (two racing checkpoint
#: writers at the same version can only collide on byte-identical
#: files, never overwrite each other's committed snapshot) — and it is
#: REQUIRED here, so a user's own seed file that merely looks
#: versioned (``roads.v2.bin``) is neither hidden from enumeration nor
#: ever eligible for checkpoint gc.
_CKPT_BIN_RE = re.compile(r"\.v(\d+)\.[0-9a-f]{6,32}\.bin$")

#: "no override" sentinel for ``_write_manifest_locked``'s
#: ``arrays_dir`` — None is a real value there ("this checkpoint has no
#: sidecar"), unlike ``bin_file`` where None can mean "use the entry's"
_UNSET = object()


class _Entry:
    """One named graph's mutable slot: current snapshot, pending
    overlay, the compaction serializer (one compaction per graph at
    a time — a forced REPL ``swap`` racing a threshold-triggered
    background job must not double-build), and the distance-oracle
    state (current oracle + its live-graph generation tag, the in-
    flight builder, per-graph build accounting)."""

    __slots__ = ("snapshot", "overlay", "compactor", "compact_lock",
                 "swaps", "compactions", "compact_failures",
                 "graph_gen", "oracle", "oracle_builder", "oracle_cells",
                 "index_builds", "index_aborts", "index_repairs",
                 "index_failures",
                 "wal", "wal_seq", "bin_file", "checkpoints", "recovered",
                 "arrays_dir", "touched")

    def __init__(self, snapshot: GraphSnapshot):
        self.snapshot = snapshot
        self.overlay: DeltaOverlay | None = None
        self.compactor: threading.Thread | None = None
        self.compact_lock = threading.Lock()
        self.swaps = 0
        self.compactions = 0
        self.compact_failures = 0
        # live-graph generation: bumped on every update batch, swap and
        # compaction commit — the oracle's follow-the-graph tag
        self.graph_gen = 1
        self.oracle = None  # DistanceOracle | None
        self.oracle_builder: threading.Thread | None = None
        self.oracle_cells: dict | None = None
        self.index_builds = 0
        self.index_aborts = 0
        self.index_repairs = 0
        self.index_failures = 0
        # durability state (None/unused on non-durable stores)
        self.wal: WalWriter | None = None
        self.wal_seq = 0
        self.bin_file: str | None = None
        self.checkpoints = 0
        self.recovered: dict | None = None
        # memory-tier state: the committed arrays sidecar (durable
        # stores) and the last-acquire stamp the residency accountant's
        # LRU demotion order reads
        self.arrays_dir: str | None = None
        self.touched = time.monotonic()


@guarded_by("_lock", "_entries", "_default")
class GraphStore:
    """Named, versioned, hot-swappable graphs (module docstring).

    Parameters
    ----------
    compact_threshold : pending delta edges at which a background
        compaction (rebuild + swap) is triggered. ``None`` disables
        auto-compaction (explicit :meth:`compact` / :meth:`swap` only).
    oracle_k : landmarks per graph for the distance-oracle tier
        (module docstring). ``None`` (default) disables the tier —
        :meth:`oracle` then always returns None and nothing is built.
    oracle_repair_max : adds folded into one index by incremental
        repair before a full rebuild is scheduled instead (the rebuild
        threshold; repair is exact either way, this bounds the drift a
        single index accumulates before re-selection of landmarks).
    oracle_seed : landmark-selection seed (deterministic rebuilds).
    obs_label : the ``store=`` label value this store's registry cells
        carry (default: a process-unique ``store-N``).
    wal_dir : directory rooting the durability layer (module
        docstring): per-graph write-ahead log segments, checkpoint
        ``.bin`` snapshots and ``manifest.json`` files. ``None``
        (default) disables durability — acked updates then live only in
        process memory, exactly the pre-WAL behavior.
    retain_history : keep superseded checkpoint bins and WAL segments
        instead of GC'ing them after each manifest commit, so every
        committed version stays reconstructible for ``as_of``
        time-travel queries (:meth:`reconstruct_version`,
        ``store/history.py``). Requires ``wal_dir``. Default False:
        the PR 8 GC behavior exactly (history stays readable only for
        versions whose artifacts happen to survive).
    mmap_arrays : write arrays sidecars at checkpoint commits and
        recover by mmap when a manifest points at one (module
        docstring). Default True; False forces the pre-sidecar
        rebuild-from-``.bin`` behavior everywhere (the soak's baseline
        replica runs this way to measure one private copy).
    residency_budget : process-private resident bytes across all of
        this store's snapshots past which the accountant demotes
        least-recently-acquired hot graphs to the compressed cold tier
        (module docstring). ``None`` (default) disables demotion.
    fsync : WAL fsync policy, ``always`` / ``batch`` / ``off``
        (``store/wal.py`` module docstring — what "durable enough to
        ack" means). Default ``batch``.
    fsync_batch_records : group-commit size under ``fsync="batch"``.
    faults : a :class:`bibfs_tpu.serve.faults.FaultPlan` injecting at
        the durability seams (``wal_write``/``wal_fsync``/
        ``manifest_rename``); default: built from ``BIBFS_FAULTS`` when
        set, else no injection.
    """

    def __init__(self, *, compact_threshold: int | None = 256,
                 oracle_k: int | None = None,
                 oracle_repair_max: int = 64,
                 oracle_seed: int = 0,
                 obs_label: str | None = None,
                 wal_dir=None, fsync: str = "batch",
                 fsync_batch_records: int = 64, faults=None,
                 retain_history: bool = False,
                 mmap_arrays: bool = True,
                 residency_budget: int | None = None):
        self.compact_threshold = (
            None if compact_threshold is None else int(compact_threshold)
        )
        if self.compact_threshold is not None and self.compact_threshold < 1:
            raise ValueError(
                f"compact_threshold must be >= 1, got {compact_threshold}"
            )
        self.obs_label = (
            next_instance_label("store") if obs_label is None else obs_label
        )
        self._lock = threading.RLock()
        self._entries: dict[str, _Entry] = {}
        self._default: str | None = None
        self._g_graphs = REGISTRY.gauge(
            "bibfs_store_graphs", "Graphs registered in a graph store",
            ("store",),
        ).labels(store=self.obs_label)
        self._c_swaps = REGISTRY.counter(
            "bibfs_store_swaps_total",
            "Atomic snapshot hot-swaps per graph",
            ("store", "graph"),
        )
        self._g_delta = REGISTRY.gauge(
            "bibfs_store_delta_edges",
            "Pending overlay edge updates per graph",
            ("store", "graph"),
        )
        self._c_compactions = REGISTRY.counter(
            "bibfs_store_compactions_total",
            "Delta compactions (overlay folded into a fresh snapshot)",
            ("store", "graph"),
        )
        self._c_compact_failures = REGISTRY.counter(
            "bibfs_store_compact_failures_total",
            "Background compactions that raised (overlay keeps serving; "
            "the next update re-triggers)",
            ("store", "graph"),
        )
        self.mmap_arrays = bool(mmap_arrays)
        self.residency_budget = (
            None if residency_budget is None else int(residency_budget)
        )
        if self.residency_budget is not None and self.residency_budget < 0:
            raise ValueError(
                f"residency_budget must be >= 0 bytes, "
                f"got {residency_budget}"
            )
        self._g_mmap_bytes = REGISTRY.gauge(
            "bibfs_store_mmap_bytes",
            "Sidecar bytes the graph's current snapshot keeps mapped "
            "(shared page-cache-backed, not process-private)",
            ("store", "graph"),
        )
        self._g_tier = REGISTRY.gauge(
            "bibfs_store_tier",
            "Graphs currently in each memory tier (mapped/hot/cold)",
            ("store", "tier"),
        )
        for t in ("mapped", "hot", "cold"):  # render at zero pre-traffic
            self._g_tier.labels(store=self.obs_label, tier=t).set(0)
        self._c_remaps = REGISTRY.counter(
            "bibfs_store_remap_total",
            "Recoveries served by mapping an arrays sidecar instead of "
            "rebuilding from the checkpoint .bin",
            ("store", "graph"),
        )
        # scrape-time tier/mapped-bytes refresh, weakly bound like the
        # index-age collector below: a dead store unregisters itself
        mem_ref = weakref.ref(self)

        def _collect_memory():
            st = mem_ref()
            if st is None:
                return False
            st._refresh_memory_metrics()
            return True

        REGISTRY.add_collector(_collect_memory)
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"unknown fsync policy {fsync!r} "
                f"(known: {', '.join(FSYNC_POLICIES)})"
            )
        self.wal_dir = None if wal_dir is None else os.fspath(wal_dir)
        self.retain_history = bool(retain_history)
        if self.retain_history and self.wal_dir is None:
            raise ValueError(
                "retain_history=True needs a durable store (wal_dir=): "
                "history is reconstructed from the WAL + checkpoints"
            )
        self.fsync = fsync
        self.fsync_batch_records = int(fsync_batch_records)
        if faults is None:
            from bibfs_tpu.serve.faults import FaultPlan

            faults = FaultPlan.from_env()
        self._faults = faults
        self.load_errors: list[dict] = []
        if self.wal_dir is not None:
            if not os.path.isdir(self.wal_dir):
                raise ValueError(f"wal_dir {self.wal_dir!r} is not a directory")
            self._c_wal_records = REGISTRY.counter(
                "bibfs_wal_records_total",
                "Write-ahead-log records appended (one acked update "
                "batch each)",
                ("store", "graph"),
            )
            self._c_wal_fsyncs = REGISTRY.counter(
                "bibfs_wal_fsyncs_total",
                "Write-ahead-log fsyncs issued (policy-dependent)",
                ("store", "graph"),
            )
            self._c_checkpoints = REGISTRY.counter(
                "bibfs_checkpoints_total",
                "Crash-consistent checkpoints committed (snapshot .bin "
                "+ manifest + WAL segment switch)",
                ("store", "graph"),
            )
            self._c_recovery_replayed = REGISTRY.counter(
                "bibfs_recovery_replayed_records",
                "WAL records replayed during recovery",
                ("store", "graph"),
            )
            self._g_recovery_seconds = REGISTRY.gauge(
                "bibfs_recovery_seconds",
                "Duration of the graph's last manifest+replay recovery",
                ("store", "graph"),
            )
        # the whole-graph analytics result store rides every registry
        # (memory-only when the store is not durable); the note_* hooks
        # below feed it the digest lineage its incremental maintenance
        # walks
        from bibfs_tpu.analytics.results import AnalyticsResultStore

        self.analytics = AnalyticsResultStore(
            root=(os.path.join(self.wal_dir, "analytics")
                  if self.wal_dir is not None else None),
            store_label=self.obs_label,
        )
        self.oracle_k = None if oracle_k is None else int(oracle_k)
        if self.oracle_k is not None and self.oracle_k < 1:
            raise ValueError(f"oracle_k must be >= 1, got {oracle_k}")
        self.oracle_repair_max = int(oracle_repair_max)
        self.oracle_seed = int(oracle_seed)
        self._c_index_builds = REGISTRY.counter(
            "bibfs_oracle_index_builds_total",
            "Full landmark-index builds committed per graph "
            "(incremental repairs not included)",
            ("store", "graph"),
        )
        self._g_index_age = REGISTRY.gauge(
            "bibfs_oracle_index_age_seconds",
            "Age of the graph's CURRENT landmark index (0 when the "
            "graph has none); refreshed at scrape time",
            ("store", "graph"),
        )
        if self.oracle_k is not None:
            # scrape-time age refresh, weakly bound like the engines'
            # health collector: a dead store must unregister itself, not
            # pin its graphs for process lifetime
            self_ref = weakref.ref(self)

            def _collect_index_age():
                st = self_ref()
                if st is None:
                    return False
                now = time.time()
                with st._lock:
                    for nm, e in st._entries.items():
                        st._g_index_age.labels(
                            store=st.obs_label, graph=nm
                        ).set(
                            0.0 if e.oracle is None
                            else max(now - e.oracle.index.built_at, 0.0)
                        )
                return True

            REGISTRY.add_collector(_collect_index_age)

    # ---- registration -----------------------------------------------
    def add(self, name: str, n: int | None = None, edges=None, *,
            pairs=None, snapshot: GraphSnapshot | None = None
            ) -> GraphSnapshot:
        """Register a graph under ``name`` (its version-1 snapshot).
        The first added graph becomes the default. On a durable store
        this also writes the graph's seed ``.bin`` (if absent), its
        v1 manifest, and opens its first WAL segment — and REFUSES a
        name that already has durable state on disk (recover it with
        ``from_dir(durable=True)`` instead; silently appending to a
        dead process's WAL would interleave two histories)."""
        name = str(name)
        if snapshot is None:
            if n is None:
                raise ValueError("add() needs n+edges/pairs or snapshot=")
            snapshot = GraphSnapshot.build(n, edges, pairs=pairs)
        if self.wal_dir is not None and (
            os.path.exists(self._manifest_path(name))
            or list_segments(self.wal_dir, name)
        ):
            raise ValueError(
                f"graph {name!r} has durable state in {self.wal_dir!r}; "
                "recover it with GraphStore.from_dir(..., durable=True)"
            )
        entry = self._register(name, snapshot)
        if self.wal_dir is not None:
            try:
                self._durable_register(name, entry)
            except BaseException:
                # UNREGISTER: a half-registered graph would keep
                # serving and acking updates with entry.wal None —
                # volatile acks on a store the caller believes durable,
                # the exact hole this layer closes
                with self._lock:
                    self._entries.pop(name, None)
                    if self._default == name:
                        self._default = min(self._entries, default=None)
                    self._g_graphs.set(len(self._entries))
                self.analytics.purge(name)
                raise
        self._kick_oracle(name, entry)
        self._maybe_rebalance()
        return snapshot

    def _register(self, name: str, snapshot: GraphSnapshot, *,
                  version: int = 1) -> _Entry:
        """The in-memory half of registration (shared with the recovery
        path, which re-registers at the manifest's version instead of
        1)."""
        with self._lock:
            if name in self._entries:
                raise ValueError(
                    f"graph {name!r} already registered (swap() replaces)"
                )
            # versions are store-relative: every registered graph starts
            # at v1, compaction stamps old+1 — so `graphs` output and
            # stats read as each graph's OWN history, not the order the
            # process happened to build snapshots in. (The build-time
            # global stamp remains the fallback for snapshots that never
            # enter a store.)
            snapshot.version = int(version)
            entry = _Entry(snapshot)
            self._entries[name] = entry
            if self._default is None:
                self._default = name
            self._g_graphs.set(len(self._entries))
            # mint the per-graph cells now so a scrape shows the graph
            # at zero before its first update/swap
            self._c_swaps.labels(store=self.obs_label, graph=name)
            self._g_delta.labels(store=self.obs_label, graph=name).set(0)
            self._c_compactions.labels(store=self.obs_label, graph=name)
            self._c_compact_failures.labels(store=self.obs_label, graph=name)
            self._g_mmap_bytes.labels(store=self.obs_label, graph=name).set(
                snapshot.mapped_bytes()
            )
            self._c_remaps.labels(store=self.obs_label, graph=name)
            if self.oracle_k is not None:
                from bibfs_tpu.oracle import oracle_cells

                entry.oracle_cells = oracle_cells(
                    self._oracle_label(name)
                )
                self._c_index_builds.labels(
                    store=self.obs_label, graph=name
                )
                self._g_index_age.labels(
                    store=self.obs_label, graph=name
                ).set(0.0)
        self.analytics.note_register(name, snapshot.digest)
        return entry

    @classmethod
    def from_dir(cls, path, *, durable: bool = False,
                 **kwargs) -> "GraphStore":
        """A store over every ``*.bin`` graph in a directory, each
        registered under its file stem (``social.bin`` -> ``social``),
        sorted so the default graph is deterministic.

        ``durable=True`` roots the durability layer in the SAME
        directory (``wal_dir=path`` unless overridden) and RECOVERS any
        graph that left a manifest or WAL behind: manifest snapshot +
        ordered segment replay, torn tail truncated, overlay re-armed
        (module docstring). Checkpoint ``.bin`` files
        (``<name>.v<V>.bin``) are never treated as seed graphs.

        A corrupt or unreadable graph (torn ``.bin``, bad manifest,
        digest mismatch) is SKIPPED with a counted, visible warning —
        recorded in ``store.load_errors`` — instead of aborting the
        whole registry load; only a directory with no loadable graph at
        all raises."""
        from bibfs_tpu.graph.io import read_graph_bin

        path = os.fspath(path)
        if durable:
            kwargs.setdefault("wal_dir", path)
        store = cls(**kwargs)
        names = set()
        for fname in os.listdir(path):
            if fname.endswith(".bin") and not _CKPT_BIN_RE.search(fname):
                names.add(fname[: -len(".bin")])
            elif fname.endswith(".manifest.json"):
                names.add(fname[: -len(".manifest.json")])
        if not names:
            raise ValueError(f"no *.bin graphs in {path!r}")
        for name in sorted(names):
            try:
                if store.wal_dir is not None and (
                    os.path.exists(store._manifest_path(name))
                    or list_segments(store.wal_dir, name)
                ):
                    store._recover_graph(name)
                else:
                    n, edges = read_graph_bin(
                        os.path.join(path, f"{name}.bin")
                    )
                    store.add(name, n, edges)
            except (OSError, ValueError, KeyError,
                    json.JSONDecodeError) as e:
                store.load_errors.append({
                    "graph": name,
                    "error": f"{type(e).__name__}: {e}"[:300],
                })
                print(
                    f"[Store] skipping graph {name!r}: {e}",
                    file=sys.stderr,
                )
        if not store.names():
            raise ValueError(
                f"no readable graph in {path!r} "
                f"({len(store.load_errors)} skipped)"
            )
        return store

    # ---- durability (WAL + checkpoints + recovery) -------------------
    def _fire(self, site: str) -> None:
        if self._faults is not None:
            self._faults.fire(site)

    def _manifest_path(self, name: str) -> str:
        return os.path.join(self.wal_dir, f"{name}.manifest.json")

    def _open_segment(self, name: str, seq: int) -> WalWriter:
        rec = self._c_wal_records.labels(store=self.obs_label, graph=name)
        fsn = self._c_wal_fsyncs.labels(store=self.obs_label, graph=name)
        return WalWriter(
            segment_path(self.wal_dir, name, seq),
            fsync=self.fsync,
            batch_records=self.fsync_batch_records,
            fire=self._fire,
            on_record=rec.inc,
            on_fsync=fsn.inc,
        )

    def _durable_register(self, name: str, entry: _Entry) -> None:
        """Fresh durable registration: seed ``.bin`` (written atomically
        if absent; digest-verified against the registered snapshot if
        present — the manifest will reference it, and a mismatched seed
        would make every later recovery refuse the graph), v1 manifest,
        first WAL segment."""
        from bibfs_tpu.graph.io import read_graph_bin, write_graph_bin

        entry.bin_file = f"{name}.bin"
        seed = os.path.join(self.wal_dir, entry.bin_file)
        if not os.path.exists(seed):
            write_graph_bin(
                seed, entry.snapshot.n, entry.snapshot.undirected_edges()
            )
        else:
            n, edges = read_graph_bin(seed)
            on_disk = GraphSnapshot.build(n, edges)
            if on_disk.digest != entry.snapshot.digest:
                raise ValueError(
                    f"{entry.bin_file} already exists with different "
                    f"content (digest {on_disk.digest} != registered "
                    f"{entry.snapshot.digest}); refusing to register a "
                    "graph its own seed could not recover"
                )
        if self.mmap_arrays:
            # the seed's arrays sidecar, BEFORE the manifest references
            # it — heavy (O(E) writes + hashes) but off the store lock,
            # and what makes a respawn of this very graph map instead
            # of rebuild
            from bibfs_tpu.store.sidecar import write_sidecar

            entry.arrays_dir = write_sidecar(
                self.wal_dir, name, entry.snapshot, fire=self._fire
            )
        entry.wal_seq = 1
        self._c_checkpoints.labels(store=self.obs_label, graph=name)
        self._c_recovery_replayed.labels(store=self.obs_label, graph=name)
        self._g_recovery_seconds.labels(
            store=self.obs_label, graph=name
        ).set(0.0)
        with self._lock:
            self._write_manifest_locked(name, entry)
        entry.wal = self._open_segment(name, entry.wal_seq)

    def _write_manifest_locked(self, name: str, entry: _Entry, *,
                               snapshot: GraphSnapshot | None = None,
                               bin_file: str | None = None,
                               arrays_dir=_UNSET) -> None:
        """Commit the graph's manifest by atomic rename: tmp file,
        flush+fsync, ``os.replace`` (the ``manifest_rename`` fault
        seam), directory fsync. A crash (or injected fault) anywhere in
        here leaves the PREVIOUS manifest governing recovery — with the
        superseded WAL segments still on disk, so nothing acked is
        lost, only replayed from one checkpoint further back.
        ``snapshot``/``bin_file`` override the entry's (``swap()``
        commits durably BEFORE the in-memory flip)."""
        snapshot = entry.snapshot if snapshot is None else snapshot
        manifest = {
            "graph": name,
            "version": snapshot.version,
            "digest": snapshot.digest,
            "n": snapshot.n,
            "edges": snapshot.num_edges,
            "bin": entry.bin_file if bin_file is None else bin_file,
            # the mmap recovery path's pointer; None when the store
            # writes no sidecars — recovery then always rebuilds
            "arrays": (
                entry.arrays_dir if arrays_dir is _UNSET else arrays_dir
            ),
            "wal": f"{name}.wal.{entry.wal_seq}",
            "wal_seq": entry.wal_seq,
            "wal_offset": 0,
            "checkpoints": entry.checkpoints,
        }
        path = self._manifest_path(name)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            self._fire("manifest_rename")
            os.replace(tmp, path)
        except BaseException:
            self._unlink_quiet(tmp)
            raise
        fsync_dir(self.wal_dir)
        # record the committed version in the graph's history file
        # (store/history.py) — the as_of read path's index. ONLY on a
        # retain_history store: without retention the artifacts an
        # entry points at are GC'd at the very next commit (the entry
        # could never reconstruct), and the read-rewrite + two fsyncs
        # per commit under the store lock would be pure cost growing
        # with version count. Best-effort AFTER the manifest commit: a
        # failed history write must not un-commit a checkpoint that is
        # already governing recovery; that version just reads as
        # unreconstructible, loudly.
        if not self.retain_history:
            return
        from bibfs_tpu.store.history import append_history

        try:
            append_history(self.wal_dir, name, {
                "version": snapshot.version,
                "digest": snapshot.digest,
                "bin": manifest["bin"],
                "wal_seq": entry.wal_seq,
                "n": snapshot.n,
                "edges": snapshot.num_edges,
            })
        except OSError as e:
            print(
                f"[Store] history append failed for {name!r} "
                f"v{snapshot.version}: {e}",
                file=sys.stderr,
            )

    def _wal_roll_locked(self, name: str, entry: _Entry) -> int:
        """Switch the graph to a fresh WAL segment (the crash-safe form
        of truncation — ``store/wal.py`` module docstring). MUST run in
        the same locked section as the overlay capture it fences."""
        old = entry.wal
        entry.wal_seq += 1
        entry.wal = self._open_segment(name, entry.wal_seq)
        if old is not None:
            old.close()  # flushes + fsyncs the completed segment
        return entry.wal_seq

    def _checkpoint_locked(self, name: str, entry: _Entry,
                           bin_file: str,
                           arrays_dir: str | None = None) -> None:
        """Commit a checkpoint for the CURRENT (just-swapped) snapshot:
        point the manifest at ``bin_file`` and ``arrays_dir`` (both
        already atomically written/renamed) and the current WAL
        segment. Counted + spanned."""
        with span("store_checkpoint", graph=name,
                  version=entry.snapshot.version, wal_seq=entry.wal_seq):
            entry.bin_file = bin_file
            entry.arrays_dir = arrays_dir
            self._write_manifest_locked(name, entry)
            entry.checkpoints += 1
            self._c_checkpoints.labels(
                store=self.obs_label, graph=name
            ).inc()

    def _unlink_quiet(self, path) -> None:
        if not path:
            return
        try:
            os.unlink(path if os.path.isabs(str(path))
                      else os.path.join(self.wal_dir, str(path)))
        except OSError:
            pass

    def _ckpt_bin_name(self, name: str, snapshot: GraphSnapshot) -> str:
        """Checkpoint snapshot filename: version + content-digest
        prefix, so concurrent writers can only ever collide on
        byte-identical files (``_CKPT_BIN_RE``)."""
        return f"{name}.v{snapshot.version}.{snapshot.digest[:12]}.bin"

    def _gc_durable(self, name: str, entry: _Entry) -> None:
        """Delete superseded checkpoint bins and WAL segments (below
        the committed manifest) — best-effort, after the manifest
        rename made them unreachable. The manifest's current bin and
        the seed ``<name>.bin`` are always kept (the seed is the
        directory's human-visible original and the non-durable
        ``from_dir`` fallback). A ``retain_history`` store skips GC
        entirely — superseded bins and segments ARE the time-travel
        read path (``store/history.py``)."""
        if self.retain_history:
            return
        from bibfs_tpu.store.sidecar import (
            ARRAYS_DIR_RE,
            remove_sidecar_quiet,
        )

        cur_v = entry.snapshot.version
        cur_seq = entry.wal_seq
        keep = entry.bin_file
        keep_arrays = entry.arrays_dir
        for seq, path in list_segments(self.wal_dir, name):
            if seq < cur_seq:
                self._unlink_quiet(path)
        prefix = f"{name}.v"
        for fname in os.listdir(self.wal_dir):
            if not fname.startswith(prefix) or fname == keep:
                continue
            m = _CKPT_BIN_RE.search(fname)
            if (m is not None and fname[: m.start()] == name
                    and int(m.group(1)) <= cur_v):
                self._unlink_quiet(os.path.join(self.wal_dir, fname))
                continue
            if fname == keep_arrays:
                continue
            # superseded arrays sidecars go with their bins; a dead
            # writer's ``<...>.arrays.tmp.<pid>`` orphan (never
            # committed by rename) goes too — version-bounded either
            # way, so an in-flight writer targeting a NEWER version is
            # never swept from under its rename
            m = ARRAYS_DIR_RE.search(fname)
            if m is None:
                m = re.search(
                    r"\.v(\d+)\.[0-9a-f]{6,32}\.arrays\.tmp\.\d+$", fname
                )
            if (m is not None and fname[: m.start()] == name
                    and int(m.group(1)) <= cur_v):
                remove_sidecar_quiet(os.path.join(self.wal_dir, fname))

    def _recover_graph(self, name: str) -> None:
        """Manifest + replay recovery (module docstring): load the
        manifest's snapshot (digest-verified), replay every surviving
        WAL segment ``>= wal_seq`` in order — truncating a torn tail on
        the live segment — re-arm the overlay, and leave the landmark
        index rebuilding at the recovered generation. Raises (BEFORE
        registering anything) on a broken base, a digest mismatch, a
        torn NON-final segment, or a record its own prefix rejects —
        ``from_dir`` then skips the graph with a counted warning: a
        graph whose durable history cannot be fully proven is refused,
        never served approximately."""
        from bibfs_tpu.graph.io import read_graph_bin

        t0 = time.perf_counter()
        mpath = self._manifest_path(name)
        manifest = None
        if os.path.exists(mpath):
            with open(mpath) as f:
                manifest = json.load(f)
        bin_file = (
            f"{name}.bin" if manifest is None else str(manifest["bin"])
        )
        version = 1 if manifest is None else int(manifest["version"])
        wal_seq = 1 if manifest is None else int(manifest["wal_seq"])
        arrays_dir = (
            None if manifest is None else manifest.get("arrays")
        )
        snap = None
        remapped = False
        if arrays_dir is not None and self.mmap_arrays:
            # recovery-by-remap: map the committed sidecar read-only —
            # bounded by a sequential verify pass over shared
            # page-cache bytes, not an O(E log E) rebuild. The content
            # digest is recomputed FROM THE MAPPED BYTES
            # (from_sidecar), so what serves is proven to be what was
            # checkpointed. Any failure (torn, missing, foreign)
            # falls through to the .bin rebuild below, loudly.
            from bibfs_tpu.store.sidecar import load_sidecar

            try:
                smap = load_sidecar(
                    os.path.join(self.wal_dir, str(arrays_dir)),
                    verify="size",
                )
                if (manifest.get("digest") is not None
                        and smap.digest != manifest["digest"]):
                    raise ValueError(
                        f"sidecar digest {smap.digest} != manifest "
                        f"{manifest['digest']} (stale sidecar)"
                    )
                snap = GraphSnapshot.from_sidecar(smap, version=version)
                remapped = True
            except (OSError, ValueError, KeyError) as e:
                print(
                    f"[Store] sidecar remap failed for {name!r} "
                    f"({arrays_dir}): {e}; rebuilding from {bin_file}",
                    file=sys.stderr,
                )
                snap = None
        if snap is None:
            arrays_dir = None  # the manifest's sidecar is not servable
            n, edges = read_graph_bin(os.path.join(self.wal_dir, bin_file))
            snap = GraphSnapshot.build(n, edges)
        if manifest is not None and manifest.get("digest") is not None \
                and manifest["digest"] != snap.digest:
            raise ValueError(
                f"{bin_file}: content digest {snap.digest} does not "
                f"match manifest {manifest['digest']} — refusing to "
                "serve a snapshot that is not the one checkpointed"
            )
        replayed = 0
        truncated = False
        overlay = None
        segments = [
            (s, p) for s, p in list_segments(self.wal_dir, name)
            if s >= wal_seq
        ]
        # replay is PROVEN before anything registers: a raise below
        # (torn non-final segment, inconsistent record) must leave the
        # store without a half-registered graph for from_dir to skip
        with span("store_recover", graph=name, version=version,
                  segments=len(segments)):
            for i, (seq, spath) in enumerate(segments):
                last = i == len(segments) - 1
                if last:
                    # truncate a torn tail in place so appends resume
                    # on a provably-valid prefix (the one tear a
                    # process crash can legitimately leave: mid-append
                    # on the live segment)
                    records, torn = repair_wal(spath)
                    truncated = truncated or torn
                else:
                    records, _good, torn = read_wal(spath)
                    if torn:
                        # a non-final segment was completed and flushed
                        # before its switch, so a tear there is damage
                        # outside our control — and records in LATER
                        # segments depend on the lost ones. Serving the
                        # provable prefix while accepting new acks
                        # would fork the history (replay could never
                        # reach them): refuse the graph instead, the
                        # digest-mismatch contract
                        raise ValueError(
                            f"{os.path.basename(spath)}: torn "
                            "non-final WAL segment — acked records "
                            "beyond it are unrecoverable; refusing to "
                            "serve a forked history"
                        )
                for _rec_version, adds, dels in records:
                    if overlay is None:
                        overlay = DeltaOverlay(snap)
                        overlay.ensure_index()
                    try:
                        overlay.apply(adds, dels)
                    except ValueError as e:
                        # a CRC-valid record its own prefix rejects is
                        # logic-level corruption — same contract
                        raise ValueError(
                            f"{os.path.basename(spath)}: WAL record "
                            f"inconsistent with its own prefix ({e}); "
                            "refusing to serve a forked history"
                        ) from e
                    replayed += 1
            entry = self._register(name, snap, version=version)
            entry.bin_file = bin_file
            entry.arrays_dir = (
                None if arrays_dir is None else str(arrays_dir)
            )
            self._c_checkpoints.labels(store=self.obs_label, graph=name)
            entry.graph_gen += replayed  # one live-graph gen per batch
            entry.wal_seq = segments[-1][0] if segments else wal_seq
            entry.wal = self._open_segment(name, entry.wal_seq)
            delta = 0
            if overlay is not None and overlay.delta_edges > 0:
                entry.overlay = overlay
                delta = overlay.delta_edges
            self._g_delta.labels(store=self.obs_label, graph=name).set(delta)
        dt = time.perf_counter() - t0
        self._c_recovery_replayed.labels(
            store=self.obs_label, graph=name
        ).inc(replayed)
        self._g_recovery_seconds.labels(
            store=self.obs_label, graph=name
        ).set(dt)
        if remapped:
            self._c_remaps.labels(store=self.obs_label, graph=name).inc()
            self._g_mmap_bytes.labels(store=self.obs_label, graph=name).set(
                snap.mapped_bytes()
            )
        entry.recovered = {
            "version": version,
            "replayed_records": replayed,
            "torn_tail_truncated": truncated,
            "segments": len(segments),
            "delta_edges": delta,
            "recovery_s": round(dt, 6),
            "remapped": remapped,
        }
        if (self.compact_threshold is not None
                and delta >= self.compact_threshold):
            # a long replay re-armed a big overlay: fold it off the
            # serving path now rather than waiting for the next update
            with self._lock:
                if entry.compactor is None:
                    entry.compactor = threading.Thread(
                        target=self._compact_job, args=(name, entry),
                        name=f"bibfs-compact-{name}", daemon=True,
                    )
                    entry.compactor.start()
        self._kick_oracle(name, entry)
        self._maybe_rebalance()

    # ---- resolution --------------------------------------------------
    def _entry(self, name: str) -> _Entry:
        entry = self._entries.get(str(name))
        if entry is None:
            raise KeyError(
                f"unknown graph {name!r} (have: {sorted(self._entries)})"
            )
        return entry

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def default_graph(self) -> str:
        with self._lock:
            if self._default is None:
                raise ValueError("store has no graphs")
            return self._default

    def current(self, name: str) -> GraphSnapshot:
        """The graph's current snapshot — an identity read (cheap
        same-version check). Pin with :meth:`acquire` before USING one
        across a swap window."""
        with self._lock:
            return self._entry(name).snapshot

    def acquire(self, name: str) -> GraphSnapshot:
        """The current snapshot, retained under the store lock — so a
        concurrent swap cannot retire it between the read and the pin.
        The caller owns one ``release()``."""
        with self._lock:
            entry = self._entry(name)
            entry.touched = time.monotonic()  # the accountant's LRU stamp
            return entry.snapshot.retain()

    def touch(self, name: str) -> None:
        """Refresh ``name``'s access-recency stamp WITHOUT pinning —
        the engines call this at their snapshot-pin seam (every flush
        bind resolves through an already-retained runtime, so without
        it a hot graph would keep the ``touched`` stamp of its first
        acquire and :meth:`rebalance` would demote by acquisition
        order, not true access recency). Unknown names are ignored:
        the engine may race a remove, and recency is advisory."""
        with self._lock:
            entry = self._entries.get(str(name))
            if entry is not None:
                entry.touched = time.monotonic()

    def overlay(self, name: str) -> DeltaOverlay | None:
        """The graph's pending overlay, or None when it has no pending
        updates — the engines' exact-answering route check."""
        with self._lock:
            ov = self._entry(name).overlay
        if ov is not None and ov.delta_edges == 0:
            return None
        return ov

    # ---- live updates ------------------------------------------------
    def update(self, name: str, adds=(), dels=()) -> dict:
        """Apply one batch of undirected edge updates to ``name``'s
        overlay (creating it on first update). Crossing
        ``compact_threshold`` kicks a background compaction. Returns
        ``{"adds": ..., "dels": ..., "compacting": bool}``.

        On a durable store the batch is WAL-logged between validation
        and the in-memory commit — validate, log, commit, one locked
        section — and this method returning IS the ack: it happens only
        after the record is durable under the fsync policy. A failed
        append (disk fault, injected ``wal_write``/``wal_fsync``)
        raises with NOTHING committed: the update is refused rather
        than accepted-but-volatile.

        The locked section is what fences the append against a
        checkpoint's capture+segment-switch, so under ``fsync=always``
        the fsync runs while holding the store lock: updates (a
        control-plane path) then serialize against name resolution for
        one fsync's latency. Serving reads are pointer reads — the
        stall is bounded and deliberate; a per-graph WAL lock would buy
        that latency back at the price of a second lock order across
        every capture seam."""
        name = str(name)
        adds = [tuple(e) for e in adds]  # consumed twice when the
        dels = [tuple(e) for e in dels]  # oracle repairs (below)
        while True:
            with self._lock:
                entry = self._entry(name)
                if entry.overlay is None:
                    entry.overlay = DeltaOverlay(entry.snapshot)
                overlay = entry.overlay
            # the first apply against a base needs its O(E) membership
            # index — build it OFF the store lock (every serving thread
            # resolves names through that lock; a Python pass over
            # every edge under it is a serving stall)
            overlay.ensure_index()
            with self._lock:
                if self._entry(name).overlay is not overlay:
                    # a swap/compaction replaced the overlay while the
                    # index built: restart against the current state
                    continue
                if entry.wal is not None:
                    # validate, log, commit: the dry run rejects a bad
                    # batch BEFORE it can reach the log, and makes the
                    # committing apply below infallible — so the WAL
                    # never holds a record the overlay refused, and the
                    # overlay never holds a batch the WAL lost
                    overlay.apply(adds, dels, commit=False)
                    entry.wal.append(entry.snapshot.version, adds, dels)
                counts = overlay.apply(adds, dels)
                # feed the analytics result store the acked delta (a
                # leaf-lock append — its incremental maintenance needs
                # the adds-only lineage, and deletes mark a barrier)
                self.analytics.note_update(name, adds, dels)
                # the live graph changed: the oracle gen moves forward
                # IN THE SAME locked section as the apply, so no reader
                # can pair the new edge state with the old index
                entry.graph_gen += 1
                gen_after = entry.graph_gen
                prev_oracle = entry.oracle
                delta = counts["adds"] + counts["dels"]
                self._g_delta.labels(
                    store=self.obs_label, graph=name
                ).set(delta)
                compacting = entry.compactor is not None
                if (not compacting and self.compact_threshold is not None
                        and delta >= self.compact_threshold):
                    entry.compactor = threading.Thread(
                        target=self._compact_job, args=(name, entry),
                        name=f"bibfs-compact-{name}", daemon=True,
                    )
                    entry.compactor.start()
                    compacting = True
            self._oracle_after_update(
                name, entry, overlay, adds, dels, gen_after, prev_oracle
            )
            self._maybe_rebalance()
            return {**counts, "compacting": compacting}

    # ---- oracle lifecycle --------------------------------------------
    def _oracle_label(self, name: str) -> str:
        return f"{self.obs_label}/{name}"

    def oracle(self, name: str):
        """The graph's :class:`~bibfs_tpu.oracle.DistanceOracle`, or
        None when disabled / not (yet) built for the CURRENT live edge
        state — the follow-the-graph read the engines route through: a
        gen mismatch means the index describes a superseded graph and
        is simply not returned, so a stale index can never answer."""
        if self.oracle_k is None:
            return None
        with self._lock:
            entry = self._entry(name)
            orc = entry.oracle
            if orc is None or orc.index.gen != entry.graph_gen:
                return None
            return orc

    def wait_for_index(self, name: str, timeout: float = 60.0) -> bool:
        """Block until ``name`` has a current index (True) or the
        timeout passes (False) — a test/bench aid; serving code never
        waits, it just falls through to the solvers until the
        background build commits. Re-kicks the builder if nothing is
        in flight (e.g. after an aborted build)."""
        deadline = time.monotonic() + timeout
        kicked_gen = None
        while True:
            if self.oracle(str(name)) is not None:
                return True
            if time.monotonic() >= deadline:
                return False
            with self._lock:
                entry = self._entry(str(name))
                builder = entry.oracle_builder
                gen = entry.graph_gen
            # at most one re-kick per live-graph generation: a builder
            # that declined (pending deletes) or failed would otherwise
            # be respawned every poll tick for the whole timeout
            if builder is None and gen != kicked_gen:
                self._kick_oracle(str(name), entry)
                kicked_gen = gen
            time.sleep(0.02)

    def _oracle_after_update(self, name, entry, overlay, adds, dels,
                             gen_after, prev_oracle) -> None:
        """Post-batch index maintenance, OFF the store lock: an
        adds-only batch against a current index repairs into a fresh
        index (exact — ``oracle/trees.py``) and commits it iff nothing
        raced; anything else (a delete, a stale/absent index, repair
        drift past ``oracle_repair_max``) schedules a full background
        rebuild instead."""
        if self.oracle_k is None:
            return
        prev_ok = (
            prev_oracle is not None
            and prev_oracle.index.gen == gen_after - 1
        )
        if (dels or not prev_ok
                or prev_oracle.index.repaired_edges + len(adds)
                > self.oracle_repair_max):
            self._kick_oracle(name, entry)
            return
        from bibfs_tpu.oracle import DistanceOracle

        n = entry.snapshot.n
        canon = [canonical_edge(n, u, v) for u, v in adds]
        del_set, add_adj = overlay.correction()
        if del_set:
            # a valid index implies a dels-free overlay (builds and
            # repairs both refuse one) — defensive: never repair across
            # a delete, a relaxation through a deleted base edge would
            # under-count
            self._kick_oracle(name, entry)
            return
        row_ptr, col_ind = entry.snapshot.csr()
        with span("store_index_build", graph=name, kind="repair",
                  adds=len(canon)):
            index = prev_oracle.index.repair_adds(
                row_ptr, col_ind, add_adj, canon, gen=gen_after
            )
        with self._lock:
            if (entry.graph_gen == gen_after
                    and entry.oracle is prev_oracle):
                entry.oracle = DistanceOracle(
                    index, metrics_label=self._oracle_label(name),
                    cells=entry.oracle_cells,
                )
                entry.index_repairs += 1
            # else: a racing mutation superseded this repair — its own
            # maintenance path (which saw a stale index) rebuilds

    def _kick_oracle(self, name, entry) -> None:
        """Start a background full index build for ``name``'s live
        graph unless one is already in flight (or the tier is off)."""
        if self.oracle_k is None:
            return
        with self._lock:
            if (entry.oracle_builder is not None
                    and entry.oracle_builder.is_alive()):
                return
            entry.oracle_builder = threading.Thread(
                target=self._oracle_job, args=(name, entry),
                name=f"bibfs-oracle-{name}", daemon=True,
            )
            entry.oracle_builder.start()

    def _oracle_job(self, name, entry) -> None:
        """The background builder: capture a consistent (snapshot,
        overlay, gen) off the store lock, traverse, commit under it
        only if the gen still matches — a swap or update landing
        mid-build ABORTS the commit (the capture is stale truth) and
        the build retries against the new state a bounded number of
        times; past that, the next mutation re-kicks."""
        from bibfs_tpu.oracle import DistanceOracle, build_index

        try:
            for _attempt in range(3):
                with self._lock:
                    snap = entry.snapshot
                    overlay = entry.overlay
                    gen = entry.graph_gen
                if overlay is not None and overlay.stats()["dels"] > 0:
                    # no exact repair exists across a delete and the
                    # overlaid graph is not a snapshot: the next
                    # compaction folds it and re-kicks this builder
                    return
                if overlay is not None and overlay.delta_edges > 0:
                    from bibfs_tpu.graph.csr import build_csr

                    row_ptr, col_ind = build_csr(
                        snap.n, overlay.merged_edges()
                    )
                else:
                    row_ptr, col_ind = snap.csr()
                with span("store_index_build", graph=name,
                          k=self.oracle_k, gen=gen):
                    index = build_index(
                        snap.n, row_ptr, col_ind, self.oracle_k,
                        seed=self.oracle_seed, digest=snap.digest,
                        version=snap.version, gen=gen,
                    )
                with self._lock:
                    if entry.graph_gen == gen:
                        entry.oracle = DistanceOracle(
                            index,
                            metrics_label=self._oracle_label(name),
                            cells=entry.oracle_cells,
                        )
                        entry.index_builds += 1
                        self._c_index_builds.labels(
                            store=self.obs_label, graph=name
                        ).inc()
                        return
                    entry.index_aborts += 1
        except Exception:
            # the tier is an accelerator, not a dependency: a failed
            # build leaves every query on the solver routes — but it
            # must be VISIBLE (stats), not silent
            with self._lock:
                entry.index_failures += 1
        finally:
            with self._lock:
                entry.oracle_builder = None

    # ---- compaction + hot-swap ---------------------------------------
    def _compact_job(self, name: str, entry: _Entry) -> None:
        try:
            self._compact_inline(name)
        except Exception:
            # the overlay keeps serving exactly and the next update
            # re-triggers — but a persistently failing compaction means
            # unbounded delta growth and every query on the host overlay
            # route, so it must be VISIBLE, not swallowed: count it
            # (scraped via /metrics and surfaced in stats()).
            with self._lock:
                entry.compact_failures += 1
            self._c_compact_failures.labels(
                store=self.obs_label, graph=name
            ).inc()
        finally:
            with self._lock:
                entry.compactor = None

    def _compact_inline(self, name: str) -> GraphSnapshot:
        """Build base+delta into a fresh snapshot OFF the store lock,
        swap it in, and REBASE updates that raced the build into a
        fresh overlay over the new snapshot. The old overlay object is
        never mutated: flushes that captured it keep answering the
        exact old-base+full-delta graph (the same edge set).

        On a durable store a compaction IS a checkpoint: the capture
        and the WAL segment switch share one locked section (updates
        append+apply under that same lock, so every record is either in
        the capture — folded into the new ``.bin`` — or in the fresh
        segment, replayed on top of it), the snapshot lands as an
        atomically-replaced ``<name>.v<V>.bin``, and the manifest
        rename commits the whole thing; superseded segments/bins are
        deleted only after that rename."""
        with self._lock:
            entry = self._entry(name)
        with entry.compact_lock:
            with self._lock:
                overlay = entry.overlay
                if overlay is None or overlay.delta_edges == 0:
                    return entry.snapshot  # nothing pending: no-op
                adds, dels = overlay.capture()
                base_version = entry.snapshot.version
                if entry.wal is not None:
                    self._wal_roll_locked(name, entry)
            with span("store_compact", graph=name,
                      delta=len(adds) + len(dels)):
                # the heavy build, on the sets captured under the lock
                new, adds, dels = overlay.snapshot(adds, dels)
                bin_file = None
                arrays_dir = None
                if entry.wal is not None:
                    from bibfs_tpu.graph.io import write_graph_bin

                    new.version = base_version + 1  # re-stamped at commit
                    bin_file = self._ckpt_bin_name(name, new)
                    write_graph_bin(
                        os.path.join(self.wal_dir, bin_file),
                        new.n, new.undirected_edges(),
                    )
                    if self.mmap_arrays:
                        # the servable twin, same off-lock discipline
                        from bibfs_tpu.store.sidecar import write_sidecar

                        arrays_dir = write_sidecar(
                            self.wal_dir, name, new, fire=self._fire
                        )
                # pre-warm the carried overlay's base index off-lock
                # too: rebase residue applies under the store lock below
                rebased = DeltaOverlay(new)
                rebased.ensure_index()
                with self._lock:
                    if self._entry(name).overlay is not overlay:
                        # an external swap() landed during the build and
                        # discarded this overlay — its snapshot is the
                        # caller's declared truth; committing ours would
                        # silently overwrite it with stale
                        # old-base+delta content. Abort: the folded
                        # updates were discarded BY the swap, exactly as
                        # swap()'s contract states. (The switched WAL
                        # segment is harmless — recovery replays
                        # segments in order regardless of which
                        # checkpoint ends up committed; the orphan bin
                        # is removed unless the racing swap committed
                        # the byte-identical file.)
                        if entry.bin_file != bin_file:
                            self._unlink_quiet(bin_file)
                        if (arrays_dir is not None
                                and entry.arrays_dir != arrays_dir):
                            from bibfs_tpu.store.sidecar import (
                                remove_sidecar_quiet,
                            )

                            remove_sidecar_quiet(
                                os.path.join(self.wal_dir, arrays_dir)
                            )
                        return entry.snapshot
                    # store-relative stamp (see add())
                    new.version = entry.snapshot.version + 1
                    self._swap_locked(name, entry, new)
                    # edge-wise live-vs-new diff, NOT set subtraction: a
                    # racing update may have CANCELLED a captured
                    # pending edge, which must become a real update
                    # against the new snapshot (DeltaOverlay.rebase)
                    a2, d2 = overlay.rebase(adds, dels)
                    if a2 or d2:
                        rebased.apply(sorted(a2), sorted(d2))
                        entry.overlay = rebased
                    else:
                        entry.overlay = None
                    self._g_delta.labels(
                        store=self.obs_label, graph=name
                    ).set(len(a2) + len(d2))
                    # rebase residue means the folded digest is NOT the
                    # exact sum of the noted updates — a lineage barrier
                    self.analytics.note_fold(
                        name, new.digest, clean=not (a2 or d2)
                    )
                    entry.compactions += 1
                    self._c_compactions.labels(
                        store=self.obs_label, graph=name
                    ).inc()
                    if entry.wal is not None:
                        # the manifest rename is the checkpoint commit;
                        # a failure here (injected manifest_rename, a
                        # full disk) raises out as a counted compact
                        # failure with the in-memory swap already live —
                        # consistent either way, because the OLD
                        # manifest still governs recovery and every
                        # segment it needs is still on disk
                        self._checkpoint_locked(
                            name, entry, bin_file, arrays_dir
                        )
            if entry.wal is not None:
                self._gc_durable(name, entry)
            # the swap dropped the old index (gen moved): rebuild for
            # the fresh snapshot off the serving path
            self._kick_oracle(name, entry)
            self._maybe_rebalance()
            return new

    def compact(self, name: str) -> GraphSnapshot:
        """Force a synchronous compaction+swap NOW (the REPL ``swap``
        command). Serialized against any in-flight background
        compaction; folds whatever is pending when its turn comes."""
        return self._compact_inline(str(name))

    def roll(self, name: str, adds=(), dels=()) -> GraphSnapshot:
        """Apply one edge-update batch and synchronously fold it into a
        fresh, atomically hot-swapped snapshot — the per-replica step of
        a fleet rolling swap (``bibfs_tpu/fleet``): the router drains a
        replica, calls ``roll()`` on THAT replica's store, ready-probes,
        re-admits, and moves to the next, so the fleet serves mixed
        versions mid-roll while every replica's answers stay exact for
        the version it declares. With nothing passed and nothing
        pending this is a no-op returning the current snapshot."""
        name = str(name)
        if adds or dels:
            self.update(name, adds=adds, dels=dels)
        return self.compact(name)

    def swap(self, name: str, snapshot: GraphSnapshot) -> GraphSnapshot:
        """Atomically point ``name`` at an externally built snapshot.
        Returns the OLD snapshot (already released by the store; it
        retires once in-flight flush pins drop). Any pending overlay is
        discarded — the new snapshot is the caller's declared truth.

        On a durable store the declared truth is checkpointed too: the
        snapshot lands as an atomic ``<name>.v<V>.<digest>.bin``, the
        WAL switches to a fresh segment, and the manifest rename
        commits — all BEFORE the in-memory flip, in the same continuous
        locked section. The ordering matters here in a way it does not
        for compaction: a swap DISCARDS the pending overlay, so an
        in-memory-first commit whose manifest rename then failed would
        fork history (the live process acks updates validated against
        the new snapshot while the old manifest still replays the
        discarded overlay). Durable-commit-first means a manifest
        failure raises with the in-memory state — and therefore every
        future ack — unchanged; a crash between the rename and the flip
        just recovers to the declared truth the caller asked for."""
        name = str(name)
        bin_file = None
        arrays_dir = None
        with self._lock:
            entry = self._entry(name)
            if entry.wal is not None:
                if snapshot.version <= entry.snapshot.version:
                    raise ValueError(
                        f"swap must move {name!r} forward: new version "
                        f"{snapshot.version} <= current "
                        f"{entry.snapshot.version}"
                    )
                bin_file = self._ckpt_bin_name(name, snapshot)
        if bin_file is not None:
            # the heavy writes, OFF the store lock; an abort below
            # leaves only cleaned-up orphans
            from bibfs_tpu.graph.io import write_graph_bin

            write_graph_bin(
                os.path.join(self.wal_dir, bin_file),
                snapshot.n, snapshot.undirected_edges(),
            )
            if self.mmap_arrays:
                from bibfs_tpu.store.sidecar import write_sidecar

                arrays_dir = write_sidecar(
                    self.wal_dir, name, snapshot, fire=self._fire
                )
        try:
            with self._lock:
                entry = self._entry(name)
                if entry.wal is not None:
                    # re-validate under THIS lock hold (the bin write
                    # above ran off-lock): from here to the in-memory
                    # flip nothing can interleave, so the durable
                    # commit and the flip cannot disagree
                    if snapshot.version <= entry.snapshot.version:
                        raise ValueError(
                            f"swap must move {name!r} forward: new "
                            f"version {snapshot.version} <= current "
                            f"{entry.snapshot.version}"
                        )
                    self._wal_roll_locked(name, entry)
                    with span("store_checkpoint", graph=name,
                              version=snapshot.version,
                              wal_seq=entry.wal_seq):
                        self._write_manifest_locked(
                            name, entry,
                            snapshot=snapshot, bin_file=bin_file,
                            arrays_dir=arrays_dir,
                        )
                        entry.bin_file = bin_file
                        entry.arrays_dir = arrays_dir
                        entry.checkpoints += 1
                        self._c_checkpoints.labels(
                            store=self.obs_label, graph=name
                        ).inc()
                old = self._swap_locked(name, entry, snapshot)
                # declared-truth replacement: no maintainable lineage
                self.analytics.note_swap(name, snapshot.digest)
                entry.overlay = None
                self._g_delta.labels(
                    store=self.obs_label, graph=name
                ).set(0)
        except BaseException:
            # never unlink a file a COMMITTED manifest references: a
            # racing checkpoint can only have produced this exact path
            # with byte-identical content (digest-suffixed name)
            if entry.bin_file != bin_file:
                self._unlink_quiet(bin_file)
            raise
        if entry.wal is not None:
            self._gc_durable(name, entry)
        self._kick_oracle(name, entry)
        return old

    def _swap_locked(self, name: str, entry: _Entry,
                     new: GraphSnapshot) -> GraphSnapshot:
        old = entry.snapshot
        if new.version <= old.version:
            raise ValueError(
                f"swap must move {name!r} forward: new version "
                f"{new.version} <= current {old.version}"
            )
        with span("store_swap", graph=name, version=new.version,
                  old_version=old.version):
            entry.snapshot = new
            entry.swaps += 1
            # the follow-the-graph swap: gen moves with the snapshot in
            # ONE locked mutation, and the superseded index is dropped
            # outright (its memory goes with it) — a caller sees either
            # (old snapshot, old index) or (new snapshot, no index),
            # never a cross pairing. Callers kick the rebuild after
            # releasing the lock.
            entry.graph_gen += 1
            entry.oracle = None
            self._c_swaps.labels(store=self.obs_label, graph=name).inc()
            old.release()  # the store's reference; flush pins remain
        return old

    # ---- residency accountant (memory tiers, module docstring) -------
    def _refresh_memory_metrics(self) -> None:
        """Scrape-time gauge refresh: per-graph mapped bytes + the
        tier census. Snapshot reads only (each snapshot's own lock
        nests inside the store lock, the established order)."""
        with self._lock:
            snaps = {
                name: e.snapshot for name, e in self._entries.items()
            }
        tiers = {"mapped": 0, "hot": 0, "cold": 0}
        for name, snap in snaps.items():
            self._g_mmap_bytes.labels(
                store=self.obs_label, graph=name
            ).set(snap.mapped_bytes())
            tiers[snap.tier] += 1
        for tier, count in tiers.items():
            self._g_tier.labels(store=self.obs_label, tier=tier).set(count)

    def _maybe_rebalance(self) -> None:
        if self.residency_budget is not None:
            self.rebalance()

    def rebalance(self) -> dict:
        """One accountant pass: while the store's process-private
        resident total exceeds ``residency_budget``, demote the
        least-recently-acquired hot graph to the compressed cold tier
        (``GraphSnapshot.demote`` — encode runs off the store lock; the
        serving pointer never moves, a cold graph just decodes back on
        its next access). Called after every registration, update batch
        and compaction commit; callable any time. Returns what it did."""
        with self._lock:
            candidates = [
                (e.touched, name, e.snapshot)
                for name, e in self._entries.items()
            ]
        total = sum(s.resident_bytes() for _, _, s in candidates)
        demoted: list[str] = []
        freed = 0
        if self.residency_budget is not None:
            for _touched, name, snap in sorted(
                    candidates, key=lambda c: c[0]):
                if total <= self.residency_budget:
                    break
                if snap.tier != "hot":
                    continue
                got = snap.demote()
                if got > 0:
                    total -= got
                    freed += got
                    demoted.append(name)
        self._refresh_memory_metrics()
        return {
            "demoted": demoted,
            "freed_bytes": freed,
            "resident_bytes": total,
        }

    def memory_stats(self) -> dict:
        """Per-graph tier / resident / mapped bytes plus the budget
        headroom — the ``bibfs-serve`` stdin ``memory`` command's
        payload and the memtier soak's probe."""
        with self._lock:
            per = {}
            for name, entry in self._entries.items():
                per[name] = {
                    **entry.snapshot.memory(),
                    "version": entry.snapshot.version,
                    "digest": entry.snapshot.digest,
                    "arrays": entry.arrays_dir,
                }
        resident = sum(g["resident_bytes"] for g in per.values())
        mapped = sum(g["mapped_bytes"] for g in per.values())
        budget = self.residency_budget
        return {
            "graphs": per,
            "resident_bytes": resident,
            "mapped_bytes": mapped,
            "residency_budget": budget,
            "headroom_bytes": (
                None if budget is None else budget - resident
            ),
            "mmap_arrays": self.mmap_arrays,
        }

    # ---- time-travel reads (store/history.py) ------------------------
    def history(self, name: str) -> list[dict]:
        """The graph's committed version history entries (empty on a
        non-durable store or before the first commit)."""
        if self.wal_dir is None:
            return []
        from bibfs_tpu.store.history import load_history

        return load_history(self.wal_dir, str(name))

    def reconstruct_version(self, name: str, version: int) -> GraphSnapshot:
        """The graph as of committed ``version`` — a FRESH, unpinned
        :class:`~bibfs_tpu.store.snapshot.GraphSnapshot` the caller
        owns (digest-verified against the history recorded at commit
        time; ``store/history.py``). The current version answers from
        the live base snapshot's canonical pairs without touching
        disk. Raises ``ValueError`` for an unknown or no-longer-
        provable version — a history read is exact or refused, never
        approximate."""
        name, version = str(name), int(version)
        with self._lock:
            cur = self._entry(name).snapshot
        if version == cur.version:
            # fresh object sharing the immutable pairs array: the
            # caller's refcount lifecycle stays decoupled from the
            # store's (a later hot-swap retires only the store's)
            return GraphSnapshot(
                cur.n, cur.pairs, digest=cur.digest, version=version
            )
        if self.wal_dir is None:
            raise ValueError(
                f"as_of version {version} != current {cur.version} "
                f"needs a durable store (wal_dir=) to reconstruct from"
            )
        from bibfs_tpu.store.history import reconstruct_version

        return reconstruct_version(self.wal_dir, name, version)

    # ---- introspection ----------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            graphs = {}
            for name, entry in self._entries.items():
                graphs[name] = {
                    **entry.snapshot.stats(),
                    "delta_edges": (
                        0 if entry.overlay is None
                        else entry.overlay.delta_edges
                    ),
                    "swaps": entry.swaps,
                    "compactions": entry.compactions,
                    "compact_failures": entry.compact_failures,
                    "compacting": entry.compactor is not None,
                    "oracle": self._oracle_stats_locked(entry),
                }
                if entry.wal is not None:
                    graphs[name]["durable"] = {
                        "wal_seq": entry.wal_seq,
                        "wal": entry.wal.stats(),
                        "bin": entry.bin_file,
                        "arrays": entry.arrays_dir,
                        "checkpoints": entry.checkpoints,
                        "recovered": entry.recovered,
                    }
            return {
                "graphs": graphs,
                "default": self._default,
                "compact_threshold": self.compact_threshold,
                "oracle_k": self.oracle_k,
                "durable": self.wal_dir is not None,
                "retain_history": self.retain_history,
                "fsync": self.fsync if self.wal_dir is not None else None,
                "load_errors": list(self.load_errors),
                # leaf lock below this one — same order as the commit
                # hooks (note_update/note_fold under self._lock)
                "analytics": self.analytics.stats(),
            }

    def _oracle_stats_locked(self, entry: _Entry) -> dict | None:
        if self.oracle_k is None:
            return None
        orc = entry.oracle
        current = orc is not None and orc.index.gen == entry.graph_gen
        out = {
            "k": self.oracle_k,
            "ready": current,
            "gen": entry.graph_gen,
            "builds": entry.index_builds,
            "repairs": entry.index_repairs,
            "aborts": entry.index_aborts,
            "failures": entry.index_failures,
            "building": entry.oracle_builder is not None,
        }
        if orc is not None:
            out["index"] = orc.index.stats()
            out["hits"] = {k: c.value for k, c in orc.cells.items()}
        elif entry.oracle_cells is not None:
            out["hits"] = {
                k: c.value for k, c in entry.oracle_cells.items()
            }
        return out

    def close(self) -> None:
        """Join in-flight background compactions and index builds, and
        close the WAL writers (final fsync barrier) — test/shutdown
        aid."""
        with self._lock:
            jobs = [
                e.compactor for e in self._entries.values()
                if e.compactor is not None
            ] + [
                e.oracle_builder for e in self._entries.values()
                if e.oracle_builder is not None
            ]
        for job in jobs:
            job.join()
        with self._lock:
            wals = [
                e.wal for e in self._entries.values()
                if e.wal is not None
            ]
        for w in wals:
            try:
                w.close()
            except OSError:
                pass
