"""Named multi-graph store with atomic hot-swap.

One serving process, many graphs, each one live-updatable: the
:class:`GraphStore` maps names to their current
:class:`~bibfs_tpu.store.snapshot.GraphSnapshot` (plus a pending
:class:`~bibfs_tpu.store.delta.DeltaOverlay` when edge updates have
arrived since the last compaction). The engines resolve a name to a
snapshot at flush time and pin it for the flush, so a swap is:

1. build the replacement snapshot (compaction — background thread, or
   any externally built snapshot handed to :meth:`swap`);
2. under the store lock, point the name at the new snapshot — the swap
   itself is a pointer flip plus metrics, so serving traffic never
   waits on a rebuild;
3. in-flight flushes finish on the OLD snapshot through their pins; the
   old snapshot retires when the last pin drops
   (refcount — ``snapshot.release``).

Updates below the compaction threshold serve exactly through the
overlay (``serve/engine`` routes those queries to
:meth:`DeltaOverlay.solve`); once ``delta_edges`` reaches
``compact_threshold`` the store kicks a background compaction that
rebuilds the ELL into a fresh snapshot off the hot path and swaps it
in. An overlay is never mutated once handed out: a compaction REBASES
the updates that raced its build into a fresh overlay over the new
snapshot, so a flush that grabbed the old overlay keeps answering the
exact old-base+full-delta graph — which is, by construction, the same
edge set the new snapshot + rebased overlay describes.

**Distance-oracle tier** (``oracle_k=K``): each graph additionally
carries a landmark :class:`~bibfs_tpu.oracle.DistanceOracle` built as
background work off the serving path — the same compaction-style
discipline: build from a consistent capture off the store lock, commit
under it only if nothing moved. The follow-the-graph invariant is one
integer: every mutation of a graph's *live* edge state (an update
batch, a hot-swap, a compaction commit) bumps ``graph_gen``, every
index is stamped with the gen it was built for, and :meth:`oracle`
refuses to return an index whose gen is not current — a stale index can
never answer for a newer graph, by construction rather than by timing.
Adds-only update batches are repaired INTO a fresh index synchronously
(exact — see ``oracle/trees.py``; bounded by ``oracle_repair_max``,
past which a full rebuild is scheduled instead); a delete invalidates
the index until the next compaction folds it into a snapshot the
builder can traverse.

Observability: ``bibfs_store_graphs`` (gauge), ``bibfs_store_swaps_total``
/ ``bibfs_store_compactions_total`` / ``bibfs_store_compact_failures_total``
(counters, per graph), ``bibfs_store_delta_edges`` (gauge, per graph),
``bibfs_oracle_index_builds_total`` (counter, per graph) and
``bibfs_oracle_index_age_seconds`` (gauge, per graph, refreshed at
scrape time) in the process registry, plus ``store_swap`` /
``store_compact`` / ``store_index_build`` trace spans.
"""

from __future__ import annotations

import os
import threading
import time
import weakref

from bibfs_tpu.obs.metrics import REGISTRY, next_instance_label
from bibfs_tpu.obs.trace import span
from bibfs_tpu.store.delta import DeltaOverlay, canonical_edge
from bibfs_tpu.store.snapshot import GraphSnapshot


class _Entry:
    """One named graph's mutable slot: current snapshot, pending
    overlay, the compaction serializer (one compaction per graph at
    a time — a forced REPL ``swap`` racing a threshold-triggered
    background job must not double-build), and the distance-oracle
    state (current oracle + its live-graph generation tag, the in-
    flight builder, per-graph build accounting)."""

    __slots__ = ("snapshot", "overlay", "compactor", "compact_lock",
                 "swaps", "compactions", "compact_failures",
                 "graph_gen", "oracle", "oracle_builder", "oracle_cells",
                 "index_builds", "index_aborts", "index_repairs",
                 "index_failures")

    def __init__(self, snapshot: GraphSnapshot):
        self.snapshot = snapshot
        self.overlay: DeltaOverlay | None = None
        self.compactor: threading.Thread | None = None
        self.compact_lock = threading.Lock()
        self.swaps = 0
        self.compactions = 0
        self.compact_failures = 0
        # live-graph generation: bumped on every update batch, swap and
        # compaction commit — the oracle's follow-the-graph tag
        self.graph_gen = 1
        self.oracle = None  # DistanceOracle | None
        self.oracle_builder: threading.Thread | None = None
        self.oracle_cells: dict | None = None
        self.index_builds = 0
        self.index_aborts = 0
        self.index_repairs = 0
        self.index_failures = 0


class GraphStore:
    """Named, versioned, hot-swappable graphs (module docstring).

    Parameters
    ----------
    compact_threshold : pending delta edges at which a background
        compaction (rebuild + swap) is triggered. ``None`` disables
        auto-compaction (explicit :meth:`compact` / :meth:`swap` only).
    oracle_k : landmarks per graph for the distance-oracle tier
        (module docstring). ``None`` (default) disables the tier —
        :meth:`oracle` then always returns None and nothing is built.
    oracle_repair_max : adds folded into one index by incremental
        repair before a full rebuild is scheduled instead (the rebuild
        threshold; repair is exact either way, this bounds the drift a
        single index accumulates before re-selection of landmarks).
    oracle_seed : landmark-selection seed (deterministic rebuilds).
    obs_label : the ``store=`` label value this store's registry cells
        carry (default: a process-unique ``store-N``).
    """

    def __init__(self, *, compact_threshold: int | None = 256,
                 oracle_k: int | None = None,
                 oracle_repair_max: int = 64,
                 oracle_seed: int = 0,
                 obs_label: str | None = None):
        self.compact_threshold = (
            None if compact_threshold is None else int(compact_threshold)
        )
        if self.compact_threshold is not None and self.compact_threshold < 1:
            raise ValueError(
                f"compact_threshold must be >= 1, got {compact_threshold}"
            )
        self.obs_label = (
            next_instance_label("store") if obs_label is None else obs_label
        )
        self._lock = threading.RLock()
        self._entries: dict[str, _Entry] = {}
        self._default: str | None = None
        self._g_graphs = REGISTRY.gauge(
            "bibfs_store_graphs", "Graphs registered in a graph store",
            ("store",),
        ).labels(store=self.obs_label)
        self._c_swaps = REGISTRY.counter(
            "bibfs_store_swaps_total",
            "Atomic snapshot hot-swaps per graph",
            ("store", "graph"),
        )
        self._g_delta = REGISTRY.gauge(
            "bibfs_store_delta_edges",
            "Pending overlay edge updates per graph",
            ("store", "graph"),
        )
        self._c_compactions = REGISTRY.counter(
            "bibfs_store_compactions_total",
            "Delta compactions (overlay folded into a fresh snapshot)",
            ("store", "graph"),
        )
        self._c_compact_failures = REGISTRY.counter(
            "bibfs_store_compact_failures_total",
            "Background compactions that raised (overlay keeps serving; "
            "the next update re-triggers)",
            ("store", "graph"),
        )
        self.oracle_k = None if oracle_k is None else int(oracle_k)
        if self.oracle_k is not None and self.oracle_k < 1:
            raise ValueError(f"oracle_k must be >= 1, got {oracle_k}")
        self.oracle_repair_max = int(oracle_repair_max)
        self.oracle_seed = int(oracle_seed)
        self._c_index_builds = REGISTRY.counter(
            "bibfs_oracle_index_builds_total",
            "Full landmark-index builds committed per graph "
            "(incremental repairs not included)",
            ("store", "graph"),
        )
        self._g_index_age = REGISTRY.gauge(
            "bibfs_oracle_index_age_seconds",
            "Age of the graph's CURRENT landmark index (0 when the "
            "graph has none); refreshed at scrape time",
            ("store", "graph"),
        )
        if self.oracle_k is not None:
            # scrape-time age refresh, weakly bound like the engines'
            # health collector: a dead store must unregister itself, not
            # pin its graphs for process lifetime
            self_ref = weakref.ref(self)

            def _collect_index_age():
                st = self_ref()
                if st is None:
                    return False
                now = time.time()
                with st._lock:
                    for nm, e in st._entries.items():
                        st._g_index_age.labels(
                            store=st.obs_label, graph=nm
                        ).set(
                            0.0 if e.oracle is None
                            else max(now - e.oracle.index.built_at, 0.0)
                        )
                return True

            REGISTRY.add_collector(_collect_index_age)

    # ---- registration -----------------------------------------------
    def add(self, name: str, n: int | None = None, edges=None, *,
            pairs=None, snapshot: GraphSnapshot | None = None
            ) -> GraphSnapshot:
        """Register a graph under ``name`` (its version-1 snapshot).
        The first added graph becomes the default."""
        name = str(name)
        if snapshot is None:
            if n is None:
                raise ValueError("add() needs n+edges/pairs or snapshot=")
            snapshot = GraphSnapshot.build(n, edges, pairs=pairs)
        with self._lock:
            if name in self._entries:
                raise ValueError(
                    f"graph {name!r} already registered (swap() replaces)"
                )
            # versions are store-relative: every registered graph starts
            # at v1, compaction stamps old+1 — so `graphs` output and
            # stats read as each graph's OWN history, not the order the
            # process happened to build snapshots in. (The build-time
            # global stamp remains the fallback for snapshots that never
            # enter a store.)
            snapshot.version = 1
            entry = _Entry(snapshot)
            self._entries[name] = entry
            if self._default is None:
                self._default = name
            self._g_graphs.set(len(self._entries))
            # mint the per-graph cells now so a scrape shows the graph
            # at zero before its first update/swap
            self._c_swaps.labels(store=self.obs_label, graph=name)
            self._g_delta.labels(store=self.obs_label, graph=name).set(0)
            self._c_compactions.labels(store=self.obs_label, graph=name)
            self._c_compact_failures.labels(store=self.obs_label, graph=name)
            if self.oracle_k is not None:
                from bibfs_tpu.oracle import oracle_cells

                entry.oracle_cells = oracle_cells(
                    self._oracle_label(name)
                )
                self._c_index_builds.labels(
                    store=self.obs_label, graph=name
                )
                self._g_index_age.labels(
                    store=self.obs_label, graph=name
                ).set(0.0)
        self._kick_oracle(name, entry)
        return snapshot

    @classmethod
    def from_dir(cls, path, **kwargs) -> "GraphStore":
        """A store over every ``*.bin`` graph in a directory, each
        registered under its file stem (``social.bin`` -> ``social``),
        sorted so the default graph is deterministic."""
        from bibfs_tpu.graph.io import read_graph_bin

        store = cls(**kwargs)
        names = sorted(
            f for f in os.listdir(path) if f.endswith(".bin")
        )
        if not names:
            raise ValueError(f"no *.bin graphs in {path!r}")
        for fname in names:
            n, edges = read_graph_bin(os.path.join(path, fname))
            store.add(os.path.splitext(fname)[0], n, edges)
        return store

    # ---- resolution --------------------------------------------------
    def _entry(self, name: str) -> _Entry:
        entry = self._entries.get(str(name))
        if entry is None:
            raise KeyError(
                f"unknown graph {name!r} (have: {sorted(self._entries)})"
            )
        return entry

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def default_graph(self) -> str:
        with self._lock:
            if self._default is None:
                raise ValueError("store has no graphs")
            return self._default

    def current(self, name: str) -> GraphSnapshot:
        """The graph's current snapshot — an identity read (cheap
        same-version check). Pin with :meth:`acquire` before USING one
        across a swap window."""
        with self._lock:
            return self._entry(name).snapshot

    def acquire(self, name: str) -> GraphSnapshot:
        """The current snapshot, retained under the store lock — so a
        concurrent swap cannot retire it between the read and the pin.
        The caller owns one ``release()``."""
        with self._lock:
            return self._entry(name).snapshot.retain()

    def overlay(self, name: str) -> DeltaOverlay | None:
        """The graph's pending overlay, or None when it has no pending
        updates — the engines' exact-answering route check."""
        with self._lock:
            ov = self._entry(name).overlay
        if ov is not None and ov.delta_edges == 0:
            return None
        return ov

    # ---- live updates ------------------------------------------------
    def update(self, name: str, adds=(), dels=()) -> dict:
        """Apply one batch of undirected edge updates to ``name``'s
        overlay (creating it on first update). Crossing
        ``compact_threshold`` kicks a background compaction. Returns
        ``{"adds": ..., "dels": ..., "compacting": bool}``."""
        name = str(name)
        adds = [tuple(e) for e in adds]  # consumed twice when the
        dels = [tuple(e) for e in dels]  # oracle repairs (below)
        while True:
            with self._lock:
                entry = self._entry(name)
                if entry.overlay is None:
                    entry.overlay = DeltaOverlay(entry.snapshot)
                overlay = entry.overlay
            # the first apply against a base needs its O(E) membership
            # index — build it OFF the store lock (every serving thread
            # resolves names through that lock; a Python pass over
            # every edge under it is a serving stall)
            overlay.ensure_index()
            with self._lock:
                if self._entry(name).overlay is not overlay:
                    # a swap/compaction replaced the overlay while the
                    # index built: restart against the current state
                    continue
                counts = overlay.apply(adds, dels)
                # the live graph changed: the oracle gen moves forward
                # IN THE SAME locked section as the apply, so no reader
                # can pair the new edge state with the old index
                entry.graph_gen += 1
                gen_after = entry.graph_gen
                prev_oracle = entry.oracle
                delta = counts["adds"] + counts["dels"]
                self._g_delta.labels(
                    store=self.obs_label, graph=name
                ).set(delta)
                compacting = entry.compactor is not None
                if (not compacting and self.compact_threshold is not None
                        and delta >= self.compact_threshold):
                    entry.compactor = threading.Thread(
                        target=self._compact_job, args=(name, entry),
                        name=f"bibfs-compact-{name}", daemon=True,
                    )
                    entry.compactor.start()
                    compacting = True
            self._oracle_after_update(
                name, entry, overlay, adds, dels, gen_after, prev_oracle
            )
            return {**counts, "compacting": compacting}

    # ---- oracle lifecycle --------------------------------------------
    def _oracle_label(self, name: str) -> str:
        return f"{self.obs_label}/{name}"

    def oracle(self, name: str):
        """The graph's :class:`~bibfs_tpu.oracle.DistanceOracle`, or
        None when disabled / not (yet) built for the CURRENT live edge
        state — the follow-the-graph read the engines route through: a
        gen mismatch means the index describes a superseded graph and
        is simply not returned, so a stale index can never answer."""
        if self.oracle_k is None:
            return None
        with self._lock:
            entry = self._entry(name)
            orc = entry.oracle
            if orc is None or orc.index.gen != entry.graph_gen:
                return None
            return orc

    def wait_for_index(self, name: str, timeout: float = 60.0) -> bool:
        """Block until ``name`` has a current index (True) or the
        timeout passes (False) — a test/bench aid; serving code never
        waits, it just falls through to the solvers until the
        background build commits. Re-kicks the builder if nothing is
        in flight (e.g. after an aborted build)."""
        deadline = time.monotonic() + timeout
        kicked_gen = None
        while True:
            if self.oracle(str(name)) is not None:
                return True
            if time.monotonic() >= deadline:
                return False
            with self._lock:
                entry = self._entry(str(name))
                builder = entry.oracle_builder
                gen = entry.graph_gen
            # at most one re-kick per live-graph generation: a builder
            # that declined (pending deletes) or failed would otherwise
            # be respawned every poll tick for the whole timeout
            if builder is None and gen != kicked_gen:
                self._kick_oracle(str(name), entry)
                kicked_gen = gen
            time.sleep(0.02)

    def _oracle_after_update(self, name, entry, overlay, adds, dels,
                             gen_after, prev_oracle) -> None:
        """Post-batch index maintenance, OFF the store lock: an
        adds-only batch against a current index repairs into a fresh
        index (exact — ``oracle/trees.py``) and commits it iff nothing
        raced; anything else (a delete, a stale/absent index, repair
        drift past ``oracle_repair_max``) schedules a full background
        rebuild instead."""
        if self.oracle_k is None:
            return
        prev_ok = (
            prev_oracle is not None
            and prev_oracle.index.gen == gen_after - 1
        )
        if (dels or not prev_ok
                or prev_oracle.index.repaired_edges + len(adds)
                > self.oracle_repair_max):
            self._kick_oracle(name, entry)
            return
        from bibfs_tpu.oracle import DistanceOracle

        n = entry.snapshot.n
        canon = [canonical_edge(n, u, v) for u, v in adds]
        del_set, add_adj = overlay.correction()
        if del_set:
            # a valid index implies a dels-free overlay (builds and
            # repairs both refuse one) — defensive: never repair across
            # a delete, a relaxation through a deleted base edge would
            # under-count
            self._kick_oracle(name, entry)
            return
        row_ptr, col_ind = entry.snapshot.csr()
        with span("store_index_build", graph=name, kind="repair",
                  adds=len(canon)):
            index = prev_oracle.index.repair_adds(
                row_ptr, col_ind, add_adj, canon, gen=gen_after
            )
        with self._lock:
            if (entry.graph_gen == gen_after
                    and entry.oracle is prev_oracle):
                entry.oracle = DistanceOracle(
                    index, metrics_label=self._oracle_label(name),
                    cells=entry.oracle_cells,
                )
                entry.index_repairs += 1
            # else: a racing mutation superseded this repair — its own
            # maintenance path (which saw a stale index) rebuilds

    def _kick_oracle(self, name, entry) -> None:
        """Start a background full index build for ``name``'s live
        graph unless one is already in flight (or the tier is off)."""
        if self.oracle_k is None:
            return
        with self._lock:
            if (entry.oracle_builder is not None
                    and entry.oracle_builder.is_alive()):
                return
            entry.oracle_builder = threading.Thread(
                target=self._oracle_job, args=(name, entry),
                name=f"bibfs-oracle-{name}", daemon=True,
            )
            entry.oracle_builder.start()

    def _oracle_job(self, name, entry) -> None:
        """The background builder: capture a consistent (snapshot,
        overlay, gen) off the store lock, traverse, commit under it
        only if the gen still matches — a swap or update landing
        mid-build ABORTS the commit (the capture is stale truth) and
        the build retries against the new state a bounded number of
        times; past that, the next mutation re-kicks."""
        from bibfs_tpu.oracle import DistanceOracle, build_index

        try:
            for _attempt in range(3):
                with self._lock:
                    snap = entry.snapshot
                    overlay = entry.overlay
                    gen = entry.graph_gen
                if overlay is not None and overlay.stats()["dels"] > 0:
                    # no exact repair exists across a delete and the
                    # overlaid graph is not a snapshot: the next
                    # compaction folds it and re-kicks this builder
                    return
                if overlay is not None and overlay.delta_edges > 0:
                    from bibfs_tpu.graph.csr import build_csr

                    row_ptr, col_ind = build_csr(
                        snap.n, overlay.merged_edges()
                    )
                else:
                    row_ptr, col_ind = snap.csr()
                with span("store_index_build", graph=name,
                          k=self.oracle_k, gen=gen):
                    index = build_index(
                        snap.n, row_ptr, col_ind, self.oracle_k,
                        seed=self.oracle_seed, digest=snap.digest,
                        version=snap.version, gen=gen,
                    )
                with self._lock:
                    if entry.graph_gen == gen:
                        entry.oracle = DistanceOracle(
                            index,
                            metrics_label=self._oracle_label(name),
                            cells=entry.oracle_cells,
                        )
                        entry.index_builds += 1
                        self._c_index_builds.labels(
                            store=self.obs_label, graph=name
                        ).inc()
                        return
                    entry.index_aborts += 1
        except Exception:
            # the tier is an accelerator, not a dependency: a failed
            # build leaves every query on the solver routes — but it
            # must be VISIBLE (stats), not silent
            with self._lock:
                entry.index_failures += 1
        finally:
            with self._lock:
                entry.oracle_builder = None

    # ---- compaction + hot-swap ---------------------------------------
    def _compact_job(self, name: str, entry: _Entry) -> None:
        try:
            self._compact_inline(name)
        except Exception:
            # the overlay keeps serving exactly and the next update
            # re-triggers — but a persistently failing compaction means
            # unbounded delta growth and every query on the host overlay
            # route, so it must be VISIBLE, not swallowed: count it
            # (scraped via /metrics and surfaced in stats()).
            with self._lock:
                entry.compact_failures += 1
            self._c_compact_failures.labels(
                store=self.obs_label, graph=name
            ).inc()
        finally:
            with self._lock:
                entry.compactor = None

    def _compact_inline(self, name: str) -> GraphSnapshot:
        """Build base+delta into a fresh snapshot OFF the store lock,
        swap it in, and REBASE updates that raced the build into a
        fresh overlay over the new snapshot. The old overlay object is
        never mutated: flushes that captured it keep answering the
        exact old-base+full-delta graph (the same edge set)."""
        with self._lock:
            entry = self._entry(name)
        with entry.compact_lock:
            with self._lock:
                overlay = entry.overlay
                if overlay is None or overlay.delta_edges == 0:
                    return entry.snapshot  # nothing pending: no-op
            with span("store_compact", graph=name,
                      delta=overlay.delta_edges):
                new, adds, dels = overlay.snapshot()  # the heavy build
                # pre-warm the carried overlay's base index off-lock
                # too: rebase residue applies under the store lock below
                rebased = DeltaOverlay(new)
                rebased.ensure_index()
                with self._lock:
                    if self._entry(name).overlay is not overlay:
                        # an external swap() landed during the build and
                        # discarded this overlay — its snapshot is the
                        # caller's declared truth; committing ours would
                        # silently overwrite it with stale
                        # old-base+delta content. Abort: the folded
                        # updates were discarded BY the swap, exactly as
                        # swap()'s contract states.
                        return entry.snapshot
                    # store-relative stamp (see add())
                    new.version = entry.snapshot.version + 1
                    self._swap_locked(name, entry, new)
                    # edge-wise live-vs-new diff, NOT set subtraction: a
                    # racing update may have CANCELLED a captured
                    # pending edge, which must become a real update
                    # against the new snapshot (DeltaOverlay.rebase)
                    a2, d2 = overlay.rebase(adds, dels)
                    if a2 or d2:
                        rebased.apply(sorted(a2), sorted(d2))
                        entry.overlay = rebased
                    else:
                        entry.overlay = None
                    self._g_delta.labels(
                        store=self.obs_label, graph=name
                    ).set(len(a2) + len(d2))
                    entry.compactions += 1
                    self._c_compactions.labels(
                        store=self.obs_label, graph=name
                    ).inc()
            # the swap dropped the old index (gen moved): rebuild for
            # the fresh snapshot off the serving path
            self._kick_oracle(name, entry)
            return new

    def compact(self, name: str) -> GraphSnapshot:
        """Force a synchronous compaction+swap NOW (the REPL ``swap``
        command). Serialized against any in-flight background
        compaction; folds whatever is pending when its turn comes."""
        return self._compact_inline(str(name))

    def roll(self, name: str, adds=(), dels=()) -> GraphSnapshot:
        """Apply one edge-update batch and synchronously fold it into a
        fresh, atomically hot-swapped snapshot — the per-replica step of
        a fleet rolling swap (``bibfs_tpu/fleet``): the router drains a
        replica, calls ``roll()`` on THAT replica's store, ready-probes,
        re-admits, and moves to the next, so the fleet serves mixed
        versions mid-roll while every replica's answers stay exact for
        the version it declares. With nothing passed and nothing
        pending this is a no-op returning the current snapshot."""
        name = str(name)
        if adds or dels:
            self.update(name, adds=adds, dels=dels)
        return self.compact(name)

    def swap(self, name: str, snapshot: GraphSnapshot) -> GraphSnapshot:
        """Atomically point ``name`` at an externally built snapshot.
        Returns the OLD snapshot (already released by the store; it
        retires once in-flight flush pins drop). Any pending overlay is
        discarded — the new snapshot is the caller's declared truth."""
        name = str(name)
        with self._lock:
            entry = self._entry(name)
            old = self._swap_locked(name, entry, snapshot)
            entry.overlay = None
            self._g_delta.labels(store=self.obs_label, graph=name).set(0)
        self._kick_oracle(name, entry)
        return old

    def _swap_locked(self, name: str, entry: _Entry,
                     new: GraphSnapshot) -> GraphSnapshot:
        old = entry.snapshot
        if new.version <= old.version:
            raise ValueError(
                f"swap must move {name!r} forward: new version "
                f"{new.version} <= current {old.version}"
            )
        with span("store_swap", graph=name, version=new.version,
                  old_version=old.version):
            entry.snapshot = new
            entry.swaps += 1
            # the follow-the-graph swap: gen moves with the snapshot in
            # ONE locked mutation, and the superseded index is dropped
            # outright (its memory goes with it) — a caller sees either
            # (old snapshot, old index) or (new snapshot, no index),
            # never a cross pairing. Callers kick the rebuild after
            # releasing the lock.
            entry.graph_gen += 1
            entry.oracle = None
            self._c_swaps.labels(store=self.obs_label, graph=name).inc()
            old.release()  # the store's reference; flush pins remain
        return old

    # ---- introspection ----------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            graphs = {}
            for name, entry in self._entries.items():
                graphs[name] = {
                    **entry.snapshot.stats(),
                    "delta_edges": (
                        0 if entry.overlay is None
                        else entry.overlay.delta_edges
                    ),
                    "swaps": entry.swaps,
                    "compactions": entry.compactions,
                    "compact_failures": entry.compact_failures,
                    "compacting": entry.compactor is not None,
                    "oracle": self._oracle_stats_locked(entry),
                }
            return {
                "graphs": graphs,
                "default": self._default,
                "compact_threshold": self.compact_threshold,
                "oracle_k": self.oracle_k,
            }

    def _oracle_stats_locked(self, entry: _Entry) -> dict | None:
        if self.oracle_k is None:
            return None
        orc = entry.oracle
        current = orc is not None and orc.index.gen == entry.graph_gen
        out = {
            "k": self.oracle_k,
            "ready": current,
            "gen": entry.graph_gen,
            "builds": entry.index_builds,
            "repairs": entry.index_repairs,
            "aborts": entry.index_aborts,
            "failures": entry.index_failures,
            "building": entry.oracle_builder is not None,
        }
        if orc is not None:
            out["index"] = orc.index.stats()
            out["hits"] = {k: c.value for k, c in orc.cells.items()}
        elif entry.oracle_cells is not None:
            out["hits"] = {
                k: c.value for k, c in entry.oracle_cells.items()
            }
        return out

    def close(self) -> None:
        """Join in-flight background compactions and index builds
        (test/shutdown aid)."""
        with self._lock:
            jobs = [
                e.compactor for e in self._entries.values()
                if e.compactor is not None
            ] + [
                e.oracle_builder for e in self._entries.values()
                if e.oracle_builder is not None
            ]
        for job in jobs:
            job.join()
