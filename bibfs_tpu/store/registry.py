"""Named multi-graph store with atomic hot-swap.

One serving process, many graphs, each one live-updatable: the
:class:`GraphStore` maps names to their current
:class:`~bibfs_tpu.store.snapshot.GraphSnapshot` (plus a pending
:class:`~bibfs_tpu.store.delta.DeltaOverlay` when edge updates have
arrived since the last compaction). The engines resolve a name to a
snapshot at flush time and pin it for the flush, so a swap is:

1. build the replacement snapshot (compaction — background thread, or
   any externally built snapshot handed to :meth:`swap`);
2. under the store lock, point the name at the new snapshot — the swap
   itself is a pointer flip plus metrics, so serving traffic never
   waits on a rebuild;
3. in-flight flushes finish on the OLD snapshot through their pins; the
   old snapshot retires when the last pin drops
   (refcount — ``snapshot.release``).

Updates below the compaction threshold serve exactly through the
overlay (``serve/engine`` routes those queries to
:meth:`DeltaOverlay.solve`); once ``delta_edges`` reaches
``compact_threshold`` the store kicks a background compaction that
rebuilds the ELL into a fresh snapshot off the hot path and swaps it
in. An overlay is never mutated once handed out: a compaction REBASES
the updates that raced its build into a fresh overlay over the new
snapshot, so a flush that grabbed the old overlay keeps answering the
exact old-base+full-delta graph — which is, by construction, the same
edge set the new snapshot + rebased overlay describes.

Observability: ``bibfs_store_graphs`` (gauge), ``bibfs_store_swaps_total``
/ ``bibfs_store_compactions_total`` / ``bibfs_store_compact_failures_total``
(counters, per graph), ``bibfs_store_delta_edges`` (gauge, per graph) in
the process registry, plus ``store_swap`` / ``store_compact`` trace
spans.
"""

from __future__ import annotations

import os
import threading

from bibfs_tpu.obs.metrics import REGISTRY, next_instance_label
from bibfs_tpu.obs.trace import span
from bibfs_tpu.store.delta import DeltaOverlay
from bibfs_tpu.store.snapshot import GraphSnapshot


class _Entry:
    """One named graph's mutable slot: current snapshot, pending
    overlay, and the compaction serializer (one compaction per graph at
    a time — a forced REPL ``swap`` racing a threshold-triggered
    background job must not double-build)."""

    __slots__ = ("snapshot", "overlay", "compactor", "compact_lock",
                 "swaps", "compactions", "compact_failures")

    def __init__(self, snapshot: GraphSnapshot):
        self.snapshot = snapshot
        self.overlay: DeltaOverlay | None = None
        self.compactor: threading.Thread | None = None
        self.compact_lock = threading.Lock()
        self.swaps = 0
        self.compactions = 0
        self.compact_failures = 0


class GraphStore:
    """Named, versioned, hot-swappable graphs (module docstring).

    Parameters
    ----------
    compact_threshold : pending delta edges at which a background
        compaction (rebuild + swap) is triggered. ``None`` disables
        auto-compaction (explicit :meth:`compact` / :meth:`swap` only).
    obs_label : the ``store=`` label value this store's registry cells
        carry (default: a process-unique ``store-N``).
    """

    def __init__(self, *, compact_threshold: int | None = 256,
                 obs_label: str | None = None):
        self.compact_threshold = (
            None if compact_threshold is None else int(compact_threshold)
        )
        if self.compact_threshold is not None and self.compact_threshold < 1:
            raise ValueError(
                f"compact_threshold must be >= 1, got {compact_threshold}"
            )
        self.obs_label = (
            next_instance_label("store") if obs_label is None else obs_label
        )
        self._lock = threading.RLock()
        self._entries: dict[str, _Entry] = {}
        self._default: str | None = None
        self._g_graphs = REGISTRY.gauge(
            "bibfs_store_graphs", "Graphs registered in a graph store",
            ("store",),
        ).labels(store=self.obs_label)
        self._c_swaps = REGISTRY.counter(
            "bibfs_store_swaps_total",
            "Atomic snapshot hot-swaps per graph",
            ("store", "graph"),
        )
        self._g_delta = REGISTRY.gauge(
            "bibfs_store_delta_edges",
            "Pending overlay edge updates per graph",
            ("store", "graph"),
        )
        self._c_compactions = REGISTRY.counter(
            "bibfs_store_compactions_total",
            "Delta compactions (overlay folded into a fresh snapshot)",
            ("store", "graph"),
        )
        self._c_compact_failures = REGISTRY.counter(
            "bibfs_store_compact_failures_total",
            "Background compactions that raised (overlay keeps serving; "
            "the next update re-triggers)",
            ("store", "graph"),
        )

    # ---- registration -----------------------------------------------
    def add(self, name: str, n: int | None = None, edges=None, *,
            pairs=None, snapshot: GraphSnapshot | None = None
            ) -> GraphSnapshot:
        """Register a graph under ``name`` (its version-1 snapshot).
        The first added graph becomes the default."""
        name = str(name)
        if snapshot is None:
            if n is None:
                raise ValueError("add() needs n+edges/pairs or snapshot=")
            snapshot = GraphSnapshot.build(n, edges, pairs=pairs)
        with self._lock:
            if name in self._entries:
                raise ValueError(
                    f"graph {name!r} already registered (swap() replaces)"
                )
            # versions are store-relative: every registered graph starts
            # at v1, compaction stamps old+1 — so `graphs` output and
            # stats read as each graph's OWN history, not the order the
            # process happened to build snapshots in. (The build-time
            # global stamp remains the fallback for snapshots that never
            # enter a store.)
            snapshot.version = 1
            self._entries[name] = _Entry(snapshot)
            if self._default is None:
                self._default = name
            self._g_graphs.set(len(self._entries))
            # mint the per-graph cells now so a scrape shows the graph
            # at zero before its first update/swap
            self._c_swaps.labels(store=self.obs_label, graph=name)
            self._g_delta.labels(store=self.obs_label, graph=name).set(0)
            self._c_compactions.labels(store=self.obs_label, graph=name)
            self._c_compact_failures.labels(store=self.obs_label, graph=name)
        return snapshot

    @classmethod
    def from_dir(cls, path, **kwargs) -> "GraphStore":
        """A store over every ``*.bin`` graph in a directory, each
        registered under its file stem (``social.bin`` -> ``social``),
        sorted so the default graph is deterministic."""
        from bibfs_tpu.graph.io import read_graph_bin

        store = cls(**kwargs)
        names = sorted(
            f for f in os.listdir(path) if f.endswith(".bin")
        )
        if not names:
            raise ValueError(f"no *.bin graphs in {path!r}")
        for fname in names:
            n, edges = read_graph_bin(os.path.join(path, fname))
            store.add(os.path.splitext(fname)[0], n, edges)
        return store

    # ---- resolution --------------------------------------------------
    def _entry(self, name: str) -> _Entry:
        entry = self._entries.get(str(name))
        if entry is None:
            raise KeyError(
                f"unknown graph {name!r} (have: {sorted(self._entries)})"
            )
        return entry

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def default_graph(self) -> str:
        with self._lock:
            if self._default is None:
                raise ValueError("store has no graphs")
            return self._default

    def current(self, name: str) -> GraphSnapshot:
        """The graph's current snapshot — an identity read (cheap
        same-version check). Pin with :meth:`acquire` before USING one
        across a swap window."""
        with self._lock:
            return self._entry(name).snapshot

    def acquire(self, name: str) -> GraphSnapshot:
        """The current snapshot, retained under the store lock — so a
        concurrent swap cannot retire it between the read and the pin.
        The caller owns one ``release()``."""
        with self._lock:
            return self._entry(name).snapshot.retain()

    def overlay(self, name: str) -> DeltaOverlay | None:
        """The graph's pending overlay, or None when it has no pending
        updates — the engines' exact-answering route check."""
        with self._lock:
            ov = self._entry(name).overlay
        if ov is not None and ov.delta_edges == 0:
            return None
        return ov

    # ---- live updates ------------------------------------------------
    def update(self, name: str, adds=(), dels=()) -> dict:
        """Apply one batch of undirected edge updates to ``name``'s
        overlay (creating it on first update). Crossing
        ``compact_threshold`` kicks a background compaction. Returns
        ``{"adds": ..., "dels": ..., "compacting": bool}``."""
        name = str(name)
        while True:
            with self._lock:
                entry = self._entry(name)
                if entry.overlay is None:
                    entry.overlay = DeltaOverlay(entry.snapshot)
                overlay = entry.overlay
            # the first apply against a base needs its O(E) membership
            # index — build it OFF the store lock (every serving thread
            # resolves names through that lock; a Python pass over
            # every edge under it is a serving stall)
            overlay.ensure_index()
            with self._lock:
                if self._entry(name).overlay is not overlay:
                    # a swap/compaction replaced the overlay while the
                    # index built: restart against the current state
                    continue
                counts = overlay.apply(adds, dels)
                delta = counts["adds"] + counts["dels"]
                self._g_delta.labels(
                    store=self.obs_label, graph=name
                ).set(delta)
                compacting = entry.compactor is not None
                if (not compacting and self.compact_threshold is not None
                        and delta >= self.compact_threshold):
                    entry.compactor = threading.Thread(
                        target=self._compact_job, args=(name, entry),
                        name=f"bibfs-compact-{name}", daemon=True,
                    )
                    entry.compactor.start()
                    compacting = True
            return {**counts, "compacting": compacting}

    # ---- compaction + hot-swap ---------------------------------------
    def _compact_job(self, name: str, entry: _Entry) -> None:
        try:
            self._compact_inline(name)
        except Exception:
            # the overlay keeps serving exactly and the next update
            # re-triggers — but a persistently failing compaction means
            # unbounded delta growth and every query on the host overlay
            # route, so it must be VISIBLE, not swallowed: count it
            # (scraped via /metrics and surfaced in stats()).
            with self._lock:
                entry.compact_failures += 1
            self._c_compact_failures.labels(
                store=self.obs_label, graph=name
            ).inc()
        finally:
            with self._lock:
                entry.compactor = None

    def _compact_inline(self, name: str) -> GraphSnapshot:
        """Build base+delta into a fresh snapshot OFF the store lock,
        swap it in, and REBASE updates that raced the build into a
        fresh overlay over the new snapshot. The old overlay object is
        never mutated: flushes that captured it keep answering the
        exact old-base+full-delta graph (the same edge set)."""
        with self._lock:
            entry = self._entry(name)
        with entry.compact_lock:
            with self._lock:
                overlay = entry.overlay
                if overlay is None or overlay.delta_edges == 0:
                    return entry.snapshot  # nothing pending: no-op
            with span("store_compact", graph=name,
                      delta=overlay.delta_edges):
                new, adds, dels = overlay.snapshot()  # the heavy build
                # pre-warm the carried overlay's base index off-lock
                # too: rebase residue applies under the store lock below
                rebased = DeltaOverlay(new)
                rebased.ensure_index()
                with self._lock:
                    if self._entry(name).overlay is not overlay:
                        # an external swap() landed during the build and
                        # discarded this overlay — its snapshot is the
                        # caller's declared truth; committing ours would
                        # silently overwrite it with stale
                        # old-base+delta content. Abort: the folded
                        # updates were discarded BY the swap, exactly as
                        # swap()'s contract states.
                        return entry.snapshot
                    # store-relative stamp (see add())
                    new.version = entry.snapshot.version + 1
                    self._swap_locked(name, entry, new)
                    # edge-wise live-vs-new diff, NOT set subtraction: a
                    # racing update may have CANCELLED a captured
                    # pending edge, which must become a real update
                    # against the new snapshot (DeltaOverlay.rebase)
                    a2, d2 = overlay.rebase(adds, dels)
                    if a2 or d2:
                        rebased.apply(sorted(a2), sorted(d2))
                        entry.overlay = rebased
                    else:
                        entry.overlay = None
                    self._g_delta.labels(
                        store=self.obs_label, graph=name
                    ).set(len(a2) + len(d2))
                    entry.compactions += 1
                    self._c_compactions.labels(
                        store=self.obs_label, graph=name
                    ).inc()
            return new

    def compact(self, name: str) -> GraphSnapshot:
        """Force a synchronous compaction+swap NOW (the REPL ``swap``
        command). Serialized against any in-flight background
        compaction; folds whatever is pending when its turn comes."""
        return self._compact_inline(str(name))

    def swap(self, name: str, snapshot: GraphSnapshot) -> GraphSnapshot:
        """Atomically point ``name`` at an externally built snapshot.
        Returns the OLD snapshot (already released by the store; it
        retires once in-flight flush pins drop). Any pending overlay is
        discarded — the new snapshot is the caller's declared truth."""
        name = str(name)
        with self._lock:
            entry = self._entry(name)
            old = self._swap_locked(name, entry, snapshot)
            entry.overlay = None
            self._g_delta.labels(store=self.obs_label, graph=name).set(0)
        return old

    def _swap_locked(self, name: str, entry: _Entry,
                     new: GraphSnapshot) -> GraphSnapshot:
        old = entry.snapshot
        if new.version <= old.version:
            raise ValueError(
                f"swap must move {name!r} forward: new version "
                f"{new.version} <= current {old.version}"
            )
        with span("store_swap", graph=name, version=new.version,
                  old_version=old.version):
            entry.snapshot = new
            entry.swaps += 1
            self._c_swaps.labels(store=self.obs_label, graph=name).inc()
            old.release()  # the store's reference; flush pins remain
        return old

    # ---- introspection ----------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            graphs = {}
            for name, entry in self._entries.items():
                graphs[name] = {
                    **entry.snapshot.stats(),
                    "delta_edges": (
                        0 if entry.overlay is None
                        else entry.overlay.delta_edges
                    ),
                    "swaps": entry.swaps,
                    "compactions": entry.compactions,
                    "compact_failures": entry.compact_failures,
                    "compacting": entry.compactor is not None,
                }
            return {
                "graphs": graphs,
                "default": self._default,
                "compact_threshold": self.compact_threshold,
            }

    def close(self) -> None:
        """Join in-flight background compactions (test/shutdown aid)."""
        with self._lock:
            jobs = [
                e.compactor for e in self._entries.values()
                if e.compactor is not None
            ]
        for job in jobs:
            job.join()
