"""Batched live edge updates on top of an immutable snapshot.

Production graphs change, but the serving stack's compiled programs and
padded device tables are built for ONE immutable shape — rebuilding them
per edge insert would turn every update into a multi-second stall. A
:class:`DeltaOverlay` splits the difference the way LSM stores do:

- **the base stays immutable** — the :class:`GraphSnapshot` (and every
  device table built from it) is untouched; updates accumulate as two
  small canonical edge sets (``adds``/``dels``);
- **queries stay exact** — while a delta is pending, queries against the
  graph run :meth:`solve`: a host-side level-synchronous BFS over the
  base CSR *corrected by the overlay* (added neighbors appended,
  deleted edges skipped). For the small deltas the overlay is meant to
  hold, that is a few extra set probes per scanned edge — far cheaper
  than a rebuild, and bit-exact against a from-scratch solve on the
  updated graph (the churn harness gates on it);
- **compaction is off the hot path** — once ``delta_edges`` crosses the
  store's threshold, :meth:`snapshot` materializes the merged edge list
  into a fresh :class:`GraphSnapshot` (new digest, next version) on a
  background thread, and the store hot-swaps it in. An overlay handed
  to a reader is never mutated afterwards — updates that raced the
  compaction are REBASED by the store into a fresh overlay over the
  new snapshot, so nothing is lost and mid-flight solves stay exact.

Updates are edge-only by design: the vertex set (and therefore ``n``,
the padded table shapes, and the compiled-program bucket) is fixed at
snapshot creation, which is what makes a same-bucket hot-swap cost zero
recompiles.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from bibfs_tpu.store.snapshot import GraphSnapshot


def canonical_edge(n: int, u, v) -> tuple[int, int]:
    """Validate one undirected edge against the vertex range and return
    it in canonical ``(min, max)`` orientation."""
    u, v = int(u), int(v)
    if not (0 <= u < n and 0 <= v < n):
        raise ValueError(f"edge endpoint out of range for n={n}: ({u}, {v})")
    if u == v:
        raise ValueError(f"self-loop ({u}, {u}) is not a valid edge")
    return (u, v) if u < v else (v, u)


class DeltaOverlay:
    """Pending edge inserts/deletes over one base snapshot (module
    docstring). Thread-safe: the store mutates it under update/swap
    calls while engine flushes read it for exact query answering."""

    def __init__(self, base: GraphSnapshot):
        self.base = base
        self._lock = threading.Lock()
        self._adds: set[tuple[int, int]] = set()
        self._dels: set[tuple[int, int]] = set()
        self._base_edges: set | None = None  # lazy membership index
        self._base_csr = None  # own handle: survives base retirement

    # ---- mutation ----------------------------------------------------
    def _base_has(self, e: tuple[int, int]) -> bool:
        if self._base_edges is None:
            self._base_edges = set(
                map(tuple, self.base.undirected_edges().tolist())
            )
        return e in self._base_edges

    def ensure_index(self) -> None:
        """Pre-build the O(E) base-edge membership index. The store
        calls this OUTSIDE its global lock before the first
        ``apply``/``rebase`` needs it — a Python pass over every base
        edge under the store lock would stall every serving thread
        resolving names through the store."""
        with self._lock:
            self._base_has((0, 0))

    def apply(self, adds=(), dels=(), *, commit: bool = True) -> dict:
        """Apply one batch of undirected edge updates. An add of an
        edge the (overlaid) graph already has, or a delete of one it
        does not, is rejected — silent no-ops would let a typo'd update
        pass unnoticed. An add cancels a pending delete of the same
        edge (and vice versa). The batch is atomic: staged on copies
        and committed only once every edge validates, so a rejected
        batch leaves the overlay exactly as it was (no half-applied
        updates leaking into the next compaction). Returns the
        overlay's post-batch counts.

        ``commit=False`` runs the full staging validation and returns
        the would-be counts WITHOUT committing — the durable store's
        WAL ordering needs "validate, log, then commit" (a rejected
        batch must never reach the log, a logged batch must never fail
        the in-memory commit), and the dry run is what makes the second
        ``apply`` of that sequence infallible under the same lock."""
        n = self.base.n
        with self._lock:
            stage_a, stage_d = set(self._adds), set(self._dels)
            for u, v in adds:
                e = canonical_edge(n, u, v)
                if e in stage_d:
                    stage_d.discard(e)
                elif self._base_has(e) or e in stage_a:
                    raise ValueError(f"edge {e} already present")
                else:
                    stage_a.add(e)
            for u, v in dels:
                e = canonical_edge(n, u, v)
                if e in stage_a:
                    stage_a.discard(e)
                elif not self._base_has(e) or e in stage_d:
                    raise ValueError(f"edge {e} not present")
                else:
                    stage_d.add(e)
            if commit:
                self._adds, self._dels = stage_a, stage_d
            return {"adds": len(stage_a), "dels": len(stage_d)}

    def capture(self) -> tuple[set, set]:
        """A consistent copy of the pending sets (what a compaction
        will fold in)."""
        with self._lock:
            return set(self._adds), set(self._dels)

    def rebase(self, adds: set, dels: set) -> tuple[set, set]:
        """The overlay to carry onto the snapshot built from the
        captured ``(adds, dels)``: ``(a2, d2)`` such that
        ``new + a2 - d2`` equals the overlay's LIVE graph right now.

        Not plain set subtraction: an update that lands during the
        build can CANCEL a captured pending edge (a delete of a
        captured pending add empties ``_adds`` without recording a
        delete), so the carried sets must be computed as the edge-wise
        difference between the live graph ``L = base + a_live - d_live``
        and the new snapshot ``N = base + adds - dels`` — only edges in
        one of the four sets can differ."""
        with self._lock:
            a_live, d_live = set(self._adds), set(self._dels)
            a2, d2 = set(), set()
            for e in a_live | d_live | adds | dels:
                in_live = (e in a_live
                           or (self._base_has(e) and e not in d_live))
                in_new = (e in adds
                          or (self._base_has(e) and e not in dels))
                if in_live and not in_new:
                    a2.add(e)
                elif in_new and not in_live:
                    d2.add(e)
            return a2, d2

    @property
    def delta_edges(self) -> int:
        with self._lock:
            return len(self._adds) + len(self._dels)

    # ---- exact query answering ---------------------------------------
    def correction(self) -> tuple[set, dict]:
        """A consistent ``(dels, add_adj)`` correction for
        :meth:`solve` — capture it ONCE per flush batch and pass it to
        every solve in the batch: the copy + adjacency build is
        O(delta) under the overlay lock, pure waste repeated per query
        (and the shared capture makes the whole batch answer one
        consistent delta state)."""
        with self._lock:
            dels = set(self._dels)
            add_adj: dict[int, list[int]] = {}
            for u, v in self._adds:
                add_adj.setdefault(u, []).append(v)
                add_adj.setdefault(v, []).append(u)
        return dels, add_adj

    def solve(self, src: int, dst: int, correction=None):
        """Exact shortest path on base+delta: level-synchronous BFS over
        the base CSR with overlay correction (module docstring). Returns
        a :class:`~bibfs_tpu.solvers.api.BFSResult`; never touches the
        device stack. ``correction`` is an optional pre-captured
        :meth:`correction` (per-batch amortization)."""
        from bibfs_tpu.solvers.api import BFSResult

        src, dst = int(src), int(dst)
        n = self.base.n
        if not (0 <= src < n and 0 <= dst < n):
            raise ValueError(f"src/dst out of range for n={n}")
        t0 = time.perf_counter()
        if src == dst:
            return BFSResult(True, 0, [src], src, 0.0, 0, 0)
        if self._base_csr is None:
            # hold our own handle: a swap can retire the base while a
            # captured overlay still answers a batch on it, and a
            # retired snapshot's csr() builds UNCACHED — without this,
            # every solve in that batch would rebuild the full CSR
            self._base_csr = self.base.csr()
        row_ptr, col_ind = self._base_csr
        dels, add_adj = (
            self.correction() if correction is None else correction
        )
        parent = np.full(n, -1, dtype=np.int64)
        parent[src] = src
        frontier = [src]
        levels = 0
        edges_scanned = 0
        found = False
        while frontier and not found:
            levels += 1
            nxt = []
            for u in frontier:
                base_nbrs = col_ind[row_ptr[u]: row_ptr[u + 1]]
                extra = add_adj.get(u)
                for v in (
                    base_nbrs if extra is None
                    else list(base_nbrs) + extra
                ):
                    v = int(v)
                    edges_scanned += 1
                    if dels and (
                        (u, v) if u < v else (v, u)
                    ) in dels:
                        continue
                    if parent[v] >= 0:
                        continue
                    parent[v] = u
                    if v == dst:
                        found = True
                        break
                    nxt.append(v)
                if found:
                    break
            frontier = nxt
        if not found:
            return BFSResult(
                False, None, None, None,
                time.perf_counter() - t0, levels, edges_scanned,
            )
        path = [dst]
        while path[-1] != src:
            path.append(int(parent[path[-1]]))
        path.reverse()
        return BFSResult(
            True, len(path) - 1, path, None,
            time.perf_counter() - t0, levels, edges_scanned,
        )

    # ---- compaction --------------------------------------------------
    def merged_edges(self, adds: set | None = None,
                     dels: set | None = None) -> np.ndarray:
        """The undirected base+delta edge list (``u < v`` rows) for the
        given captured sets (default: the live pending sets)."""
        if adds is None or dels is None:
            adds, dels = self.capture()
        base = self.base.undirected_edges()
        if dels:
            # vectorized membership: encode (u, v) as u*n+v scalar keys
            # — a Python loop over every base edge per compaction would
            # dominate the rebuild at production edge counts
            n = np.int64(self.base.n)
            keys = base[:, 0] * n + base[:, 1]
            darr = np.array(sorted(dels), dtype=np.int64)
            base = base[~np.isin(keys, darr[:, 0] * n + darr[:, 1])]
        if adds:
            base = np.concatenate(
                [base, np.array(sorted(adds), dtype=np.int64)], axis=0
            )
        return base

    def snapshot(self, adds: set | None = None,
                 dels: set | None = None) -> tuple[GraphSnapshot, set, set]:
        """Materialize base+delta into a fresh snapshot (the compaction
        build — run it OFF the serving path). Returns ``(snapshot,
        adds, dels)`` where the sets are exactly what was folded in, for
        the rebase after the store swaps. The durable store passes sets
        it captured under its own lock (the WAL segment fence); with
        none given, a fresh :meth:`capture` is taken here."""
        if adds is None or dels is None:
            adds, dels = self.capture()
        snap = GraphSnapshot.build(
            self.base.n, self.merged_edges(adds, dels)
        )
        return snap, adds, dels

    def stats(self) -> dict:
        with self._lock:
            return {"adds": len(self._adds), "dels": len(self._dels)}
