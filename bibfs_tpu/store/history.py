"""Version history: the durability layer as queryable time-travel truth.

PR 8 made every acked update durable (WAL) and every compaction a
crash-consistent checkpoint (versioned ``.bin`` + manifest) — but the
manifest only ever names the CURRENT version; superseded checkpoints
exist solely as recovery insurance until GC deletes them. This module
turns that machinery into a readable HISTORY, which is what the
``as_of`` query kind (:class:`bibfs_tpu.query.AsOf`) stands on:

- ``<name>.history.json`` — one entry per committed version
  ``{version, digest, bin, wal_seq, n, edges}``, appended at every
  manifest commit (registration, compaction checkpoint, external
  swap) of a ``retain_history=True`` store, by atomic
  tmp+``os.replace`` like the manifest itself. (A non-retaining store
  writes no history: GC deletes the artifacts an entry would point at
  by the very next commit, so the entries could never reconstruct —
  and the per-commit rewrite+fsync under the store lock would be pure
  cost.) The digest is the exactness anchor: whatever path a
  reconstruction takes, its content hash must equal the one recorded
  at commit time or the read is refused.
- :func:`reconstruct_version` — the edge set as of version ``v``,
  by the cheapest provable route: the retained checkpoint ``.bin``
  when it survives (one file read + digest check), else seed + WAL
  replay of every segment BELOW the version's first segment
  (``wal_seq``): the checkpoint capture and the segment switch share
  one locked section in the store (``store/wal.py``), so "segments
  < wal_seq(v)" is EXACTLY the record set folded into v — the replay
  lands on the same digest or raises.

GC normally deletes superseded bins and segments once a newer
manifest commits; ``GraphStore(retain_history=True)`` keeps them, so
every committed version stays reconstructible for the store's
lifetime — the mode the time-travel soak runs in. Without retention,
reconstruction still works for any version whose artifacts survive
(and always for v1, whose seed ``.bin`` is never deleted with an
intact WAL chain) and fails LOUDLY otherwise, never approximately.
"""

from __future__ import annotations

import json
import os

import numpy as np

from bibfs_tpu.store.wal import fsync_dir, list_segments, read_wal


def history_path(wal_dir, name: str) -> str:
    return os.path.join(os.fspath(wal_dir), f"{name}.history.json")


def load_history(wal_dir, name: str) -> list[dict]:
    """The graph's committed version entries, ascending by version
    (missing/corrupt file reads as empty — reconstruction then fails
    per-version with a clear error, never a crash here)."""
    try:
        with open(history_path(wal_dir, name)) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return []
    entries = data.get("entries")
    if not isinstance(entries, list):
        return []
    clean = []
    for e in entries:
        try:
            clean.append({
                "version": int(e["version"]),
                "digest": str(e["digest"]),
                "bin": str(e["bin"]),
                "wal_seq": int(e["wal_seq"]),
                "n": int(e["n"]),
                "edges": int(e["edges"]),
            })
        except (TypeError, KeyError, ValueError):
            continue
    clean.sort(key=lambda e: e["version"])
    return clean


def append_history(wal_dir, name: str, entry: dict) -> None:
    """Record one committed version (idempotent per version number —
    a re-commit of the same version replaces its entry). Atomic
    tmp+``os.replace`` + directory fsync, the manifest's own commit
    discipline: the file sits in the durable directory and must never
    be half-written."""
    entries = [
        e for e in load_history(wal_dir, name)
        if e["version"] != int(entry["version"])
    ]
    entries.append({
        "version": int(entry["version"]),
        "digest": str(entry["digest"]),
        "bin": str(entry["bin"]),
        "wal_seq": int(entry["wal_seq"]),
        "n": int(entry["n"]),
        "edges": int(entry["edges"]),
    })
    entries.sort(key=lambda e: e["version"])
    path = history_path(wal_dir, name)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump({"version": 1, "entries": entries}, f,
                      indent=1, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(wal_dir)


def replay_edge_set(n: int, seed_edges: np.ndarray, wal_dir, name: str,
                    below_seq: int) -> np.ndarray:
    """The undirected edge set after replaying every WAL segment with
    ``seq < below_seq`` over the seed, in sequence order — the record
    set the checkpoint that opened segment ``below_seq`` folded in
    (module docstring). Raises on a torn segment: a history read must
    be provable, never approximate."""
    from bibfs_tpu.store.delta import canonical_edge

    edges = {
        canonical_edge(n, int(u), int(v)) for u, v in seed_edges
    }
    for seq, path in list_segments(wal_dir, name):
        if seq >= below_seq:
            continue
        records, _good, torn = read_wal(path)
        if torn:
            raise ValueError(
                f"{os.path.basename(path)}: torn WAL segment in the "
                f"history replay for {name!r} — refusing an unprovable "
                "reconstruction"
            )
        for _ver, adds, dels in records:
            for u, v in adds:
                edges.add(canonical_edge(n, int(u), int(v)))
            for u, v in dels:
                edges.discard(canonical_edge(n, int(u), int(v)))
    if not edges:
        return np.zeros((0, 2), dtype=np.int64)
    return np.array(sorted(edges), dtype=np.int64)


def reconstruct_version(wal_dir, name: str, version: int):
    """The graph as of committed ``version``: a fresh
    :class:`~bibfs_tpu.store.snapshot.GraphSnapshot`, digest-verified
    against the history entry recorded when that version committed.
    Raises ``ValueError`` when the version is unknown or its artifacts
    (checkpoint bin AND the WAL chain) no longer prove it."""
    from bibfs_tpu.graph.io import read_graph_bin
    from bibfs_tpu.store.snapshot import GraphSnapshot

    version = int(version)
    entries = {e["version"]: e for e in load_history(wal_dir, name)}
    entry = entries.get(version)
    if entry is None:
        known = sorted(entries)
        raise ValueError(
            f"no history entry for {name!r} version {version} "
            f"(recorded: {known or 'none'})"
        )
    bin_path = os.path.join(os.fspath(wal_dir), entry["bin"])
    snap = None
    if os.path.exists(bin_path):
        n, edges = read_graph_bin(bin_path)
        snap = GraphSnapshot.build(n, edges, version=version)
        if snap.digest != entry["digest"]:
            # a reused filename with different content (should be
            # impossible for digest-suffixed checkpoint bins, possible
            # for a hand-replaced seed): fall through to WAL replay,
            # which carries its own proof
            snap = None
    if snap is None:
        seed_path = os.path.join(os.fspath(wal_dir), f"{name}.bin")
        if not os.path.exists(seed_path):
            raise ValueError(
                f"{name!r} version {version}: checkpoint bin "
                f"{entry['bin']} is gone and no seed remains — "
                "unreconstructible (run the store with "
                "retain_history=True to keep history readable)"
            )
        n, seed_edges = read_graph_bin(seed_path)
        edges = replay_edge_set(
            n, seed_edges, wal_dir, name, entry["wal_seq"]
        )
        snap = GraphSnapshot.build(n, edges, version=version)
        if snap.digest != entry["digest"]:
            raise ValueError(
                f"{name!r} version {version}: WAL replay digest "
                f"{snap.digest} != recorded {entry['digest']} — part "
                "of the segment chain is missing (run the store with "
                "retain_history=True to keep history readable)"
            )
    return snap
