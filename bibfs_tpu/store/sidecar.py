"""Arrays sidecar — the zero-copy on-disk twin of a checkpoint ``.bin``.

A checkpoint bin (``<name>.v<V>.<digest12>.bin``) is the *portable*
truth: uint32 edge pairs any reference reader can load — but loading it
means re-canonicalizing O(E log E) and materializing every derived
table privately per process. The **arrays sidecar**
(``<name>.v<V>.<digest12>.arrays/``) is the *servable* truth: the
snapshot's derived arrays laid out as raw little-endian files a
process can ``np.memmap`` read-only —

- ``pairs``        int64 ``[D, 2]``   canonical directed pairs (the
  digest's hash input; ``pairs[:, 1]`` doubles as the CSR ``col_ind``
  because canonical order IS CSR expansion order);
- ``csr.indptr``   int64 ``[n+1]``    CSR row pointers;
- ``csr32.indices`` int32 ``[D]``     contiguous int32 neighbor ids —
  exactly the native C solver's column format, so every replica's host
  route shares ONE page-cache copy instead of each building a private
  CSR from the edge list;
- optional groups, written only when already materialized on the
  snapshot at checkpoint time (a checkpoint never forces a build):
  ``ell.*`` (serving ELL table), ``blocked.*`` (MXU tile tables),
  ``oracle.*`` (landmark K×n distance matrix + landmark ids).

``manifest.json`` inside the directory binds it all: graph identity
(content digest, version, n, edges), per-file dtype/shape/BLAKE2b, and
the scalar metadata needed to reconstruct the dataclasses
(``EllGraph`` width/padding, ``BlockedGraph`` tiling, oracle gen).

**Commit protocol — rename-last.** All files (manifest included) land
in a same-directory ``<final>.tmp.<pid>`` directory, each flushed and
fsynced, the tmp directory fsynced, and only then is the tmp
``os.rename``d onto the final name and the parent fsynced. A crash
anywhere before the rename leaves a ``*.tmp.*`` orphan that loaders
never match and the next write cleans up; after it, a complete
sidecar. Nothing is ever written into a visible ``.arrays`` directory
— the ``atomic-write`` lint rule (analysis/rules/atomic_write.py)
enforces rename-last on this module. The digest-suffixed name gives
the same no-overwrite guarantee as checkpoint bins: two racing writers
can only collide on byte-identical content, so an already-present
final directory is simply kept.

Loading (``load_sidecar``) maps every file read-only, validates sizes
against the manifest always, and (by default) re-hashes file contents
against the manifest BLAKE2bs — a sequential page-cache read, far
cheaper than a rebuild, and the pages it faults in are the very pages
serving will use. A sidecar that fails any check raises; the store's
recovery falls back to the ``.bin`` rebuild path, never serves a
half-proven mapping.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil

import numpy as np

from bibfs_tpu.store.wal import fsync_dir

SIDECAR_FORMAT = 1

#: sidecar directories (``<name>.v<V>.<digest12>.arrays``) — same
#: shape contract as ``_CKPT_BIN_RE`` in store/registry.py, and like it
#: the digest suffix is REQUIRED for gc eligibility.
ARRAYS_DIR_RE = re.compile(r"\.v(\d+)\.[0-9a-f]{6,32}\.arrays$")

#: hash chunk: big enough to stream at disk bandwidth, small enough to
#: keep the hasher's working set out of the way
_HASH_CHUNK = 1 << 24


def sidecar_dir_name(name: str, snapshot) -> str:
    """``roads.v3.1f2a9c0d4e5b.arrays`` — version + digest prefix, the
    checkpoint-bin naming contract applied to the directory."""
    return f"{name}.v{snapshot.version}.{snapshot.digest[:12]}.arrays"


def _hash_bytes(buf) -> str:
    h = hashlib.blake2b(digest_size=16)
    if getattr(buf, "size", len(buf)) > 0:
        # empty arrays can't cast (zero in shape); their hash is of b""
        mv = memoryview(buf).cast("B")
        for off in range(0, len(mv), _HASH_CHUNK):
            h.update(mv[off:off + _HASH_CHUNK])
    return h.hexdigest()


def _write_array(dirpath: str, fname: str, arr: np.ndarray) -> dict:
    """One raw array file inside the (still-tmp) sidecar directory:
    little-endian C-order bytes, flushed and fsynced. Returns its
    manifest entry."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype.byteorder == ">":  # raw files are little-endian
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    path = os.path.join(dirpath, fname)
    with open(path, "wb") as f:
        arr.tofile(f)
        f.flush()
        os.fsync(f.fileno())
    return {
        "file": fname,
        "dtype": arr.dtype.str,
        "shape": list(arr.shape),
        "blake2b": _hash_bytes(arr),
    }


def _csr_indptr(n: int, pairs: np.ndarray) -> np.ndarray:
    """Row pointers straight from the canonical pairs — deliberately
    NOT ``snapshot.csr()``: the writer must not memoize an O(E) int64
    ``col_ind`` copy into the parent process just to checkpoint it."""
    deg = np.bincount(pairs[:, 0], minlength=n)
    row_ptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    return row_ptr


def write_sidecar(root, name: str, snapshot, *, oracle_index=None,
                  fire=None) -> str:
    """Write (or keep) the snapshot's arrays sidecar under ``root``.
    Returns the committed directory name (relative to ``root``).
    Idempotent: an already-committed sidecar for this (version, digest)
    is kept as-is — the digest-suffixed name makes it byte-equivalent.

    ``oracle_index`` (a ``LandmarkIndex``) adds the ``oracle.*`` group;
    ``fire`` is the store's fault-injection hook (site
    ``sidecar_rename`` guards the commit point).
    """
    root = os.fspath(root)
    dirname = sidecar_dir_name(name, snapshot)
    final = os.path.join(root, dirname)
    if os.path.isdir(final):
        return dirname
    tmp = f"{final}.tmp.{os.getpid()}"
    try:
        if os.path.isdir(tmp):  # a dead writer's orphan
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        pairs = np.ascontiguousarray(snapshot.pairs, dtype=np.int64)
        arrays = {
            "pairs": _write_array(tmp, "pairs.bin", pairs),
            "csr.indptr": _write_array(
                tmp, "csr_indptr.bin", _csr_indptr(snapshot.n, pairs)
            ),
            # transient int32 copy, dropped as soon as it is on disk
            "csr32.indices": _write_array(
                tmp, "csr32_indices.bin",
                pairs[:, 1].astype(np.int32),
            ),
        }
        meta: dict = {}
        # optional groups: ONLY what the snapshot already materialized
        # (peek the private memos — a checkpoint must never force an
        # O(E) layout build onto the commit path)
        ell = snapshot._ell
        if ell is not None:
            arrays["ell.nbr"] = _write_array(tmp, "ell_nbr.bin", ell.nbr)
            arrays["ell.deg"] = _write_array(tmp, "ell_deg.bin", ell.deg)
            arrays["ell.overflow"] = _write_array(
                tmp, "ell_overflow.bin", ell.overflow
            )
            meta["ell"] = {
                "n": ell.n, "n_pad": ell.n_pad, "width": ell.width,
                "num_edges": ell.num_edges,
            }
        blocked = snapshot._blocked
        if blocked is not None:
            arrays["blocked.tab"] = _write_array(
                tmp, "blocked_tab.bin", blocked.tab
            )
            arrays["blocked.bcol"] = _write_array(
                tmp, "blocked_bcol.bin", blocked.bcol
            )
            arrays["blocked.deg"] = _write_array(
                tmp, "blocked_deg.bin", blocked.deg
            )
            meta["blocked"] = {
                "n": blocked.n, "n_pad": blocked.n_pad,
                "tile": blocked.tile, "nblocks": blocked.nblocks,
                "bwidth": blocked.bwidth,
                "num_edges": blocked.num_edges,
                "nnz_blocks": blocked.nnz_blocks,
            }
        if oracle_index is not None:
            arrays["oracle.dist"] = _write_array(
                tmp, "oracle_dist.bin", oracle_index.dist
            )
            arrays["oracle.landmarks"] = _write_array(
                tmp, "oracle_landmarks.bin", oracle_index.landmarks
            )
            meta["oracle"] = {
                "gen": oracle_index.gen,
                "built_at": oracle_index.built_at,
                "repaired_edges": oracle_index.repaired_edges,
            }
        manifest = {
            "format": SIDECAR_FORMAT,
            "graph": name,
            "digest": snapshot.digest,
            "version": snapshot.version,
            "n": snapshot.n,
            "edges": snapshot.num_edges,
            "arrays": arrays,
            "meta": meta,
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        fsync_dir(tmp)
        if fire is not None:
            fire("sidecar_rename")
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    fsync_dir(root)
    return dirname


class SidecarMap:
    """A loaded sidecar: the manifest plus read-only ``np.memmap``
    views of every array file. Holding a reference keeps the mappings
    alive; dropping the last reference lets the GC unmap (there is no
    explicit close — in-flight readers of a view must never see their
    buffer yanked, the snapshot-retire contract)."""

    def __init__(self, path: str, manifest: dict,
                 arrays: dict[str, np.ndarray]):
        self.path = path
        self.manifest = manifest
        self.arrays = arrays

    @property
    def digest(self) -> str:
        return str(self.manifest["digest"])

    @property
    def version(self) -> int:
        return int(self.manifest["version"])

    @property
    def n(self) -> int:
        return int(self.manifest["n"])

    def meta(self, group: str) -> dict:
        return self.manifest.get("meta", {}).get(group, {})

    def has(self, *keys: str) -> bool:
        return all(k in self.arrays for k in keys)

    @property
    def mapped_bytes(self) -> int:
        return sum(int(a.nbytes) for a in self.arrays.values())

    def stats(self) -> dict:
        return {
            "path": self.path,
            "digest": self.digest,
            "version": self.version,
            "mapped_bytes": self.mapped_bytes,
            "arrays": sorted(self.arrays),
        }


def load_sidecar(path, *, verify: str = "full") -> SidecarMap:
    """Map a committed sidecar directory read-only.

    ``verify="full"`` (default) re-hashes every file against its
    manifest BLAKE2b — one sequential pass that also pre-faults the
    pages serving will read. ``verify="size"`` checks only byte sizes
    (shape x itemsize vs the file) — the property a torn write cannot
    fake past the rename-last commit, for callers that will content-
    verify another way (recovery re-derives the graph digest from the
    mapped pairs). Any mismatch raises ``ValueError``.
    """
    if verify not in ("full", "size"):
        raise ValueError(f"unknown verify mode {verify!r}")
    path = os.fspath(path)
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    fmt = int(manifest.get("format", 0))
    if fmt != SIDECAR_FORMAT:
        raise ValueError(
            f"{path}: sidecar format {fmt} != supported {SIDECAR_FORMAT}"
        )
    arrays: dict[str, np.ndarray] = {}
    for key, spec in manifest["arrays"].items():
        fpath = os.path.join(path, str(spec["file"]))
        dtype = np.dtype(str(spec["dtype"]))
        shape = tuple(int(s) for s in spec["shape"])
        expected = dtype.itemsize * int(np.prod(shape)) if shape else \
            dtype.itemsize
        actual = os.path.getsize(fpath)
        if actual != expected:
            raise ValueError(
                f"{fpath}: {actual} bytes on disk, manifest claims "
                f"{expected} ({dtype.str}{list(shape)})"
            )
        if expected == 0:
            arr = np.zeros(shape, dtype=dtype)
        else:
            arr = np.memmap(fpath, dtype=dtype, mode="r", shape=shape)
        if verify == "full" and expected:
            got = _hash_bytes(arr)
            if got != spec["blake2b"]:
                raise ValueError(
                    f"{fpath}: content hash {got} != manifest "
                    f"{spec['blake2b']} — refusing to map a torn or "
                    "foreign array"
                )
        arrays[key] = arr
    return SidecarMap(path, manifest, arrays)


def remove_sidecar_quiet(path) -> None:
    """Best-effort removal (gc of superseded sidecars + their orphaned
    ``*.tmp.*`` siblings)."""
    try:
        shutil.rmtree(path)
    except OSError:
        pass
