"""Immutable, versioned graph snapshots — the store's unit of truth.

Until now every serving layer identified "the graph" by the Python
object that happened to hold it: the engine's distance-cache namespace
defaulted to ``id(self)`` (which CPython reuses after GC), and each
engine rebuilt its own CSR/ELL tables from a raw edge list it could not
prove anyone else shared. A :class:`GraphSnapshot` replaces that with
content-addressed identity:

- **digest** — a BLAKE2b hash over ``(n, canonical pairs)``. Two
  snapshots with the same digest ARE the same graph, whatever path the
  bytes took to arrive; a cache entry keyed by digest can never alias a
  different graph, across engines, versions, or process lifetimes of
  the id() counter.
- **version** — a monotonic stamp. Digests answer "is this the same
  content"; versions answer "which came first" — the store's hot-swap
  invariant (a swap only ever moves a name FORWARD) is checked against
  it. Builds stamp a process-wide counter; a :class:`GraphStore`
  re-stamps store-relative history on registration/compaction (v1, v2,
  ...) so each graph's version reads as its own lineage.
- **memoized builds** — ``pairs``/``csr()``/``ell()``/``tiered()`` each
  build once under a lock and are shared by every consumer of the
  snapshot (engine runtimes, overlay solves, oracle checks), so a
  hot-swap costs one canonicalization pass total, not one per layer.
- **refcount retirement** — the store holds one reference; every
  in-flight engine flush pins one more (``retain``/``release``). A
  swapped-out snapshot is retired (retire hooks fire, memoized tables
  become collectable) only when the last in-flight flush lands — the
  swap barrier that lets old batches finish on the graph they started
  on.

**Memory tiers.** A snapshot lives in one of three tiers:

- ``mapped`` — built by :meth:`GraphSnapshot.from_sidecar` over an
  arrays sidecar (``store/sidecar.py``): ``pairs``, the CSR and the
  native int32 column table are read-only ``np.memmap`` views, so M
  processes serving the same graph share ONE page-cache-resident copy
  and recovery maps instead of rebuilding. Retirement keeps the
  no-unmapped-reads contract the in-memory tiers have: ``release()``
  only NULLS references (the ``SidecarMap`` holds the mappings), so an
  in-flight flush that pinned a view keeps a valid buffer until the
  GC drops the last holder — nothing ever calls ``munmap`` under a
  live reader.
- ``hot`` — the original behavior: private in-memory arrays.
- ``cold`` — past the store's residency budget: the adjacency is held
  ONLY as a varint+delta :class:`~bibfs_tpu.graph.compress.CompressedCSR`
  (``demote()``); ``pairs``/``csr()`` transparently decode back on the
  next access (``promote`` — exact, the codec round-trips bit-for-bit,
  and canonical pair order IS CSR expansion order so pairs are
  reconstructed rather than stored twice). The store's residency
  accountant (``store/registry.py``) drives demotions; any access
  promotes.

The serving-layout build (``ell()``) imports ``serve.buckets`` lazily:
the store layer sits beside ``serve``, not above it, and must be
importable without dragging the engine stack in.
"""

from __future__ import annotations

import hashlib
import itertools
import threading

import numpy as np

# process-wide monotonic version stamps; also the fallback identity
# counter for snapshots built without hashable content (never reused,
# unlike id())
_VERSIONS = itertools.count(1)
_ANON = itertools.count()


def next_version() -> int:
    """The next process-wide monotonic snapshot version."""
    return next(_VERSIONS)


#: digest hash chunk — bounds the hasher's transient working set; the
#: chunked loop (not ``tobytes()``) is what lets the digest of an
#: mmap-backed pairs array stream through the page cache instead of
#: materializing a private O(E) byte copy
_DIGEST_CHUNK = 1 << 24


def content_digest(n: int, pairs: np.ndarray) -> str:
    """BLAKE2b over ``(n, canonical pairs)`` — the content identity.

    ``pairs`` must already be canonical (mirrored, deduped, sorted —
    :func:`bibfs_tpu.graph.csr.canonical_pairs`), which makes the hash
    insensitive to edge order, duplication, and orientation in whatever
    list the graph arrived as."""
    h = hashlib.blake2b(digest_size=16)
    h.update(str(int(n)).encode())
    h.update(b"|")
    arr = np.ascontiguousarray(pairs, dtype=np.int64)
    mv = memoryview(arr).cast("B") if arr.size else memoryview(b"")
    for off in range(0, len(mv), _DIGEST_CHUNK):
        h.update(mv[off:off + _DIGEST_CHUNK])
    return h.hexdigest()


class GraphSnapshot:
    """One immutable version of one graph (module docstring).

    Build with :meth:`build` (computes the canonical pairs and digest).
    ``digest=None`` at direct construction falls back to a process-wide
    monotonic ``anon-N`` label — still never reused, unlike ``id()``.
    """

    def __init__(self, n: int, pairs: np.ndarray, *, digest: str | None = None,
                 version: int | None = None):
        self.n = int(n)
        self._pairs = pairs
        self.digest = (
            f"anon-{next(_ANON)}" if digest is None else str(digest)
        )
        self.version = next_version() if version is None else int(version)
        self.num_edges = int(pairs.shape[0]) // 2
        # RLock: a memoized builder holding the lock reads self.pairs,
        # and on a cold snapshot that property re-enters to promote
        self._lock = threading.RLock()
        self._refs = 1  # the creator's (usually the store's) reference
        self._retired = False
        self._retire_hooks: list = []
        self._csr = None
        self._ell = None  # serving-bucketed ELL
        self._tiered = None
        self._blocked = None  # MXU tile layout (graph/blocked.py)
        # memory-tier state (module docstring)
        self._sidecar = None  # SidecarMap pinning the mmap views
        self._native32 = None  # (row_ptr i64, col_ind i32) native format
        self._cold = None  # CompressedCSR once demoted
        self._promotions = 0
        self._demotions = 0

    @property
    def pairs(self) -> np.ndarray:
        """The canonical directed pairs. On a cold snapshot the access
        IS the promotion: decode back to hot (exact) before returning —
        post-retire the decode still answers but is not re-cached,
        matching the memoized builders."""
        p = self._pairs
        if p is not None:
            return p
        with self._lock:
            if self._pairs is not None:
                return self._pairs
            if self._cold is None:
                raise RuntimeError(
                    f"snapshot {self.digest} has neither pairs nor a "
                    "cold-tier encoding"
                )
            pairs, csr = self._decode_cold()
            if not self._retired:
                self._pairs = pairs
                if self._csr is None:
                    self._csr = csr
                self._promotions += 1
            return pairs

    @pairs.setter
    def pairs(self, value: np.ndarray) -> None:
        self._pairs = value

    def _decode_cold(self):
        """Cold-tier decode: the exact CSR, and the canonical pairs
        rebuilt from it (canonical order is CSR expansion order — the
        inverse of ``build_csr``)."""
        from bibfs_tpu.graph.compress import decode_csr

        row_ptr, col = decode_csr(self._cold)
        pairs = np.empty((col.shape[0], 2), dtype=np.int64)
        pairs[:, 0] = np.repeat(
            np.arange(self.n, dtype=np.int64), np.diff(row_ptr)
        )
        pairs[:, 1] = col
        return pairs, (row_ptr, col)

    @classmethod
    def build(cls, n: int, edges: np.ndarray | None = None, *,
              pairs: np.ndarray | None = None,
              version: int | None = None) -> "GraphSnapshot":
        """Canonicalize ``edges`` (or adopt precomputed ``pairs``) and
        stamp the content digest + a fresh monotonic version."""
        from bibfs_tpu.graph.csr import canonical_pairs

        if pairs is None:
            pairs = canonical_pairs(n, edges)
        return cls(n, pairs,
                   digest=content_digest(n, pairs), version=version)

    @classmethod
    def from_sidecar(cls, smap, *, version: int | None = None,
                     verify_digest: bool = True) -> "GraphSnapshot":
        """A ``mapped``-tier snapshot over a loaded arrays sidecar
        (:func:`bibfs_tpu.store.sidecar.load_sidecar`): pairs, CSR and
        the native int32 columns are read-only memmap views — zero
        private copies, shared page cache across processes.

        ``verify_digest=True`` recomputes :func:`content_digest` over
        the mapped pairs and requires it to equal the sidecar's — the
        bit-identical-to-in-memory-build property, proven on the very
        bytes about to serve (a chunked stream, not a copy). Raises
        ``ValueError`` on mismatch; callers fall back to a rebuild."""
        n = smap.n
        pairs = smap.arrays["pairs"]
        if verify_digest:
            got = content_digest(n, pairs)
            if got != smap.digest:
                raise ValueError(
                    f"{smap.path}: mapped pairs digest {got} != sidecar "
                    f"manifest {smap.digest} — refusing to serve a "
                    "mapping that is not the checkpointed graph"
                )
        snap = cls(
            n, pairs, digest=smap.digest,
            version=smap.version if version is None else version,
        )
        snap._sidecar = smap
        indptr = smap.arrays.get("csr.indptr")
        if indptr is not None:
            # col_ind is a VIEW of the mapped pairs (canonical order is
            # CSR expansion order) — strided, still zero-copy; the one
            # consumer needing contiguity (the native solver) gets the
            # dedicated csr32 table below
            snap._csr = (indptr, pairs[:, 1])
            c32 = smap.arrays.get("csr32.indices")
            if c32 is not None:
                snap._native32 = (indptr, c32)
        if smap.has("ell.nbr", "ell.deg", "ell.overflow"):
            from bibfs_tpu.graph.csr import EllGraph

            m = smap.meta("ell")
            snap._ell = EllGraph(
                n=int(m["n"]), n_pad=int(m["n_pad"]),
                width=int(m["width"]), num_edges=int(m["num_edges"]),
                nbr=smap.arrays["ell.nbr"], deg=smap.arrays["ell.deg"],
                overflow=smap.arrays["ell.overflow"],
            )
        if smap.has("blocked.tab", "blocked.bcol", "blocked.deg"):
            from bibfs_tpu.graph.blocked import BlockedGraph

            m = smap.meta("blocked")
            snap._blocked = BlockedGraph(
                n=int(m["n"]), n_pad=int(m["n_pad"]),
                tile=int(m["tile"]), nblocks=int(m["nblocks"]),
                bwidth=int(m["bwidth"]), num_edges=int(m["num_edges"]),
                nnz_blocks=int(m["nnz_blocks"]),
                tab=smap.arrays["blocked.tab"],
                bcol=smap.arrays["blocked.bcol"],
                deg=smap.arrays["blocked.deg"],
            )
        return snap

    # ---- memoized builds --------------------------------------------
    # Each getter reads the memo into a LOCAL before testing it: the
    # fast path races release() nulling the field, and a bare
    # `if self._x is None: ... return self._x` could pass the test yet
    # return the concurrently-nulled None. A post-retire call (an
    # overlay still answering on a swapped-out base) builds and returns
    # WITHOUT re-caching — retirement freed the memory for good.
    def csr(self):
        """The ``(row_ptr, col_ind)`` CSR adjacency, built once."""
        t = self._csr
        if t is None:
            from bibfs_tpu.graph.csr import build_csr

            with self._lock:
                t = self._csr
                if t is None:
                    t = build_csr(self.n, pairs=self.pairs)
                    if not self._retired:
                        self._csr = t
        return t

    def ell(self):
        """The serving-bucketed ELL table
        (:func:`bibfs_tpu.serve.buckets.bucketed_ell`), built once —
        every engine runtime over this snapshot shares it."""
        t = self._ell
        if t is None:
            from bibfs_tpu.serve.buckets import bucketed_ell

            with self._lock:
                t = self._ell
                if t is None:
                    t = bucketed_ell(self.n, pairs=self.pairs)
                    if not self._retired:
                        self._ell = t
        return t

    def tiered(self):
        """The tiered-ELL layout (power-law graphs), built once."""
        t = self._tiered
        if t is None:
            from bibfs_tpu.graph.csr import build_tiered

            with self._lock:
                t = self._tiered
                if t is None:
                    t = build_tiered(self.n, pairs=self.pairs)
                    if not self._retired:
                        self._tiered = t
        return t

    def blocked(self):
        """The MXU-tile blocked adjacency
        (:func:`bibfs_tpu.graph.blocked.build_blocked`), built once —
        the ``route="blocked"`` runtimes of every engine over this
        snapshot share it, and a hot-swap rebuilds it through the same
        machinery as CSR/ELL."""
        t = self._blocked
        if t is None:
            from bibfs_tpu.graph.blocked import build_blocked

            with self._lock:
                t = self._blocked
                if t is None:
                    t = build_blocked(self.n, pairs=self.pairs)
                    if not self._retired:
                        self._blocked = t
        return t

    def undirected_edges(self) -> np.ndarray:
        """The ``u < v`` half of the canonical pairs — what the native
        host builder (which mirrors internally) and the delta-overlay
        merge both consume."""
        p = self.pairs
        return p[p[:, 0] < p[:, 1]]

    # ---- memory tiers (module docstring) -----------------------------
    def native_csr(self):
        """``(row_ptr int64, col_ind int32)`` in exactly the native C
        solver's format when this snapshot is sidecar-mapped (one
        shared page-cache copy per machine), else None — the engine's
        host route then builds its private :class:`NativeGraph`."""
        return self._native32

    @property
    def tier(self) -> str:
        """``mapped`` / ``hot`` / ``cold`` (module docstring)."""
        if self._sidecar is not None:
            return "mapped"
        if self._pairs is None and self._cold is not None:
            return "cold"
        return "hot"

    def demote(self) -> int:
        """Move a ``hot`` snapshot to the ``cold`` tier: encode the CSR
        into a :class:`~bibfs_tpu.graph.compress.CompressedCSR` and
        drop the resident arrays (pairs included — they decode back
        exactly). Returns resident bytes freed (0 when already cold,
        mapped, or retired — mapped arrays are the page cache's to
        reclaim, not ours). The encode runs OFF the snapshot lock; only
        the pointer drops run under it."""
        with self._lock:
            if (self._retired or self._sidecar is not None
                    or self._pairs is None):
                return 0
            before = self.resident_bytes()
            cold = self._cold
        if cold is None:
            from bibfs_tpu.graph.compress import encode_csr

            cold = encode_csr(*self.csr())
        with self._lock:
            if self._retired or self._pairs is None:
                return 0
            self._cold = cold
            self._pairs = None
            self._csr = self._ell = self._tiered = self._blocked = None
            self._native32 = None
            self._demotions += 1
            return max(before - self.resident_bytes(), 0)

    def promote(self) -> bool:
        """Decode a ``cold`` snapshot back to ``hot`` now (any
        pairs/CSR access does this implicitly). True iff a decode
        happened."""
        with self._lock:
            if self._pairs is not None or self._cold is None:
                return False
            decoded = self.pairs  # the property's locked decode-and-cache
            return decoded is not None

    @staticmethod
    def _owned_bytes(obj) -> int:
        """Private resident bytes of one memo — memmap views cost page
        cache, not process-private memory, and are counted by
        ``mapped_bytes`` instead."""
        if obj is None:
            return 0
        if isinstance(obj, np.ndarray):
            return 0 if isinstance(obj, np.memmap) else int(obj.nbytes)
        if isinstance(obj, tuple):
            return sum(GraphSnapshot._owned_bytes(o) for o in obj)
        total = 0
        for f in ("nbr", "deg", "overflow", "tab", "bcol",
                  "row_ptr", "data"):
            a = getattr(obj, f, None)
            if isinstance(a, np.ndarray) and not isinstance(a, np.memmap):
                total += int(a.nbytes)
        return total

    def resident_bytes(self) -> int:
        """Process-private bytes this snapshot pins (pairs + memoized
        tables + the cold encoding; mapped views excluded)."""
        return sum(self._owned_bytes(o) for o in (
            self._pairs, self._csr, self._ell, self._tiered,
            self._blocked, self._native32, self._cold,
        ))

    def mapped_bytes(self) -> int:
        """Bytes of sidecar arrays this snapshot keeps mapped (shared,
        page-cache-backed — reclaimable by the OS under pressure)."""
        return 0 if self._sidecar is None else self._sidecar.mapped_bytes

    def memory(self) -> dict:
        return {
            "tier": self.tier,
            "resident_bytes": self.resident_bytes(),
            "mapped_bytes": self.mapped_bytes(),
            "cold_bytes": self._owned_bytes(self._cold),
            "promotions": self._promotions,
            "demotions": self._demotions,
        }

    # ---- refcount retirement ----------------------------------------
    def retain(self) -> "GraphSnapshot":
        with self._lock:
            if self._retired:
                raise RuntimeError(
                    f"snapshot {self.digest} v{self.version} already retired"
                )
            self._refs += 1
        return self

    def release(self) -> bool:
        """Drop one reference; on the last one, retire: fire the hooks
        and free the memoized tables. Returns True iff this call
        retired the snapshot."""
        with self._lock:
            self._refs -= 1
            if self._refs > 0 or self._retired:
                return False
            self._retired = True
            hooks, self._retire_hooks = self._retire_hooks, []
            # the canonical pairs stay (tiny relative to the tables, and
            # stats()/digest re-derivation may still read them — on a
            # cold snapshot the CompressedCSR stays for the same
            # reason); the built adjacency tables are the memory
            # owners. Mapped views are only UNREFERENCED, never
            # explicitly unmapped: an in-flight flush that pinned a
            # table keeps a valid buffer until the GC collects the last
            # holder — no reader ever observes munmap.
            self._csr = self._ell = self._tiered = self._blocked = None
            self._native32 = None
            self._sidecar = None
        for hook in hooks:
            try:
                hook(self)
            except Exception:
                pass  # a broken hook must not break the releasing flush
        return True

    def on_retire(self, hook) -> None:
        """Run ``hook(snapshot)`` when the refcount hits zero (fires
        immediately if it already has)."""
        with self._lock:
            if not self._retired:
                self._retire_hooks.append(hook)
                return
        hook(self)

    @property
    def refs(self) -> int:
        with self._lock:
            return self._refs

    @property
    def retired(self) -> bool:
        with self._lock:
            return self._retired

    def stats(self) -> dict:
        return {
            "n": self.n,
            "edges": self.num_edges,
            "digest": self.digest,
            "version": self.version,
            "refs": self.refs,
            "tier": self.tier,
        }

    def __repr__(self) -> str:
        return (f"GraphSnapshot(n={self.n}, edges={self.num_edges}, "
                f"digest={self.digest[:12]}, version={self.version})")
