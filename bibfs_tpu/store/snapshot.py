"""Immutable, versioned graph snapshots — the store's unit of truth.

Until now every serving layer identified "the graph" by the Python
object that happened to hold it: the engine's distance-cache namespace
defaulted to ``id(self)`` (which CPython reuses after GC), and each
engine rebuilt its own CSR/ELL tables from a raw edge list it could not
prove anyone else shared. A :class:`GraphSnapshot` replaces that with
content-addressed identity:

- **digest** — a BLAKE2b hash over ``(n, canonical pairs)``. Two
  snapshots with the same digest ARE the same graph, whatever path the
  bytes took to arrive; a cache entry keyed by digest can never alias a
  different graph, across engines, versions, or process lifetimes of
  the id() counter.
- **version** — a monotonic stamp. Digests answer "is this the same
  content"; versions answer "which came first" — the store's hot-swap
  invariant (a swap only ever moves a name FORWARD) is checked against
  it. Builds stamp a process-wide counter; a :class:`GraphStore`
  re-stamps store-relative history on registration/compaction (v1, v2,
  ...) so each graph's version reads as its own lineage.
- **memoized builds** — ``pairs``/``csr()``/``ell()``/``tiered()`` each
  build once under a lock and are shared by every consumer of the
  snapshot (engine runtimes, overlay solves, oracle checks), so a
  hot-swap costs one canonicalization pass total, not one per layer.
- **refcount retirement** — the store holds one reference; every
  in-flight engine flush pins one more (``retain``/``release``). A
  swapped-out snapshot is retired (retire hooks fire, memoized tables
  become collectable) only when the last in-flight flush lands — the
  swap barrier that lets old batches finish on the graph they started
  on.

The serving-layout build (``ell()``) imports ``serve.buckets`` lazily:
the store layer sits beside ``serve``, not above it, and must be
importable without dragging the engine stack in.
"""

from __future__ import annotations

import hashlib
import itertools
import threading

import numpy as np

# process-wide monotonic version stamps; also the fallback identity
# counter for snapshots built without hashable content (never reused,
# unlike id())
_VERSIONS = itertools.count(1)
_ANON = itertools.count()


def next_version() -> int:
    """The next process-wide monotonic snapshot version."""
    return next(_VERSIONS)


def content_digest(n: int, pairs: np.ndarray) -> str:
    """BLAKE2b over ``(n, canonical pairs)`` — the content identity.

    ``pairs`` must already be canonical (mirrored, deduped, sorted —
    :func:`bibfs_tpu.graph.csr.canonical_pairs`), which makes the hash
    insensitive to edge order, duplication, and orientation in whatever
    list the graph arrived as."""
    h = hashlib.blake2b(digest_size=16)
    h.update(str(int(n)).encode())
    h.update(b"|")
    h.update(np.ascontiguousarray(pairs, dtype=np.int64).tobytes())
    return h.hexdigest()


class GraphSnapshot:
    """One immutable version of one graph (module docstring).

    Build with :meth:`build` (computes the canonical pairs and digest).
    ``digest=None`` at direct construction falls back to a process-wide
    monotonic ``anon-N`` label — still never reused, unlike ``id()``.
    """

    def __init__(self, n: int, pairs: np.ndarray, *, digest: str | None = None,
                 version: int | None = None):
        self.n = int(n)
        self.pairs = pairs
        self.digest = (
            f"anon-{next(_ANON)}" if digest is None else str(digest)
        )
        self.version = next_version() if version is None else int(version)
        self.num_edges = int(pairs.shape[0]) // 2
        self._lock = threading.Lock()
        self._refs = 1  # the creator's (usually the store's) reference
        self._retired = False
        self._retire_hooks: list = []
        self._csr = None
        self._ell = None  # serving-bucketed ELL
        self._tiered = None
        self._blocked = None  # MXU tile layout (graph/blocked.py)

    @classmethod
    def build(cls, n: int, edges: np.ndarray | None = None, *,
              pairs: np.ndarray | None = None,
              version: int | None = None) -> "GraphSnapshot":
        """Canonicalize ``edges`` (or adopt precomputed ``pairs``) and
        stamp the content digest + a fresh monotonic version."""
        from bibfs_tpu.graph.csr import canonical_pairs

        if pairs is None:
            pairs = canonical_pairs(n, edges)
        return cls(n, pairs,
                   digest=content_digest(n, pairs), version=version)

    # ---- memoized builds --------------------------------------------
    # Each getter reads the memo into a LOCAL before testing it: the
    # fast path races release() nulling the field, and a bare
    # `if self._x is None: ... return self._x` could pass the test yet
    # return the concurrently-nulled None. A post-retire call (an
    # overlay still answering on a swapped-out base) builds and returns
    # WITHOUT re-caching — retirement freed the memory for good.
    def csr(self):
        """The ``(row_ptr, col_ind)`` CSR adjacency, built once."""
        t = self._csr
        if t is None:
            from bibfs_tpu.graph.csr import build_csr

            with self._lock:
                t = self._csr
                if t is None:
                    t = build_csr(self.n, pairs=self.pairs)
                    if not self._retired:
                        self._csr = t
        return t

    def ell(self):
        """The serving-bucketed ELL table
        (:func:`bibfs_tpu.serve.buckets.bucketed_ell`), built once —
        every engine runtime over this snapshot shares it."""
        t = self._ell
        if t is None:
            from bibfs_tpu.serve.buckets import bucketed_ell

            with self._lock:
                t = self._ell
                if t is None:
                    t = bucketed_ell(self.n, pairs=self.pairs)
                    if not self._retired:
                        self._ell = t
        return t

    def tiered(self):
        """The tiered-ELL layout (power-law graphs), built once."""
        t = self._tiered
        if t is None:
            from bibfs_tpu.graph.csr import build_tiered

            with self._lock:
                t = self._tiered
                if t is None:
                    t = build_tiered(self.n, pairs=self.pairs)
                    if not self._retired:
                        self._tiered = t
        return t

    def blocked(self):
        """The MXU-tile blocked adjacency
        (:func:`bibfs_tpu.graph.blocked.build_blocked`), built once —
        the ``route="blocked"`` runtimes of every engine over this
        snapshot share it, and a hot-swap rebuilds it through the same
        machinery as CSR/ELL."""
        t = self._blocked
        if t is None:
            from bibfs_tpu.graph.blocked import build_blocked

            with self._lock:
                t = self._blocked
                if t is None:
                    t = build_blocked(self.n, pairs=self.pairs)
                    if not self._retired:
                        self._blocked = t
        return t

    def undirected_edges(self) -> np.ndarray:
        """The ``u < v`` half of the canonical pairs — what the native
        host builder (which mirrors internally) and the delta-overlay
        merge both consume."""
        p = self.pairs
        return p[p[:, 0] < p[:, 1]]

    # ---- refcount retirement ----------------------------------------
    def retain(self) -> "GraphSnapshot":
        with self._lock:
            if self._retired:
                raise RuntimeError(
                    f"snapshot {self.digest} v{self.version} already retired"
                )
            self._refs += 1
        return self

    def release(self) -> bool:
        """Drop one reference; on the last one, retire: fire the hooks
        and free the memoized tables. Returns True iff this call
        retired the snapshot."""
        with self._lock:
            self._refs -= 1
            if self._refs > 0 or self._retired:
                return False
            self._retired = True
            hooks, self._retire_hooks = self._retire_hooks, []
            # the canonical pairs stay (tiny relative to the tables, and
            # stats()/digest re-derivation may still read them); the
            # built adjacency tables are the memory owners
            self._csr = self._ell = self._tiered = self._blocked = None
        for hook in hooks:
            try:
                hook(self)
            except Exception:
                pass  # a broken hook must not break the releasing flush
        return True

    def on_retire(self, hook) -> None:
        """Run ``hook(snapshot)`` when the refcount hits zero (fires
        immediately if it already has)."""
        with self._lock:
            if not self._retired:
                self._retire_hooks.append(hook)
                return
        hook(self)

    @property
    def refs(self) -> int:
        with self._lock:
            return self._refs

    @property
    def retired(self) -> bool:
        with self._lock:
            return self._retired

    def stats(self) -> dict:
        return {
            "n": self.n,
            "edges": self.num_edges,
            "digest": self.digest,
            "version": self.version,
            "refs": self.refs,
        }

    def __repr__(self) -> str:
        return (f"GraphSnapshot(n={self.n}, edges={self.num_edges}, "
                f"digest={self.digest[:12]}, version={self.version})")
