"""Health-aware front-end router over N engine replicas.

The horizontal serving story (ROADMAP item 4): one router owns a fleet
of replicas (:mod:`bibfs_tpu.fleet.replica`) and gives callers the
engine-shaped surface — ``submit``/``query``/``query_many`` — while
underneath:

- **consistent-hash routing with spill.** Each query routes by a
  consistent hash of its graph name (``vnodes`` virtual nodes per
  replica on a 64-bit ring), so a graph's traffic sticks to one
  replica — which is what makes per-replica distance caches, oracle
  indexes and compiled-program warmth ACCUMULATE instead of being
  diluted fleet-wide: aggregate cache capacity scales with the replica
  count, the measured reason a fleet beats one replica on repeat-heavy
  multi-graph traffic (``bench_fleet.json``). A hot graph spills: when
  the hash owner's queue depth reaches ``spill_after``, the query goes
  to the least-loaded healthy replica instead
  (``bibfs_fleet_spills_total``).
- **health-driven routing table.** A poller thread polls every
  replica's ``health_snapshot()`` / ``health`` command each
  ``poll_interval_s``: ready replicas route, degraded replicas are
  demoted (used only when nothing is ready), draining/dead/live ones
  are ejected, and recovery re-admits automatically
  (``bibfs_fleet_replicas{state}``). A submit that hits a dead replica
  marks it dead immediately — ejection does not wait for the poll.
- **failure re-routing.** A replica failure — submit refused, ticket
  failed with a server-side :class:`QueryError` (``internal`` /
  ``capacity``), process death — re-routes the query to a peer with
  the PR 4 retry/backoff taxonomy (:class:`RetryPolicy` bounds
  attempts; ``bibfs_fleet_reroutes_total`` counts failovers), so one
  dead replica costs retries, not lost tickets. Client-invalid errors
  never re-route.
- **rolling swaps.** :meth:`Router.rolling_swap` rolls an edge-update
  batch across the fleet one replica at a time: demote -> engine-level
  drain (submits answer structured capacity refusals while queued
  tickets resolve) -> flush -> ``store.roll`` (apply + compact + atomic
  hot-swap on THAT replica's store) -> ready-probe -> re-admit. The
  fleet serves mixed versions mid-roll; every answer is exact for the
  version its replica declares, which each
  :class:`FleetTicket.declared_version` records.
- **catch-up re-admission.** A completed roll records the fleet's
  COMMITTED version per graph (plus a bounded roll history). A replica
  coming back from ``dead`` whose declared version lags a committed one
  is held in the ``catchup`` table state — not routable — until it
  catches up: the router replays the missed roll batches from its
  history (contiguously, version by version) onto the recovering
  replica's store, then re-reads the declared version and only then
  admits. A durable replica (``store/wal``) usually recovers to the
  committed version from its own WAL and passes straight through; a
  replica that lost state (or missed a roll while dead) is repaired
  rather than silently re-admitted at a stale version — the pre-PR 8
  failure mode, where a respawned subprocess served v1 answers for a
  fleet that had rolled to v2. A replica too far behind the retained
  history stays in ``catchup``, visibly, instead of serving stale data
  (``bibfs_fleet_catchups_total`` counts completed catch-ups).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.fleet.replica import ReplicaDead
from bibfs_tpu.obs.dtrace import FLIGHT, dspan, sample_ctx
from bibfs_tpu.obs.metrics import REGISTRY, next_instance_label
from bibfs_tpu.obs.trace import span
from bibfs_tpu.serve.resilience import (
    QueryError,
    RetryPolicy,
    to_query_error,
)

#: routing-table states a query may be sent to (in preference order)
ROUTABLE_STATES = ("ready", "degraded")
#: every state the table (and the bibfs_fleet_replicas gauge) can hold
#: — ``catchup`` holds a recovering replica whose declared graph
#: version lags the fleet's committed one (module docstring)
TABLE_STATES = ("live", "ready", "degraded", "draining", "dead",
                "catchup")

#: rolls retained per graph for catch-up replay; a replica further
#: behind than this stays in ``catchup`` (visibly) instead of being
#: re-admitted stale
ROLL_HISTORY_MAX = 8

#: error kinds that re-route to a peer; everything else is the
#: client's problem (invalid) or the caller's deadline (timeout)
REROUTE_KINDS = ("internal", "capacity")

# the fleet metric families a router mints (README "Observability") —
# re-exported from the ONE canonical list (obs/names.py) the soak's
# live-render gate, the bench CI gate and the metric-mint lint all
# share, so they cannot drift apart; bibfs_build_info rides along
# because "which build is this replica" is the fleet question
from bibfs_tpu.obs.names import FLEET_METRIC_FAMILIES  # noqa: E402,F401


def _hash64(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )


class FleetTicket:
    """A routed query's handle: wraps the serving replica's ticket and
    re-routes on replica failure when waited/polled (failover is driven
    by the waiter — the router never parks threads per ticket).
    ``replica`` / ``declared_version`` name the replica that finally
    answered and the graph version it declared at dispatch, which is
    what makes mid-rolling-swap answers verifiable."""

    __slots__ = ("src", "dst", "graph", "replica", "declared_version",
                 "attempts", "tried", "result", "error", "_router",
                 "_inner", "ctx")

    def __init__(self, router, src: int, dst: int, graph: str | None,
                 ctx=None):
        self.src = src
        self.dst = dst
        self.graph = graph
        self.replica: str | None = None
        self.declared_version = None
        self.attempts = 0
        self.tried: set = set()
        self.result = None
        self.error: BaseException | None = None
        self._router = router
        self._inner = None
        self.ctx = ctx  # sampled trace context (None = unsampled)

    def done(self) -> bool:
        return self.result is not None or self.error is not None

    def poll(self) -> bool:
        """Non-blocking progress check: True once the ticket is FINAL
        (result or terminal error). A failed inner ticket triggers the
        re-route right here (non-blocking dispatch, no backoff sleep) —
        how a streaming caller (the ``bibfs-fleet`` REPL) drives
        failover without parking a thread."""
        while True:
            if self.done():
                return True
            inner = self._inner
            if inner is None:
                return False
            if inner.error is not None:
                if not self._router._reroute(self, inner.error,
                                             blocking=False):
                    return True
                continue
            if inner.result is not None:
                self.result = inner.result
                return True
            return False

    def wait(self, timeout: float | None = 60.0):
        """Block for the result, re-routing on replica failure (with
        the retry policy's backoff) until the attempts bound; raises
        the final structured error or ``TimeoutError``."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            if self.result is not None:
                return self.result
            if self.error is not None:
                raise self.error
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"query ({self.src}, {self.dst}) unresolved "
                        f"after {timeout}s (replica {self.replica})"
                    )
            replica = self._router._replicas.get(self.replica)
            if replica is None:  # retired mid-flight (scale-in)
                if not self._router._reroute(
                        self,
                        ReplicaDead(f"replica {self.replica} retired"),
                        blocking=True):
                    raise self.error
                continue
            try:
                self.result = replica.wait_ticket(
                    self._inner, timeout=remaining
                )
                return self.result
            except TimeoutError:
                raise
            except (QueryError, ReplicaDead, RuntimeError) as e:
                if not self._router._reroute(self, e, blocking=True):
                    raise self.error


# the routing table and every catch-up/version structure the poller,
# the dispatch path and rolling_swap share; reads stay lock-free by
# design (_pick's GIL-atomic table read is the hot path)
@guarded_by("_table_lock", "_states", "_versions", "_committed",
            "_roll_history", "_needs_catchup", "_forced_drain",
            "_last_gen", "_catchup_since")
class Router:
    """Front-end router over N replicas (module docstring).

    Parameters
    ----------
    replicas : the fleet — :class:`EngineReplica` /
        :class:`ProcessReplica` (or anything replica-shaped). Names
        must be unique.
    retry : failover policy (default: 3 attempts, exp backoff +
        jitter) — ``attempts`` bounds how many replicas one query may
        try in total.
    poll_interval_s : health-poll cadence (the re-admit latency floor).
    spill_after : hash-owner queue depth at which a query spills to the
        least-loaded healthy replica (0/None disables spilling). Set it
        ABOVE the replicas' routine flush depth (a multiple of their
        ``max_batch``): a queue that merely filled to its next batch is
        the micro-batcher working, not pressure — spilling on it
        scatters hot-graph traffic and destroys exactly the cache
        affinity hash routing exists to build (measured: a threshold at
        half the flush depth spilled ~40% of a steady hot-traffic pass
        and halved the fleet's hit rate).
    vnodes : virtual nodes per replica on the hash ring.
    obs_label : the ``router=`` label on the fleet metric families
        (default: a process-unique ``router-N``).
    """

    def __init__(self, replicas, *, retry: RetryPolicy | None = None,
                 poll_interval_s: float = 0.25, spill_after: int = 256,
                 vnodes: int = 64, obs_label: str | None = None):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("a router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self._replicas = {r.name: r for r in replicas}
        self._order = sorted(self._replicas)
        self._retry = RetryPolicy(attempts=3) if retry is None else retry
        self.poll_interval_s = float(poll_interval_s)
        self.spill_after = int(spill_after or 0)
        self._vnodes = int(vnodes)
        ring = []
        for name in self._order:
            for i in range(self._vnodes):
                ring.append((_hash64(f"{name}#{i}"), name))
        ring.sort()
        self._ring = ring
        self._ring_keys = [h for h, _ in ring]
        self._table_lock = threading.Lock()
        self._states = {name: "live" for name in self._order}
        self._forced_drain: dict[str, bool] = {}
        self._versions: dict = {}
        # catch-up state (module docstring): fleet-committed version +
        # bounded roll history per graph, and the replicas whose next
        # ready transition must be version-checked (set on death)
        self._committed: dict[str, int] = {}
        self._roll_history: dict[str, list] = {}
        self._needs_catchup: set = set()
        # last seen incarnation per replica: a generation change means
        # the replica died and came back BETWEEN polls (a respawn
        # faster than one tick) — the catch-up check must still run
        self._last_gen: dict[str, int] = {
            name: getattr(r, "generation", 0)
            for name, r in self._replicas.items()
        }
        # when each replica ENTERED the catchup table state (monotonic)
        # — the stuck-duration source for the bibfs_fleet_catchup_stuck
        # gauge, stats()["pending_catchup"] and health_snapshot()
        self._catchup_since: dict[str, float] = {}
        self.obs_label = (
            next_instance_label("router") if obs_label is None
            else obs_label
        )
        self._g_replicas = REGISTRY.gauge(
            "bibfs_fleet_replicas",
            "Fleet replicas by routing-table state",
            ("router", "state"),
        )
        for s in TABLE_STATES:  # render at zero from the first scrape
            self._g_replicas.labels(router=self.obs_label, state=s).set(0)
        # the family handle outlives the ctor: add_replica mints a cell
        # for every replica that joins after construction
        self._c_routed_family = REGISTRY.counter(
            "bibfs_fleet_routed_total",
            "Queries dispatched per replica",
            ("router", "replica"),
        )
        self._routed_cells = {
            name: self._c_routed_family.labels(
                router=self.obs_label, replica=name
            )
            for name in self._order
        }
        self._g_catchup_stuck = REGISTRY.gauge(
            "bibfs_fleet_catchup_stuck",
            "Seconds a replica has been held in the catchup table "
            "state (0 = not stuck)",
            ("router", "replica"),
        )
        for name in self._order:  # render at zero from the first scrape
            self._g_catchup_stuck.labels(
                router=self.obs_label, replica=name
            ).set(0)
        self._c_reroutes = REGISTRY.counter(
            "bibfs_fleet_reroutes_total",
            "Queries re-routed off a failed/refusing replica",
            ("router",),
        ).labels(router=self.obs_label)
        self._c_spills = REGISTRY.counter(
            "bibfs_fleet_spills_total",
            "Hot-graph queries spilled to the least-loaded replica",
            ("router",),
        ).labels(router=self.obs_label)
        self._c_rolls = REGISTRY.counter(
            "bibfs_fleet_rolls_total",
            "Fleet-wide rolling swaps completed",
            ("router",),
        ).labels(router=self.obs_label)
        self._c_catchups = REGISTRY.counter(
            "bibfs_fleet_catchups_total",
            "Recovering replicas caught up to the fleet's committed "
            "version before re-admission (roll-history replays "
            "included)",
            ("router",),
        ).labels(router=self.obs_label)
        self._closed = False
        self._poll_stop = threading.Event()
        # set by nudge_poll() (replica kill/restart hooks, supervisor
        # scale events) to cut the re-admit latency floor from
        # poll_interval_s to one immediate tick
        self._poll_nudge = threading.Event()
        for r in self._replicas.values():
            self._subscribe_lifecycle(r)
        self._poll_once()  # routing works before the first poller tick
        self._poller = threading.Thread(
            target=self._poll_main, name="bibfs-fleet-poller",
            daemon=True,
        )
        self._poller.start()

    def _subscribe_lifecycle(self, replica) -> None:
        """Wire a replica's kill/restart notifications to an immediate
        poll tick (the re-admit latency cut): duck-typed, so anything
        replica-shaped without the hook still routes."""
        hook = getattr(replica, "on_lifecycle", None)
        if hook is not None:
            hook(lambda _name, _event: self.nudge_poll())

    # ---- submission --------------------------------------------------
    def replica(self, name: str):
        return self._replicas[name]

    @property
    def replica_names(self) -> list:
        return list(self._order)

    # ---- elastic membership -----------------------------------------
    def _ring_of_locked(self, order) -> list:
        ring = []
        for name in order:
            for i in range(self._vnodes):
                ring.append((_hash64(f"{name}#{i}"), name))
        ring.sort()
        return ring

    def add_replica(self, replica) -> None:
        """Admit one replica into the fleet at runtime (supervisor
        scale-out). It enters the table as ``live`` (not routable) and
        is admitted by the nudged poll tick once its health reads
        ready; a fleet with committed rolls on record version-checks
        it through the catch-up gate like any recovering replica.
        Hot-path readers stay lock-free: the replica dict, order, ring
        and key list are REPLACED wholesale (GIL-atomic reference
        assignments), never mutated in place."""
        name = replica.name
        # mint the per-replica cells BEFORE the replica becomes
        # pickable: a dispatch racing the admitting poll tick must
        # find its routed cell in place
        self._routed_cells[name] = self._c_routed_family.labels(
            router=self.obs_label, replica=name
        )
        self._g_catchup_stuck.labels(
            router=self.obs_label, replica=name
        ).set(0)
        with self._table_lock:
            if name in self._replicas:
                raise ValueError(f"replica name already routed: {name!r}")
            replicas = dict(self._replicas)
            replicas[name] = replica
            order = sorted(replicas)
            ring = self._ring_of_locked(order)
            self._states[name] = "live"
            self._last_gen[name] = getattr(replica, "generation", 0)
            if self._committed:
                # never admit a late joiner at a stale version: it
                # passes the same version gate a recovering replica does
                self._needs_catchup.add(name)
            self._replicas = replicas
            self._order = order
            self._ring = ring
            self._ring_keys = [h for h, _ in ring]
        self._subscribe_lifecycle(replica)
        self.nudge_poll()

    def remove_replica(self, name: str, *, close: bool = True) -> None:
        """Retire one replica at runtime (supervisor scale-in or
        stuck-catchup replacement). The caller is expected to have
        drained it (``begin_drain`` + ``flush``) so no acked ticket is
        lost; anything still in flight fails over through the normal
        reroute path. A router keeps at least one replica."""
        with self._table_lock:
            if name not in self._replicas:
                return
            if len(self._replicas) == 1:
                raise ValueError("a router needs at least one replica")
            replicas = dict(self._replicas)
            replica = replicas.pop(name)
            order = sorted(replicas)
            ring = self._ring_of_locked(order)
            self._replicas = replicas
            self._order = order
            self._ring = ring
            self._ring_keys = [h for h, _ in ring]
            self._states.pop(name, None)
            self._forced_drain.pop(name, None)
            self._needs_catchup.discard(name)
            self._catchup_since.pop(name, None)
            self._last_gen.pop(name, None)
            self._drop_versions_locked(name)
        self._routed_cells.pop(name, None)
        self._g_catchup_stuck.labels(
            router=self.obs_label, replica=name
        ).set(0)
        if close:
            try:
                replica.close()
            except Exception:
                pass
        self.nudge_poll()

    def submit(self, src: int, dst: int, graph: str | None = None,
               ctx=None) -> FleetTicket:
        """Route one query (hash + health + spill) and return its
        :class:`FleetTicket`. Submit-time replica refusals fail over
        immediately; client-invalid input raises ``ValueError`` to the
        caller unrerouted. The router is a trace ingress: with no
        upstream ``ctx``, the sampler may mint one here, and the
        context then rides the replica's wire protocol (stdin token /
        net frame fields) into the serving process."""
        if ctx is None:
            ctx = sample_ctx()
        ticket = FleetTicket(self, int(src), int(dst), graph, ctx)
        self._dispatch(ticket)
        return ticket

    def query(self, src: int, dst: int, graph: str | None = None):
        return self.submit(src, dst, graph).wait()

    def query_many(self, pairs, *, graph: str | None = None,
                   return_errors: bool = False) -> list:
        """Fleet-wide ``query_many``: same contract as the engines'
        (``return_errors=True`` yields per-pair
        ``BFSResult | QueryError``)."""
        tickets: list = []
        for s, d in pairs:
            try:
                tickets.append(self.submit(int(s), int(d), graph))
            except (ValueError, TypeError) as e:
                if not return_errors:
                    raise
                tickets.append(to_query_error(e, None, kind="invalid"))
            except QueryError as e:
                if not return_errors:
                    raise
                tickets.append(e)
        self.flush()
        out = []
        for t in tickets:
            if isinstance(t, QueryError):
                out.append(t)
                continue
            try:
                out.append(t.wait(timeout=120.0))
            except Exception as e:
                if not return_errors:
                    raise
                out.append(to_query_error(e, (t.src, t.dst)))
        return out

    def flush(self, timeout: float | None = None) -> None:
        """Resolve everything queued on every live replica."""
        for name in self._order:
            try:
                self._replicas[name].flush(timeout=timeout)
            except Exception:
                pass  # a dead replica's tickets fail; wait() reroutes

    def _dispatch(self, ticket: FleetTicket,
                  exclude: set | None = None,
                  is_reroute: bool = False) -> None:
        tried = set(exclude or ())
        last_err = None
        for _ in range(len(self._replicas) + 1):
            name = self._pick(ticket.graph, tried)
            replica = self._replicas.get(name)
            if replica is None:  # retired between table read and here
                tried.add(name)
                continue
            # version BEFORE submit: a rolling swap that lands while
            # this query sits in the replica's queue still resolves it
            # PRE-swap (the roll's drain flushes the queue before the
            # store rolls), so the pre-submit version is the one the
            # answer is exact for — reading after the submit could
            # attribute a v_k answer to v_k+1
            version = self._version_of(name, ticket.graph)
            try:
                if ticket.ctx is not None:
                    sp = dspan("route", ticket.ctx, replica=name,
                               reroute=is_reroute)
                    with sp:
                        inner = replica.submit(ticket.src, ticket.dst,
                                               ticket.graph, ctx=sp.ctx)
                    FLIGHT.note(
                        "route", trace=ticket.ctx.trace_id,
                        replica=name, reroute=is_reroute,
                        version=version,
                    )
                else:
                    inner = replica.submit(ticket.src, ticket.dst,
                                           ticket.graph)
            except (ValueError, TypeError):
                raise  # client-invalid: the caller's problem, no peer
                # can answer an out-of-range id differently
            except QueryError as e:
                last_err = e
                tried.add(name)
                self._c_reroutes.inc()
                continue  # draining/refusing: straight to a peer
            except Exception as e:  # ReplicaDead, closed-engine races
                last_err = e
                tried.add(name)
                self._mark_dead(name)  # eject ahead of the next poll
                self._c_reroutes.inc()
                continue
            if is_reroute:
                self._c_reroutes.inc()
            ticket._inner = inner
            ticket.replica = name
            ticket.attempts += 1
            ticket.tried.add(name)
            ticket.declared_version = version
            cell = self._routed_cells.get(name)
            if cell is not None:
                cell.inc()
            return
        raise QueryError(
            "no healthy replica accepted the query",
            kind="capacity", query=(ticket.src, ticket.dst),
            cause=last_err,
        )

    def _reroute(self, ticket: FleetTicket, err: BaseException,
                 blocking: bool) -> bool:
        """Failover one failed ticket to a peer. True = re-dispatched
        (caller keeps waiting/polling); False = terminal
        (``ticket.error`` set)."""
        kind = getattr(err, "kind", "internal")
        retryable = (
            isinstance(err, (ReplicaDead, RuntimeError))
            and not isinstance(err, QueryError)
        ) or kind in REROUTE_KINDS
        if not retryable or ticket.attempts >= self._retry.attempts:
            ticket.error = to_query_error(
                err, (ticket.src, ticket.dst)
            )
            return False
        if blocking:
            time.sleep(self._retry.delay_s(max(ticket.attempts - 1, 0)))
        try:
            with span("fleet_reroute", replica=ticket.replica):
                self._dispatch(
                    ticket, exclude=set(ticket.tried), is_reroute=True
                )
        except (QueryError, ValueError, TypeError) as e:
            ticket.error = to_query_error(e, (ticket.src, ticket.dst))
            return False
        return True

    # ---- routing policy ---------------------------------------------
    def owner(self, graph: str | None) -> str:
        """The graph's hash-ring owner over ALL replicas (health
        ignored) — the affinity introspection hook load drivers shard
        by."""
        return self._ring_walk(str(graph or ""), set(self._order))

    def _ring_walk(self, key: str, avail: set) -> str:
        h = _hash64(key)
        i = bisect.bisect_right(self._ring_keys, h)
        for k in range(len(self._ring)):
            name = self._ring[(i + k) % len(self._ring)][1]
            if name in avail:
                return name
        return next(iter(avail))

    def _pick(self, graph: str | None, exclude: set) -> str:
        # hot path: plain dict reads are GIL-atomic and the poller only
        # assigns whole values — a lock here would put one more convoy
        # point on every routed query (the fleet's hit traffic is pure
        # Python, where lock handoffs ARE the cost)
        states = self._states
        for want in ROUTABLE_STATES:
            eligible = {n for n in self._order if states.get(n) == want}
            if eligible:
                break
        else:
            raise QueryError(
                f"no healthy replicas (table: {dict(states)})",
                kind="capacity",
            )
        avail = eligible - exclude or eligible
        target = self._ring_walk(str(graph or ""), avail)
        if self.spill_after and len(avail) > 1:
            tload = self._load_of(target)
            if tload >= self.spill_after:
                alt = min(avail, key=self._load_of)
                if alt != target and self._load_of(alt) < tload:
                    self._c_spills.inc()
                    return alt
        return target

    def _load_of(self, name: str) -> int:
        """A replica's queue depth for spill/scale decisions; a replica
        retired (or dying) mid-read reads as saturated."""
        replica = self._replicas.get(name)
        if replica is None:
            return 1 << 30
        try:
            return replica.load()
        except Exception:
            return 1 << 30

    def _graph_key(self, graph: str | None) -> str:
        return str(graph or "")

    def _version_of(self, name: str, graph: str | None):
        key = (name, self._graph_key(graph))
        v = self._versions.get(key)  # GIL-atomic read; the miss path
        # (first query per (replica, graph)) and rolling_swap write
        # under the table lock
        if v is not None:
            return v
        try:
            v = self._replicas[name].version(graph)
        except Exception:
            v = None
        with self._table_lock:
            self._versions[key] = v
        return v

    # ---- health table ------------------------------------------------
    def _mark_dead(self, name: str) -> None:
        with self._table_lock:
            self._states[name] = "dead"
            self._drop_versions_locked(name)
            self._needs_catchup.add(name)

    def _drop_versions_locked(self, name: str) -> None:
        """Forget a dead replica's cached declared versions: a restart
        may come back on different state (a subprocess respawn reloads
        its store from disk, losing in-memory rolls), and a stale cache
        would mis-attribute its answers. The next dispatch re-reads the
        version from the replica itself."""
        for key in [k for k in self._versions if k[0] == name]:
            del self._versions[key]

    def _set_state(self, name: str, state: str) -> None:
        with self._table_lock:
            self._states[name] = state

    def _poll_once(self) -> None:
        counts = {s: 0 for s in TABLE_STATES}
        # snapshot: membership may change under the supervisor mid-poll
        for name, replica in list(self._replicas.items()):
            try:
                state = replica.health()["state"]
                if state not in counts:
                    state = "degraded"
            except Exception:
                state = "dead"
            gen = getattr(replica, "generation", 0)
            with self._table_lock:
                if self._forced_drain.get(name):
                    state = "draining"  # mid-roll: keep traffic off
                if (state == "dead"
                        and self._states.get(name) != "dead"):
                    self._drop_versions_locked(name)
                    self._needs_catchup.add(name)
                if gen != self._last_gen.get(name):
                    # died and respawned between polls: same treatment
                    # as an observed death
                    self._last_gen[name] = gen
                    self._drop_versions_locked(name)
                    self._needs_catchup.add(name)
                check_catchup = (
                    state in ROUTABLE_STATES
                    and name in self._needs_catchup
                    and bool(self._committed)
                )
                if not check_catchup and state in ROUTABLE_STATES:
                    self._needs_catchup.discard(name)
            if check_catchup:
                # a replica coming back from dead with fleet-committed
                # versions on record: verify (and repair) its declared
                # versions BEFORE it becomes routable — gating EVERY
                # routable state (a recovering replica polled straight
                # into 'degraded' is still dispatchable and must not
                # bypass the version check)
                if not self._try_catchup(name):
                    state = "catchup"
            now = time.monotonic()
            with self._table_lock:
                if name not in self._replicas:
                    continue  # retired while we were polling it
                self._states[name] = state
                if state == "catchup":
                    since = self._catchup_since.setdefault(name, now)
                    stuck = now - since
                else:
                    self._catchup_since.pop(name, None)
                    stuck = 0.0
            self._g_catchup_stuck.labels(
                router=self.obs_label, replica=name
            ).set(round(stuck, 3))
            counts[state] += 1
        for s, c in counts.items():
            self._g_replicas.labels(
                router=self.obs_label, state=s
            ).set(c)

    def _try_catchup(self, name: str) -> bool:
        """Version-check (and repair) one recovering replica against
        every fleet-committed graph version. Returns True once every
        committed graph's declared version has caught up (the caller
        admits the replica under its polled state); False holds it in
        ``catchup`` (not routable). Lagging graphs are repaired by
        replaying the missed roll
        batches from the bounded history, in version order — a gap
        beyond the history leaves the replica in ``catchup`` visibly
        rather than re-admitting stale answers.

        The comparison is numeric, which is sound exactly because
        fleet-managed graphs mutate ONLY through rolls: every replica's
        store moves v -> v+1 per committed roll and nothing else bumps
        versions (fleet updates are staged and land with the roll, so
        no overlay accumulates to trigger an independent background
        compaction). Mutating a fleet replica's store out-of-band
        breaks that comparability — a locally-compacted replica could
        pass the check while missing a roll's content.

        A replica that crashed BETWEEN a roll's update acks and its
        swap respawns with the batch re-armed in its overlay: the
        replay's duplicate adds are refused and the replica stays held
        here. That is the deliberate trade — safe-but-unroutable
        (visible in ``stats()["pending_catchup"]``, repaired by an
        operator restart from clean state) over any automatic fold of
        partially-recovered pending state, which could re-admit a
        replica whose declared version matches the fleet while its
        content does not."""
        replica = self._replicas.get(name)
        if replica is None:  # retired mid-poll
            return False
        with self._table_lock:
            committed = dict(self._committed)
            history = {g: list(h) for g, h in self._roll_history.items()}
        for gkey, want in committed.items():
            graph = gkey or None
            try:
                have = replica.version(graph)
            except Exception:
                return False
            have = 0 if have is None else int(have)
            if have >= want:
                continue
            with span("fleet_catchup", replica=name, graph=gkey,
                      have=have, want=want):
                for ver, adds, dels in history.get(gkey, ()):
                    if ver <= have:
                        continue
                    if ver != have + 1:
                        # history gap: the batches that would bridge it
                        # were pruned — repairing from here would skip
                        # acked updates, so hold the replica instead
                        return False
                    try:
                        have = int(replica.roll(graph, adds=adds,
                                                dels=dels))
                    except Exception:
                        return False
                if have < want:
                    return False
        with self._table_lock:
            self._needs_catchup.discard(name)
        # the version cache was dropped at death; the next dispatch
        # re-reads the (now caught-up) declared version from the replica
        self._c_catchups.inc()
        return True

    def _poll_main(self) -> None:
        # the nudge event doubles as the tick timer: a kill/restart/
        # scale event wakes the poller NOW instead of waiting out
        # poll_interval_s (the documented re-admit latency floor)
        while True:
            self._poll_nudge.wait(self.poll_interval_s)
            self._poll_nudge.clear()
            if self._poll_stop.is_set():
                return
            try:
                self._poll_once()
            except Exception:
                pass  # a poll hiccup must not kill the poller

    def nudge_poll(self) -> None:
        """Wake the health poller immediately (replica lifecycle events,
        supervisor respawns) — an event, not a tighter interval, so the
        steady-state poll cost is unchanged."""
        self._poll_nudge.set()

    # ---- rolling swap ------------------------------------------------
    def rolling_swap(self, graph: str | None = None, adds=(), dels=(),
                     *, drain_timeout_s: float = 60.0,
                     ready_timeout_s: float = 30.0) -> dict:
        """Roll one edge-update batch across the fleet, one replica at
        a time (module docstring): demote -> drain -> flush ->
        ``replica.roll`` (apply + compact + hot-swap on that replica's
        store) -> ready-probe -> re-admit. Returns the per-replica
        rows; ``ok`` requires every replica rolled and re-probed."""
        adds = [tuple(e) for e in adds]
        dels = [tuple(e) for e in dels]
        rows = []
        for name in list(self._order):
            replica = self._replicas.get(name)
            if replica is None:  # retired mid-roll
                continue
            row = {"replica": name, "ok": False}
            with span("fleet_roll", replica=name,
                      graph=self._graph_key(graph)):
                with self._table_lock:
                    self._forced_drain[name] = True
                    self._states[name] = "draining"
                t0 = time.perf_counter()
                try:
                    row["engine_drain"] = bool(replica.begin_drain())
                    replica.flush(timeout=drain_timeout_s)
                    old_v = replica.version(graph)
                    new_v = replica.roll(graph, adds=adds, dels=dels)
                    replica.end_drain()
                    ready = self._probe_ready(
                        replica, graph, timeout=ready_timeout_s
                    )
                    row.update(
                        version=[old_v, new_v], ready=ready,
                        ok=bool(ready and (
                            not (adds or dels)
                            or (old_v is not None and new_v > old_v)
                        )),
                    )
                    with self._table_lock:
                        self._versions[
                            (name, self._graph_key(graph))
                        ] = new_v
                except Exception as e:
                    row["error"] = f"{type(e).__name__}: {e}"[:300]
                    try:
                        replica.end_drain()
                    except Exception:
                        pass
                finally:
                    row["roll_s"] = round(time.perf_counter() - t0, 3)
                    with self._table_lock:
                        self._forced_drain.pop(name, None)
                        if row.get("ok"):
                            self._states[name] = "ready"  # re-admit NOW
            rows.append(row)
        ok = all(r.get("ok") for r in rows)
        if ok:
            # the family is documented as rolling swaps COMPLETED: a
            # roll with failed replicas must not count as one
            self._c_rolls.inc()
        new_versions = [
            r["version"][1] for r in rows
            if r.get("ok") and r.get("version")
            and r["version"][1] is not None
        ]
        if new_versions and (adds or dels):
            # the fleet COMMITTED this version on every replica that
            # rolled; a replica that missed it (dead mid-roll, respawn
            # from stale state) must catch up before re-admission —
            # keep the batch in the bounded history so _try_catchup can
            # replay it
            key = self._graph_key(graph)
            newv = int(max(new_versions))
            with self._table_lock:
                self._committed[key] = max(
                    self._committed.get(key, 0), newv
                )
                hist = self._roll_history.setdefault(key, [])
                hist.append((newv, list(adds), list(dels)))
                del hist[:-ROLL_HISTORY_MAX]
        return {
            "graph": self._graph_key(graph),
            "adds": len(adds),
            "dels": len(dels),
            "replicas": rows,
            "ok": ok,
        }

    def _probe_ready(self, replica, graph, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if replica.probe(graph, timeout=5.0):
                    state = replica.health()["state"]
                    if state == "ready":
                        return True
            except Exception:
                pass
            time.sleep(0.05)
        return False

    # ---- introspection / lifecycle ----------------------------------
    def table(self) -> dict:
        with self._table_lock:
            return dict(self._states)

    def catchup_stuck(self) -> dict:
        """``{replica: seconds}`` for every replica currently held in
        the ``catchup`` table state — the supervisor's escape-hatch
        input and the stuck-gauge's source of truth."""
        now = time.monotonic()
        with self._table_lock:
            return {
                name: round(now - since, 3)
                for name, since in self._catchup_since.items()
            }

    def health_snapshot(self) -> dict:
        """The fleet's ``/healthz`` payload: ready while anything is
        routable and nothing is wedged; degraded (still 200 — the
        routable replicas ARE serving) with per-replica reasons when a
        replica is dead, draining or stuck in catchup; unready when
        nothing routes at all."""
        now = time.monotonic()
        with self._table_lock:
            states = dict(self._states)
            since = dict(self._catchup_since)
        reasons = []
        for name in sorted(states):
            s = states[name]
            if s == "catchup":
                stuck = now - since.get(name, now)
                reasons.append(
                    f"replica {name} catchup ({stuck:.1f}s stuck)"
                )
            elif s in ("dead", "draining"):
                reasons.append(f"replica {name} {s}")
        routable = any(s in ROUTABLE_STATES for s in states.values())
        if not routable:
            state = "unready"
        elif reasons:
            state = "degraded"
        else:
            state = "ready"
        return {"state": state, "reasons": reasons}

    def stats(self) -> dict:
        now = time.monotonic()
        replicas = self._replicas  # snapshot vs concurrent scale events
        order = self._order
        with self._table_lock:
            states = dict(self._states)
            versions = {
                f"{name}:{g}": v
                for (name, g), v in self._versions.items()
            }
            committed = dict(self._committed)
            # dict, not list (membership tests still work): each held
            # replica carries how long it has been stuck — 0.0 until
            # the poller has actually seen it in the catchup state
            pending_catchup = {
                name: {
                    "stuck_s": round(
                        now - self._catchup_since[name], 3
                    ) if name in self._catchup_since else 0.0,
                }
                for name in sorted(self._needs_catchup)
            }
        return {
            "replicas": {
                name: {
                    "state": states.get(name),
                    "kind": getattr(replicas[name], "kind", "?"),
                    "routed": (
                        self._routed_cells[name].value
                        if name in self._routed_cells else 0
                    ),
                    "load": self._load_of(name),
                }
                for name in order if name in replicas
            },
            "versions": versions,
            "committed": committed,
            "pending_catchup": pending_catchup,
            "reroutes": self._c_reroutes.value,
            "spills": self._c_spills.value,
            "rolls": self._c_rolls.value,
            "catchups": self._c_catchups.value,
            "spill_after": self.spill_after,
            "poll_interval_s": self.poll_interval_s,
        }

    def metrics_snapshot(self) -> dict:
        """Per-replica Prometheus text — the fleet-wide scrape's raw
        material. Out-of-process replicas (subprocess REPL, net child)
        answer over their control surface; in-process EngineReplicas
        mint into THIS process's registry already and return None (the
        aggregator must not double-count them). A dead replica's entry
        is None too — a scrape never fails because one replica is
        down."""
        out: dict = {}
        for name in list(self._order):
            fn = getattr(self._replicas.get(name), "metrics_render",
                         None)
            if fn is None:
                out[name] = None
                continue
            try:
                out[name] = fn()
            except Exception:
                out[name] = None
        return out

    def close(self, close_replicas: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        self._poll_stop.set()
        self._poll_nudge.set()  # wake the poller so it sees the stop
        self._poller.join(timeout=10.0)
        if close_replicas:
            for replica in list(self._replicas.values()):
                try:
                    replica.close()
                except Exception:
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
